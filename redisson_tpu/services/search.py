"""Search service: secondary indexes + queries + aggregations.

Parity target: RSearch (``RedissonSearch.java``, 906 LoC — FT.CREATE /
FT.SEARCH / FT.AGGREGATE over hashes selected by key prefix) and the
condition tree of LiveObjectSearch (``liveobject/LiveObjectSearch.java``,
``liveobject/condition/*``: EQ/GT/GE/LT/LE/IN/AND/OR).

TPU-first design: the reference evaluates numeric predicates per-document in
the RediSearch C module; here every NUMERIC field of an index is packed into
one dense (docs × fields) float32 device matrix, so a numeric filter over N
documents is a single vectorized compare-and-reduce on device — the MXU/VPU
replaces the per-doc loop.  TEXT (tokenized words) and TAG (exact values)
fields live in host-side inverted indexes: set intersection there is
hash-table work the device has no advantage on; mixed queries intersect the
host candidate set with the device numeric mask.

Auto-indexing: the reference indexes every hash whose key matches a prefix.
Here `sync()` scans matching maps through the engine store, and maps report
into the index on write via the `document(...)`/`remove_document` hooks the
client facade calls; `sync()` is also cheap enough to call before queries
for read-your-writes freshness (it diffs record versions).
"""
from __future__ import annotations

import re
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# -- schema ------------------------------------------------------------------


class FieldType:
    TEXT = "TEXT"
    TAG = "TAG"
    NUMERIC = "NUMERIC"
    VECTOR = "VECTOR"  # device-resident embedding bank (services/vector.py)


_WORD = re.compile(r"[\w']+")


def tokenize(text: str) -> List[str]:
    return [w.lower() for w in _WORD.findall(str(text))]


# -- condition tree (liveobject/condition/* analog) --------------------------


@dataclass
class Condition:
    def and_(self, other: "Condition") -> "Condition":
        return And([self, other])

    def or_(self, other: "Condition") -> "Condition":
        return Or([self, other])


@dataclass
class Eq(Condition):
    field: str
    value: Any


@dataclass
class In(Condition):
    field: str
    values: Sequence[Any]


@dataclass
class Range(Condition):
    """lo <= field <= hi with open endpoints via inclusive flags."""

    field: str
    lo: float = float("-inf")
    hi: float = float("inf")
    lo_inc: bool = True
    hi_inc: bool = True


def Gt(field: str, v: float) -> Range:
    return Range(field, lo=v, lo_inc=False)


def Ge(field: str, v: float) -> Range:
    return Range(field, lo=v, lo_inc=True)


def Lt(field: str, v: float) -> Range:
    return Range(field, hi=v, hi_inc=False)


def Le(field: str, v: float) -> Range:
    return Range(field, hi=v, hi_inc=True)


@dataclass
class Text(Condition):
    """Full-text: all words must match (FT.SEARCH default AND semantics)."""

    field: str
    query: str


@dataclass
class And(Condition):
    parts: List[Condition] = field(default_factory=list)


@dataclass
class Or(Condition):
    parts: List[Condition] = field(default_factory=list)


# -- index -------------------------------------------------------------------


class _NumericPlane:
    """Dense (docs × numeric-fields) matrix on the block-appended device row
    bank (services/vector.DeviceRowBank).

    Historically this cached one whole-matrix device upload and re-staged
    the ENTIRE host matrix whenever the row count changed — O(docs) H2D per
    single-doc ingest.  Now appends/overwrites buffer host-side and flush as
    ONE packed upload + scatter per block (the embedding banks' discipline),
    so N single-doc ingests cost O(N/block) transfers; a query flushes at
    most the pending tail, never the full matrix."""

    def __init__(self, fields: List[str]):
        from redisson_tpu.services.vector import DeviceRowBank

        self.fields = fields
        self.col = {f: i for i, f in enumerate(fields)}
        self._count = 0
        self._bank = DeviceRowBank(len(fields)) if fields else None

    def __len__(self) -> int:
        return self._count

    @property
    def h2d_flushes(self) -> int:
        return self._bank.h2d_flushes if self._bank is not None else 0

    def _row(self, values: Dict[str, Any]) -> np.ndarray:
        row = np.full(len(self.fields), np.nan, np.float32)
        for f, v in values.items():
            if f in self.col and v is not None:
                try:
                    row[self.col[f]] = float(v)
                except (TypeError, ValueError):
                    pass  # non-numeric value in a NUMERIC column: unindexed
        return row

    def append(self, values: Dict[str, Any]) -> int:
        rowid = self._count
        self._count += 1
        if self._bank is not None:
            self._bank.set_row(rowid, self._row(values))
        return rowid

    def replace(self, rowid: int, values: Dict[str, Any]) -> None:
        if self._bank is not None:
            self._bank.set_row(rowid, self._row(values))

    def clear_row(self, rowid: int) -> None:
        # explicit NaN row (NOT the bank's zero-filled kill): NaN is the
        # "unindexed" sentinel every range compare already treats as False
        if self._bank is not None:
            self._bank.set_row(
                rowid, np.full(len(self.fields), np.nan, np.float32)
            )

    def matrix(self):
        import jax.numpy as jnp

        if self._bank is None:
            return jnp.zeros((0, 0), jnp.float32)
        bank, _bias, _scale, rows = self._bank.device_planes()
        if bank is None:
            return jnp.zeros((0, len(self.fields)), jnp.float32)
        return bank[:rows]

    def range_mask(self, cond: Range) -> np.ndarray:
        """One vectorized compare over all docs on device."""
        import jax.numpy as jnp

        m = self.matrix()
        if m.shape[0] == 0 or cond.field not in self.col:
            return np.zeros(self._count, bool)
        colv = m[:, self.col[cond.field]]
        lo_ok = colv >= cond.lo if cond.lo_inc else colv > cond.lo
        hi_ok = colv <= cond.hi if cond.hi_inc else colv < cond.hi
        mask = jnp.where(jnp.isnan(colv), False, lo_ok & hi_ok)
        return np.asarray(mask)


class SearchIndex:
    """One FT index: schema + doc table + inverted/tag/numeric planes."""

    def __init__(
        self,
        name: str,
        schema: Dict[str, str],
        prefixes: Sequence[str] = ("",),
        doc_mode: str = "entry",
        engine=None,
        vector_specs: Optional[Dict[str, Any]] = None,
    ):
        self.name = name
        self.schema = dict(schema)
        self.prefixes = list(prefixes)
        # device-resident embedding banks (FT VECTOR fields, ISSUE 11):
        # rowids shared with the numeric plane, banks record-backed so they
        # place/rebalance/tear down like every other record.  Requires the
        # engine; an engine-less index (unit-test construction) refuses
        # VECTOR fields rather than silently indexing nothing.
        self.vector_specs = dict(vector_specs or {})
        if self.vector_specs and engine is None:
            raise ValueError("VECTOR fields need an engine-bound index")
        if engine is not None and self.vector_specs:
            from redisson_tpu.services.vector import VectorPlane

            self.vectors = VectorPlane(engine, name, self.vector_specs)
        else:
            self.vectors = None
        # document model for auto-ingestion (SearchService.sync):
        #   "entry" — one doc per dict-valued map ENTRY, id "{map}:{key}"
        #             (the embedded facade's historical model)
        #   "hash"  — one doc per map RECORD, id = map name (RediSearch's
        #             ON HASH model, used by the FT.* wire verbs)
        # One model per index: the two disagree on doc identity, and mixing
        # them through the shared version stamps would suppress each other.
        if doc_mode not in ("entry", "hash"):
            raise ValueError(f"unknown doc_mode {doc_mode!r}")
        self.doc_mode = doc_mode
        self.docs: Dict[str, Dict[str, Any]] = {}          # doc_id -> fields
        self._rowid: Dict[str, int] = {}                   # doc_id -> numeric row
        self._rowdoc: List[Optional[str]] = []             # row -> doc_id
        self._text: Dict[str, Dict[str, set]] = {
            f: {} for f, t in schema.items() if t == FieldType.TEXT
        }                                                   # field -> word -> ids
        self._tag: Dict[str, Dict[Any, set]] = {
            f: {} for f, t in schema.items() if t == FieldType.TAG
        }
        self._numeric = _NumericPlane(
            [f for f, t in schema.items() if t == FieldType.NUMERIC]
        )
        self._synced_versions: Dict[str, int] = {}          # map name -> version
        # synonym groups (FT.SYNUPDATE/SYNDUMP): group id -> lowercase terms,
        # and the reverse map consulted at query time
        self.synonyms: Dict[str, set] = {}
        self._syn_of: Dict[str, set] = {}
        self._lock = threading.RLock()

    # -- synonyms (RediSearch FT.SYNUPDATE / FT.SYNDUMP) ---------------------

    def syn_update(self, group_id: str, terms: Sequence[str]) -> None:
        with self._lock:
            g = self.synonyms.setdefault(group_id, set())
            for t in terms:
                t = str(t).lower()
                g.add(t)
                self._syn_of.setdefault(t, set()).add(group_id)

    def syn_dump(self) -> Dict[str, List[str]]:
        """term -> sorted group ids (the FT.SYNDUMP reply shape)."""
        with self._lock:
            return {t: sorted(gs) for t, gs in self._syn_of.items()}

    # -- document maintenance ------------------------------------------------

    def add(self, doc_id: str, fields: Dict[str, Any]) -> None:
        with self._lock:
            if doc_id in self.docs:
                self._unindex(doc_id)
                self.docs[doc_id] = dict(fields)
                self._index_inverted(doc_id, fields)
                row = self._rowid[doc_id]
                self._numeric.replace(row, fields)
            else:
                self.docs[doc_id] = dict(fields)
                self._index_inverted(doc_id, fields)
                row = self._numeric.append(fields)
                self._rowid[doc_id] = row
                self._rowdoc.append(doc_id)
            if self.vectors:
                self.vectors.set_row(row, fields)

    def remove(self, doc_id: str) -> bool:
        with self._lock:
            if doc_id not in self.docs:
                return False
            self._unindex(doc_id)
            del self.docs[doc_id]
            row = self._rowid.pop(doc_id)
            self._rowdoc[row] = None
            self._numeric.clear_row(row)
            if self.vectors:
                self.vectors.clear_row(row)
            return True

    def _index_inverted(self, doc_id: str, fields: Dict[str, Any]) -> None:
        for f, words in self._text.items():
            for w in tokenize(fields.get(f, "")):
                words.setdefault(w, set()).add(doc_id)
        for f, tags in self._tag.items():
            v = fields.get(f)
            if v is not None:
                tags.setdefault(v, set()).add(doc_id)

    def _unindex(self, doc_id: str) -> None:
        old = self.docs[doc_id]
        for f, words in self._text.items():
            for w in tokenize(old.get(f, "")):
                ids = words.get(w)
                if ids is not None:
                    ids.discard(doc_id)
        for f, tags in self._tag.items():
            v = old.get(f)
            if v is not None and v in tags:
                tags[v].discard(doc_id)

    # -- evaluation ----------------------------------------------------------

    def _eval(self, cond: Optional[Condition]) -> set:
        with self._lock:
            if cond is None:
                return set(self.docs)
            return self._eval_inner(cond)

    def _eval_inner(self, cond: Condition) -> set:
        if isinstance(cond, And):
            sets = [self._eval_inner(p) for p in cond.parts]
            return set.intersection(*sets) if sets else set(self.docs)
        if isinstance(cond, Or):
            out: set = set()
            for p in cond.parts:
                out |= self._eval_inner(p)
            return out
        if isinstance(cond, Text):
            words = tokenize(cond.query)
            plane = self._text.get(cond.field, {})
            sets = []
            for w in words:
                ids = set(plane.get(w, set()))
                # synonym expansion (FT.SYNUPDATE groups): a query term
                # matches docs containing ANY member of its groups —
                # RediSearch semantics, index-time groups applied query-side
                for g in self._syn_of.get(w, ()):
                    for w2 in self.synonyms.get(g, ()):
                        ids |= plane.get(w2, set())
                sets.append(ids)
            return set.intersection(*sets) if sets else set()
        if isinstance(cond, Eq):
            ftype = self.schema.get(cond.field)
            if ftype == FieldType.TAG:
                return set(self._tag.get(cond.field, {}).get(cond.value, set()))
            if ftype == FieldType.NUMERIC:
                v = float(cond.value)
                return self._mask_to_ids(self._numeric.range_mask(Range(cond.field, v, v)))
            if ftype == FieldType.TEXT:
                return self._eval_inner(Text(cond.field, str(cond.value)))
            return {d for d, f in self.docs.items() if f.get(cond.field) == cond.value}
        if isinstance(cond, In):
            out = set()
            for v in cond.values:
                out |= self._eval_inner(Eq(cond.field, v))
            return out
        if isinstance(cond, Range):
            return self._mask_to_ids(self._numeric.range_mask(cond))
        raise TypeError(f"unknown condition {cond!r}")

    def _mask_to_ids(self, mask: np.ndarray) -> set:
        return {
            self._rowdoc[i]
            for i in np.nonzero(mask)[0]
            if self._rowdoc[i] is not None
        }

    def __len__(self) -> int:
        return len(self.docs)


# -- results -----------------------------------------------------------------


@dataclass
class SearchResult:
    total: int
    docs: List[Tuple[str, Dict[str, Any]]]


# -- service -----------------------------------------------------------------


def _edit_distance_le(a: str, b: str, k: int) -> bool:
    """Levenshtein distance <= k (banded DP; FT.SPELLCHECK DISTANCE 1-4)."""
    if abs(len(a) - len(b)) > k:
        return False
    prev = list(range(len(b) + 1))
    for i, ca in enumerate(a, 1):
        cur = [i] + [0] * len(b)
        best = i
        for j, cb in enumerate(b, 1):
            cur[j] = min(
                prev[j] + 1, cur[j - 1] + 1, prev[j - 1] + (ca != cb)
            )
            best = min(best, cur[j])
        if best > k:
            return False
        prev = cur
    return prev[-1] <= k


class SearchService:
    """RSearch analog bound to one engine."""

    def __init__(self, engine):
        self._engine = engine
        self._indexes: Dict[str, SearchIndex] = {}
        self._aliases: Dict[str, str] = {}       # alias -> index name
        self._dicts: Dict[str, set] = {}         # FT.DICT* custom dictionaries
        # FT.CURSOR id -> (pending rows, expires_at): abandoned cursors are
        # pruned by idle timeout + a hard cap, like RediSearch's cursor
        # expiry — without it every undrained WITHCURSOR leaks its rows for
        # the server's lifetime
        self._cursors: Dict[int, Tuple[List[Any], float]] = {}
        self._next_cursor = 1
        self._lock = threading.Lock()

    CURSOR_TTL = 300.0
    CURSOR_MAX = 128

    def _prune_cursors_locked(self) -> None:
        import time as _time

        now = _time.time()
        for cid in [c for c, (_r, exp) in self._cursors.items() if exp <= now]:
            del self._cursors[cid]
        while len(self._cursors) > self.CURSOR_MAX:
            del self._cursors[min(self._cursors)]  # oldest id first

    # -- FT.CREATE / DROPINDEX / _LIST ---------------------------------------

    @staticmethod
    def _vector_specs(schema: Dict[str, str], vector) -> Dict[str, Any]:
        """Normalize the `vector` argument ({field: VectorFieldSpec | spec
        kwargs}) and cross-check it against the schema's VECTOR fields."""
        from redisson_tpu.services.vector import VectorFieldSpec

        specs: Dict[str, Any] = {}
        for f, spec in (vector or {}).items():
            if not isinstance(spec, VectorFieldSpec):
                spec = VectorFieldSpec(field=f, **dict(spec))
            specs[f] = spec
        declared = {f for f, t in schema.items() if t == FieldType.VECTOR}
        if declared != set(specs):
            raise ValueError(
                f"VECTOR schema fields {sorted(declared)} need matching "
                f"vector specs (got {sorted(specs)})"
            )
        return specs

    def create_index(
        self,
        name: str,
        schema: Dict[str, str],
        prefixes: Sequence[str] = ("",),
        doc_mode: str = "entry",
        vector: Optional[Dict[str, Any]] = None,
    ) -> SearchIndex:
        specs = self._vector_specs(schema, vector)
        with self._lock:
            if name in self._indexes:
                raise ValueError(f"index '{name}' already exists")
            idx = SearchIndex(
                name, schema, prefixes, doc_mode,
                engine=self._engine, vector_specs=specs,
            )
            self._indexes[name] = idx
        self.sync(name)
        return idx

    def create(
        self,
        name: str,
        schema: Dict[str, str],
        prefixes: Sequence[str] = ("",),
        doc_mode: str = "entry",
        vector: Optional[Dict[str, Any]] = None,
    ) -> bool:
        """Wire-friendly FT.CREATE (returns a plain bool so it survives the
        OBJCALL pickle boundary; `create_index` returns the live index)."""
        self.create_index(name, schema, prefixes, doc_mode, vector=vector)
        return True

    def drop_index(self, name: str) -> bool:
        with self._lock:
            idx = self._indexes.pop(name, None)
        if idx is not None and idx.vectors:
            # bank records leave the store with the index — device memory is
            # released through the ordinary teardown path, so the census's
            # ftvec gauges return to baseline (the HBM-ledger brick)
            idx.vectors.drop()
        return idx is not None

    def index_names(self) -> List[str]:
        with self._lock:
            return sorted(self._indexes)

    def _idx(self, name: str) -> SearchIndex:
        with self._lock:
            name = self._aliases.get(name, name)
            idx = self._indexes.get(name)
        if idx is None:
            raise KeyError(f"no such index '{name}'")
        return idx

    def resolve(self, name: str) -> str:
        """Alias -> real index name (identity for real names)."""
        with self._lock:
            return self._aliases.get(name, name)

    # -- FT.ALTER ------------------------------------------------------------

    def alter(self, name: str, field: str, ftype: str) -> None:
        """FT.ALTER idx SCHEMA ADD field type: rebuild the index with the
        widened schema and re-add every stored doc (the numeric plane's
        column set is fixed at construction, so ALTER swaps the index the
        way RediSearch rescans)."""
        old = self._idx(name)
        if field in old.schema:
            raise ValueError(f"field '{field}' already exists")
        schema = dict(old.schema)
        schema[field] = ftype
        fresh = SearchIndex(
            old.name, schema, old.prefixes, old.doc_mode,
            engine=self._engine, vector_specs=old.vector_specs,
        )
        with old._lock:
            for doc_id, fields in old.docs.items():
                fresh.add(doc_id, fields)
        with self._lock:
            self._indexes[old.name] = fresh
        self.sync(old.name)

    # -- FT.ALIAS* -----------------------------------------------------------

    def alias_add(self, alias: str, index: str) -> None:
        self._idx(index)  # KeyError if unknown
        with self._lock:
            if alias in self._aliases:
                raise ValueError(f"alias '{alias}' already exists")
            self._aliases[alias] = self._aliases.get(index, index)

    def alias_update(self, alias: str, index: str) -> None:
        self._idx(index)
        with self._lock:
            self._aliases[alias] = self._aliases.get(index, index)

    def alias_del(self, alias: str) -> None:
        with self._lock:
            if alias not in self._aliases:
                raise ValueError(f"alias '{alias}' does not exist")
            del self._aliases[alias]

    # -- FT.DICT* ------------------------------------------------------------

    def dict_add(self, name: str, *terms: str) -> int:
        with self._lock:
            d = self._dicts.setdefault(name, set())
            before = len(d)
            d.update(terms)
            return len(d) - before

    def dict_del(self, name: str, *terms: str) -> int:
        with self._lock:
            d = self._dicts.get(name, set())
            n = 0
            for t in terms:
                if t in d:
                    d.discard(t)
                    n += 1
            return n

    def dict_dump(self, name: str) -> List[str]:
        with self._lock:
            return sorted(self._dicts.get(name, ()))

    # -- FT.SPELLCHECK -------------------------------------------------------

    def spellcheck(
        self, index: str, query: str, include: Sequence[str] = (),
        exclude: Sequence[str] = (), distance: int = 1,
    ) -> Dict[str, List[Tuple[float, str]]]:
        """Suggestions for query terms absent from the index vocabulary
        (RediSearch FT.SPELLCHECK): candidates come from the index's TEXT
        terms plus INCLUDE dicts, minus EXCLUDE dicts; scored by the share
        of docs containing the suggestion (the RediSearch score shape)."""
        idx = self._idx(index)
        self.sync(self.resolve(index))
        vocab: Dict[str, int] = {}
        with idx._lock:
            ndocs = max(1, len(idx.docs))
            for words in idx._text.values():
                for w, ids in words.items():
                    if ids:
                        vocab[w] = max(vocab.get(w, 0), len(ids))
        with self._lock:
            included = set().union(*(self._dicts.get(d, set()) for d in include)) if include else set()
            excluded = set().union(*(self._dicts.get(d, set()) for d in exclude)) if exclude else set()
        known = (set(vocab) | included) - excluded
        out: Dict[str, List[Tuple[float, str]]] = {}
        for term in tokenize(query):
            if term in known:
                continue
            sugg = [
                (vocab.get(c, 0) / ndocs if c in vocab else 0.0, c)
                for c in known
                if _edit_distance_le(term, c, distance)
            ]
            sugg.sort(key=lambda t: (-t[0], t[1]))
            out[term] = sugg
        return out

    # -- FT.CURSOR -----------------------------------------------------------

    def cursor_create(self, rows: List[Any]) -> int:
        import time as _time

        with self._lock:
            cid = self._next_cursor
            self._next_cursor += 1
            self._cursors[cid] = (list(rows), _time.time() + self.CURSOR_TTL)
            self._prune_cursors_locked()  # after insert: cap includes the new one
            return cid

    def cursor_read(self, cid: int, count: int) -> Tuple[List[Any], int]:
        """Returns (rows, next_cursor_id); 0 = exhausted (and deleted).
        A read refreshes the cursor's idle deadline."""
        import time as _time

        with self._lock:
            self._prune_cursors_locked()
            entry = self._cursors.get(cid)
            if entry is None:
                raise KeyError(f"no such cursor {cid}")
            pending, _exp = entry
            rows, rest = pending[:count], pending[count:]
            if rest:
                self._cursors[cid] = (rest, _time.time() + self.CURSOR_TTL)
                return rows, cid
            del self._cursors[cid]
            return rows, 0

    def cursor_del(self, cid: int) -> None:
        with self._lock:
            if cid not in self._cursors:
                raise KeyError(f"no such cursor {cid}")
            del self._cursors[cid]

    def info(self, name: str) -> Dict[str, Any]:
        idx = self._idx(name)
        out = {
            "name": idx.name,
            "num_docs": len(idx),
            "schema": dict(idx.schema),
            "prefixes": list(idx.prefixes),
        }
        if idx.vectors:
            out["vector_fields"] = idx.vectors.info_rows()
            out["vector_device_bytes"] = idx.vectors.device_bytes()
            out["vector_index_bytes"] = idx.vectors.index_device_bytes()
        return out

    def device_census(self) -> Dict[str, float]:
        """Embedding-bank residency gauges — the first concrete brick of the
        ROADMAP HBM-ledger item: per-process bank count + device bytes (and
        per-index byte rows for FT.INFO).  Feeds MetricsRegistry gauges and
        ResourceCensus rows; the vector soak asserts these return to
        baseline after FT.DROPINDEX."""
        with self._lock:
            indexes = list(self._indexes.values())
        banks = 0
        total = 0
        index_bytes = 0
        by_dev: Dict[int, float] = {}
        idx_by_dev: Dict[int, float] = {}
        for idx in indexes:
            if idx.vectors:
                banks += len(idx.vectors.banks)
                total += idx.vectors.device_bytes()
                index_bytes += idx.vectors.index_device_bytes()
                for d, v in idx.vectors.device_bytes_by_device().items():
                    by_dev[d] = by_dev.get(d, 0.0) + float(v)
                for d, v in idx.vectors.index_bytes_by_device().items():
                    idx_by_dev[d] = idx_by_dev.get(d, 0.0) + float(v)
        out = {
            "ftvec_banks": float(banks),
            "ftvec_device_bytes": float(total),
            # the IVF coarse index (centroids + cell table) — its own row
            # so soaks catch a cell-index leak on DROPINDEX even when the
            # bank itself tears down correctly
            "ftvec_index_bytes": float(index_bytes),
        }
        # per-DEVICE breakdown (ISSUE 15 satellite — the HBM-capacity
        # ledger's first per-chip rows): which chip holds how many bank /
        # coarse-index bytes.  Rows exist only while a device holds bytes,
        # so DROPINDEX returns every shard's row to absence == zero (the
        # sharded soak pins that).
        for d, v in sorted(by_dev.items()):
            out[f"ftvec_device_bytes_dev{d}"] = v
        for d, v in sorted(idx_by_dev.items()):
            out[f"ftvec_index_bytes_dev{d}"] = v
        return out

    # -- tracking-plane integration (ISSUE 11) --------------------------------
    #
    # FT.* is keyless on the wire, so the generic key-based tracking hooks
    # never see it.  A tracked FT.SEARCH registers the index's synthetic
    # QUERY KEY instead, and the index's INGEST STREAM (writes landing under
    # its prefixes, index DDL) invalidates that key — hot query results
    # near-cache client-side and go stale the moment the index can change.

    @staticmethod
    def query_key(name: str) -> str:
        return f"__ftq__:{name}"

    def ingest_touched(self, written_names: Sequence[str]) -> List[str]:
        """Query keys of every hash-mode index whose prefixes cover any of
        the written key names (the write-side invalidation hook the server's
        TrackingTable calls post-dispatch)."""
        with self._lock:
            indexes = list(self._indexes.items())
        out = []
        for name, idx in indexes:
            if idx.doc_mode != "hash":
                continue
            if any(
                n.startswith(p)
                for p in idx.prefixes
                for n in written_names
            ):
                out.append(self.query_key(name))
        return out

    # -- KNN (FT VECTOR, services/vector.py) ----------------------------------

    def knn(self, index: str, field: str, queries, k: int,
            condition: Optional[Condition] = None,
            nprobe: Optional[int] = None):
        """One stacked KNN over the index's embedding bank (FLAT exact, or
        routed IVF once the field's coarse quantizer trained; ``nprobe``
        overrides the IVF field's probe width for this query).

        Returns ``(device, finish)``: with the device plane armed, `device`
        is the (dist, idx) kernel-output pair — the caller wraps it in a
        LazyReply / ReadbackFuture and calls ``finish((dist, idx))`` with
        the fetched host arrays; disarmed (RTPU_NO_VECTOR), `device` is
        None and ``finish(None)`` scores on the NumPy path.  Either way
        ``finish`` maps rows back to doc ids and returns one
        ``[(doc_id, distance), ...]`` list per query (distance ascending,
        ties toward the lower rowid)."""
        from redisson_tpu.services import vector as V

        idx = self._idx(index)
        bank = idx.vectors.banks.get(field) if idx.vectors else None
        if bank is None:
            raise ValueError(f"'{field}' is not a VECTOR field of '{index}'")
        if nprobe and bank.spec.algo != "IVF":
            # validated HERE, before either scoring path dispatches: the
            # disarmed path resolves inside finish() — past the verb's
            # ValueError->RespError mapping — so a late raise would reply
            # 'ERR internal' disarmed but a clean error armed
            raise ValueError("NPROBE applies to an IVF field")
        q = np.ascontiguousarray(queries, np.float32).reshape(-1, bank.spec.dim)
        nq = q.shape[0]
        allowed = None
        if condition is not None:
            ids = idx._eval(condition)
            with idx._lock:
                allowed = np.fromiter(
                    (idx._rowid[d] for d in ids if d in idx._rowid),
                    np.int64,
                )
            if allowed.size == 0:
                return None, lambda _vals: [[] for _ in range(nq)]
        armed = V.vector_enabled()
        out = (
            bank.knn_async(q, k, allowed_rows=allowed, nprobe=nprobe)
            if armed else None
        )
        if armed and out is None:
            return None, lambda _vals: [[] for _ in range(nq)]

        def finish(vals):
            if vals is None:  # disarmed: score now, on host
                host = bank.knn_host(q, k, allowed_rows=allowed,
                                     nprobe=nprobe)
                if host is None:
                    return [[] for _ in range(nq)]
                dist_h, idx_h, _nq, k_eff = host
            else:
                # the bank decodes its own device outputs to GLOBAL rowids:
                # (dist, idx) for plain banks, (dist, shard, local) for the
                # mesh-sharded facade (gmap decode off the readback path)
                dist_h, idx_h = bank.resolve_hits(vals)
                k_eff = dist_h.shape[1]
            picked = []   # (qi, rowid, doc) winners, reply order
            for qi in range(nq):
                for j in range(k_eff):
                    if not np.isfinite(dist_h[qi, j]):
                        continue  # k exceeded the live rows: padding entry
                    r = int(idx_h[qi, j])
                    doc = (
                        idx._rowdoc[r]
                        if 0 <= r < len(idx._rowdoc) else None
                    )
                    if doc is None:
                        continue  # doc deleted between dispatch and fetch
                    picked.append((qi, r, doc))
            # the kernel/NumPy paths choose WHICH rows win; the scores on
            # the wire come from ONE canonical per-pair routine so armed
            # and disarmed replies are byte-identical (vector.pair_scores)
            res = [[] for _ in range(nq)]
            if picked:
                scores = bank.pair_scores(
                    q,
                    np.fromiter((p[0] for p in picked), np.int64,
                                count=len(picked)),
                    np.fromiter((p[1] for p in picked), np.int64,
                                count=len(picked)),
                )
                for (qi, _r, doc), d in zip(picked, scores):
                    res[qi].append((doc, float(d)))
            return res

        if not armed:
            return None, finish
        # device arrays lead, (q_count, k_eff) trail: (dist, idx) for the
        # plain bank, (dist, shard, local) for the sharded facade — the
        # LazyReply grouped readback is tuple-length agnostic
        return tuple(out[:-2]), finish

    # -- document ingestion --------------------------------------------------

    def add_document(self, index: str, doc_id: str, fields: Dict[str, Any]) -> None:
        self._idx(index).add(doc_id, fields)

    def remove_document(self, index: str, doc_id: str) -> bool:
        return self._idx(index).remove(doc_id)

    def sync(self, name: str) -> int:
        """Pull documents from every map whose name matches a prefix — the
        reference's hash auto-indexing, done as a version-diffed scan (maps
        whose record version is unchanged are skipped).  The index's
        doc_mode decides the document model (see SearchIndex.__init__)."""
        idx = self._idx(name)
        from redisson_tpu.client.objects.map import Map

        n = 0
        seen = set()
        for key in self._engine.store.keys():
            if not any(key.startswith(p) for p in idx.prefixes):
                continue
            rec = self._engine.store.get(key)
            if rec is None or rec.kind not in ("map", "map_cache"):
                continue
            seen.add(key)
            if idx._synced_versions.get(key) == rec.version:
                continue
            if idx.doc_mode == "hash":
                # wire hashes hold RAW bytes (typed HSET surface): read
                # through BytesCodec, decode to str below
                from redisson_tpu.client.codec import BytesCodec

                m = Map(self._engine, key, codec=BytesCodec())
                fields = {}
                for k, v in m.read_all_entry_set():
                    ks = k.decode() if isinstance(k, (bytes, bytearray)) else str(k)
                    if idx.schema.get(ks) == FieldType.VECTOR:
                        # raw float32 blob (the RediSearch HSET wire shape):
                        # utf-8 decoding arbitrary vector bytes would throw
                        fields[ks] = bytes(v) if isinstance(
                            v, (bytes, bytearray)
                        ) else v
                        continue
                    vs = v.decode() if isinstance(v, (bytes, bytearray)) else v
                    if idx.schema.get(ks) == FieldType.NUMERIC:
                        try:
                            vs = float(vs)
                        except (TypeError, ValueError):
                            pass
                    fields[ks] = vs
                idx.add(key, fields)
                n += 1
            else:
                for k, v in Map(self._engine, key).read_all_entry_set():
                    if isinstance(v, dict):
                        idx.add(f"{key}:{k}", v)
                        n += 1
            idx._synced_versions[key] = rec.version
        if idx.doc_mode == "hash":
            # deleted hashes leave the store silently; prune their docs or
            # searches keep serving stale fields forever
            for gone in [d for d in list(idx.docs) if d not in seen]:
                idx.remove(gone)
                idx._synced_versions.pop(gone, None)
                n += 1
        return n

    # -- FT.SEARCH -----------------------------------------------------------

    def search(
        self,
        index: str,
        condition: Optional[Condition] = None,
        sort_by: Optional[str] = None,
        descending: bool = False,
        offset: int = 0,
        limit: int = 10,
    ) -> SearchResult:
        idx = self._idx(index)
        ids = idx._eval(condition)
        docs = [(d, idx.docs[d]) for d in ids]
        if sort_by is not None:
            docs.sort(
                key=lambda kv: (kv[1].get(sort_by) is None, kv[1].get(sort_by)),
                reverse=descending,
            )
        else:
            docs.sort(key=lambda kv: kv[0])
        return SearchResult(total=len(docs), docs=docs[offset : offset + limit])

    # -- FT.AGGREGATE ---------------------------------------------------------

    _REDUCERS = {
        "count": lambda xs: len(xs),
        "sum": lambda xs: float(np.sum(xs)) if len(xs) else 0.0,
        "avg": lambda xs: float(np.mean(xs)) if len(xs) else float("nan"),
        "min": lambda xs: float(np.min(xs)) if len(xs) else float("nan"),
        "max": lambda xs: float(np.max(xs)) if len(xs) else float("nan"),
    }

    def aggregate(
        self,
        index: str,
        condition: Optional[Condition] = None,
        group_by: Optional[str] = None,
        reducers: Optional[Dict[str, Tuple[str, Optional[str]]]] = None,
        sort_by: Optional[str] = None,
        descending: bool = False,
        offset: int = 0,
        limit: Optional[int] = None,
    ) -> List[Dict[str, Any]]:
        """GROUPBY + REDUCE [+ SORTBY + LIMIT].  `reducers` maps output
        name -> (op, field); ops: count/sum/avg/min/max (field ignored for
        count).  `sort_by` names any OUTPUT column (the group key or a
        reducer name), with offset/limit paging — the FT.AGGREGATE
        SORTBY/LIMIT pipeline stages (RedissonSearch.java aggregate)."""
        idx = self._idx(index)
        ids = idx._eval(condition)
        reducers = reducers or {"count": ("count", None)}
        groups: Dict[Any, List[Dict[str, Any]]] = {}
        for d in ids:
            fields = idx.docs[d]
            key = fields.get(group_by) if group_by else None
            groups.setdefault(key, []).append(fields)
        out = []
        for key, members in groups.items():
            row: Dict[str, Any] = {} if group_by is None else {group_by: key}
            for out_name, (op, f) in reducers.items():
                if op == "count":
                    row[out_name] = len(members)
                else:
                    xs = np.asarray(
                        [float(m[f]) for m in members if m.get(f) is not None],
                        np.float64,
                    )
                    row[out_name] = self._REDUCERS[op](xs)
            out.append(row)
        if sort_by is not None:
            # type-bucketed key: a column mixing numbers and strings must
            # sort deterministically, not raise int-vs-str TypeError
            def _key(r):
                v = r.get(sort_by)
                if v is None:
                    return (2, "", 0.0)
                if isinstance(v, (int, float)) and not isinstance(v, bool):
                    return (0, "", float(v))
                return (1, str(v), 0.0)

            out.sort(key=_key, reverse=descending)
        else:
            out.sort(key=lambda r: (str(r.get(group_by)) if group_by else ""))
        if offset or limit is not None:
            out = out[offset : None if limit is None else offset + limit]
        return out
