"""Config system: typed knobs + YAML/JSON loading with env-var substitution.

Parity target: ``org/redisson/config/Config.java:57-99`` (global knobs with
defaults: threads=16, lockWatchdogTimeout=30s, protocol, transportMode,
eviction delays) plus the per-mode server configs
(``config/BaseConfig.java``, ``BaseMasterSlaveServersConfig.java``,
``ClusterServersConfig.java``: retryAttempts=3, retryInterval, timeout,
pingConnectionInterval, scanInterval, pool sizes) and the YAML/JSON loaders
with ``${ENV_VAR}`` substitution (``config/Config.java:601-631``,
``ConfigSupport.java``).

TPU-first deltas: knobs that tune Netty event loops become knobs that tune
the batching engine (flush window, max batch, shape-bucket floor) and the
device mesh (dp axis size, shard axis size, platform).
"""
from __future__ import annotations

import dataclasses
import json
import os
import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

_ENV_PATTERN = re.compile(r"\$\{([A-Za-z_][A-Za-z0-9_]*)(?::([^}]*))?\}")


def _substitute_env(text: str) -> str:
    """``${VAR}`` / ``${VAR:default}`` substitution (ConfigSupport analog)."""

    def repl(m: re.Match) -> str:
        var, default = m.group(1), m.group(2)
        val = os.environ.get(var)
        if val is None:
            if default is not None:
                return default
            raise KeyError(f"environment variable '{var}' is not set and has no default")
        return val

    return _ENV_PATTERN.sub(repl, text)


@dataclass
class SingleServerConfig:
    """Client/remote mode target (SingleServerConfig analog)."""

    address: str = "tpu://127.0.0.1:6379"
    database: int = 0
    username: Optional[str] = None
    password: Optional[str] = None
    client_name: Optional[str] = None
    # connection behavior (BaseConfig defaults)
    connect_timeout: float = 10.0            # connectTimeout 10s
    timeout: float = 3.0                     # command response timeout 3s
    retry_attempts: int = 3                  # retryAttempts=3
    retry_interval: float = 1.5              # retryInterval=1500ms
    ping_connection_interval: float = 30.0   # pingConnectionInterval=30s
    keep_alive: bool = True
    # pool sizing (connection pool analog)
    connection_pool_size: int = 8            # reference default 64 (JVM); net thread count here
    connection_minimum_idle_size: int = 1
    subscription_connection_pool_size: int = 2
    # TLS (BaseConfig SSL knobs; active for tpus://-scheme addresses or
    # whenever a CA/cert is configured — RedisChannelInitializer.java:110-219)
    ssl_ca_file: Optional[str] = None               # sslTruststore analog
    ssl_cert_file: Optional[str] = None             # sslKeystore (client cert)
    ssl_key_file: Optional[str] = None
    ssl_verify_hostname: bool = True                # sslEnableEndpointIdentification

    def build_ssl_context(self):
        """SSLContext when TLS applies (scheme or explicit knobs), else None."""
        from redisson_tpu.net.client import address_uses_tls, client_ssl_context

        if not (address_uses_tls(self.address) or self.ssl_ca_file or self.ssl_cert_file):
            return None
        return client_ssl_context(
            self.ssl_ca_file, self.ssl_cert_file, self.ssl_key_file,
            self.ssl_verify_hostname,
        )


@dataclass
class ClusterServersConfig:
    """Cluster mode (ClusterServersConfig analog)."""

    node_addresses: List[str] = field(default_factory=list)
    scan_interval: float = 5.0               # scanInterval=5000ms topology poll
    username: Optional[str] = None
    password: Optional[str] = None
    client_name: Optional[str] = None
    connect_timeout: float = 10.0
    timeout: float = 3.0
    retry_attempts: int = 3
    retry_interval: float = 1.5
    ping_connection_interval: float = 30.0
    connection_pool_size: int = 8
    read_mode: str = "MASTER"                # MASTER | SLAVE | MASTER_SLAVE
    dns_monitoring_interval: float = 5.0     # dnsMonitoringInterval; <=0 disables
    # TLS (see SingleServerConfig).  Hostname verification defaults ON like
    # the reference's sslEnableEndpointIdentification — IP-addressed nodes
    # need IP SANs in their certs or an explicit opt-out, never a silent one
    ssl_ca_file: Optional[str] = None
    ssl_cert_file: Optional[str] = None
    ssl_key_file: Optional[str] = None
    ssl_verify_hostname: bool = True

    def build_ssl_context(self):
        from redisson_tpu.net.client import address_uses_tls, client_ssl_context

        tls = any(address_uses_tls(a) for a in self.node_addresses)
        if not (tls or self.ssl_ca_file or self.ssl_cert_file):
            return None
        return client_ssl_context(
            self.ssl_ca_file, self.ssl_cert_file, self.ssl_key_file,
            self.ssl_verify_hostname,
        )


@dataclass
class ReplicatedServersConfig(ClusterServersConfig):
    """Replicated mode (ReplicatedServersConfig analog): N plain endpoints,
    master discovered by the client's ROLE scan — the Azure Redis Cache /
    ElastiCache topology (connection/ReplicatedConnectionManager.java).
    Same knob set as cluster mode; only the defaults differ: a tighter
    scan (master flips are externally driven and the group is small) and
    replica-first reads (the reference's replicated default)."""

    scan_interval: float = 1.0
    read_mode: str = "SLAVE"


@dataclass
class MeshConfig:
    """Device-mesh layout for the embedded data plane (L3', SURVEY §7.1-3).

    The reference has no analog — the closest is the cluster slot layout;
    here it's (dp, shard) axis sizes over jax.devices().
    """

    dp: int = 1                  # data-parallel axis size (1 = no dp split)
    shard: Optional[int] = None  # state-parallel axis; None = all remaining devices
    platform: Optional[str] = None  # force "cpu"/"tpu"; None = jax default
    n_devices: Optional[int] = None  # cap device count; None = all


@dataclass
class Config:
    """Global framework config (org/redisson/config/Config.java analog)."""

    # -- reference-named knobs (same semantics) ------------------------------
    threads: int = 16                         # service executor pool
    lock_watchdog_timeout: float = 30.0       # lockWatchdogTimeout=30_000ms
    check_lock_synced_slaves: bool = True
    reliable_topic_watchdog_timeout: float = 600.0   # Config.java:77
    min_cleanup_delay: float = 5.0            # eviction min delay (Config.java:83-87)
    max_cleanup_delay: float = 1800.0         # eviction max delay 30min
    clean_up_keys_amount: int = 100
    use_script_cache: bool = True
    netty_threads: int = 0                    # accepted for config-file parity; unused

    # -- TPU-first knobs (batching engine replaces Netty tuning) -------------
    batch_flush_window_us: int = 200          # micro-batch collect window
    batch_max_ops: int = 65536                # flush threshold
    min_shape_bucket: int = 256               # pow2 padding floor (kernels.MIN_BUCKET)

    # -- mode sections --------------------------------------------------------
    single_server_config: Optional[SingleServerConfig] = None
    cluster_servers_config: Optional[ClusterServersConfig] = None
    replicated_servers_config: Optional[ReplicatedServersConfig] = None
    mesh: MeshConfig = field(default_factory=MeshConfig)

    # -- SPI slots (reference extension points, §5.6) -------------------------
    # name_mapper: logical object name -> stored key, applied at handle
    # construction (NameMapper SPI).  Must expose map(name) and unmap(key);
    # see NameMapper below for the prefix convenience implementation.
    name_mapper: Any = None
    # command_mapper: wire verb rename (CommandMapper SPI — managed Redis
    # deployments rename dangerous commands).  map(name) -> name, applied
    # just before the frame is written.
    command_mapper: Any = None
    # credentials_resolver: callable(address) -> (username, password) | None,
    # resolved PER CONNECTION ATTEMPT so rotated secrets apply live
    # (CredentialsResolver SPI).
    credentials_resolver: Any = None
    # nat_mapper: advertised cluster address -> reachable address
    # ("host:port" -> "host:port"), applied to CLUSTER SLOTS discoveries
    # (NatMapper SPI — container/NAT topologies).
    nat_mapper: Any = None
    # engine hooks: instrumentation callbacks (NettyHook analog, §5.1)
    hooks: List[Any] = field(default_factory=list)

    # ------------------------------------------------------------------------

    def use_single_server(self) -> SingleServerConfig:
        if self.single_server_config is None:
            self.single_server_config = SingleServerConfig()
        return self.single_server_config

    def use_cluster_servers(self) -> ClusterServersConfig:
        if self.cluster_servers_config is None:
            self.cluster_servers_config = ClusterServersConfig()
        return self.cluster_servers_config

    def use_replicated_servers(self) -> ReplicatedServersConfig:
        if self.replicated_servers_config is None:
            self.replicated_servers_config = ReplicatedServersConfig()
        return self.replicated_servers_config

    # -- loaders (Config.fromYAML / fromJSON analogs) ------------------------

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Config":
        data = dict(data)
        single = data.pop("singleServerConfig", data.pop("single_server_config", None))
        cluster = data.pop("clusterServersConfig", data.pop("cluster_servers_config", None))
        replicated = data.pop(
            "replicatedServersConfig", data.pop("replicated_servers_config", None)
        )
        mesh = data.pop("mesh", None)
        cfg = cls(**{_snake(k): v for k, v in data.items() if _known_field(cls, _snake(k))})
        if single:
            cfg.single_server_config = _build(SingleServerConfig, single)
        if cluster:
            cfg.cluster_servers_config = _build(ClusterServersConfig, cluster)
        if replicated:
            cfg.replicated_servers_config = _build(ReplicatedServersConfig, replicated)
        if mesh:
            cfg.mesh = _build(MeshConfig, mesh)
        return cfg

    @classmethod
    def from_yaml(cls, text_or_path) -> "Config":
        import yaml

        text = _read_maybe_path(text_or_path)
        return cls.from_dict(yaml.safe_load(_substitute_env(text)) or {})

    @classmethod
    def from_json(cls, text_or_path) -> "Config":
        text = _read_maybe_path(text_or_path)
        return cls.from_dict(json.loads(_substitute_env(text)))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def to_yaml(self) -> str:
        import yaml

        return yaml.safe_dump(self.to_dict(), sort_keys=False)


def _read_maybe_path(text_or_path) -> str:
    s = str(text_or_path)
    if "\n" not in s and (s.endswith((".yaml", ".yml", ".json")) or os.path.exists(s)):
        with open(s, "r", encoding="utf-8") as f:
            return f.read()
    return s


_SNAKE1 = re.compile(r"(.)([A-Z][a-z]+)")
_SNAKE2 = re.compile(r"([a-z0-9])([A-Z])")


def _snake(name: str) -> str:
    return _SNAKE2.sub(r"\1_\2", _SNAKE1.sub(r"\1_\2", name)).lower()


def _known_field(cls, name: str) -> bool:
    return name in {f.name for f in dataclasses.fields(cls)}


def _build(cls, data: Dict[str, Any]):
    kwargs = {}
    for k, v in data.items():
        sk = _snake(k)
        if _known_field(cls, sk):
            kwargs[sk] = v
    return cls(**kwargs)


class NameMapper:
    """Prefix/suffix NameMapper (the reference ships the same convenience:
    org/redisson/api/NameMapper.direct()/prefix()).  Custom mappers only
    need map(name) -> stored key and unmap(key) -> logical name."""

    def __init__(self, prefix: str = "", suffix: str = ""):
        self.prefix = prefix
        self.suffix = suffix

    def map(self, name: str) -> str:
        return f"{self.prefix}{name}{self.suffix}"

    def unmap(self, key: str) -> str:
        out = key
        if self.prefix and out.startswith(self.prefix):
            out = out[len(self.prefix):]
        if self.suffix and out.endswith(self.suffix):
            out = out[: -len(self.suffix)]
        return out
