"""RemoteRedisson: client/remote mode — the full object surface over the wire.

Role parity: this is what `Redisson.create(config)` gives a JVM app — a
client whose object handles execute on a remote data plane.  Two paths:

  * **Hot path** (sketch/bit tensors): dedicated wire commands whose payloads
    are packed binary batches (BF.MADD64 et al.) — the RBatch flush arrives at
    the server as ONE command and dispatches ONE fused kernel.
  * **Everything else**: `OBJCALL` generic invocation — the client-side proxy
    pickles (args, kwargs), the server executes the same method on its
    embedded handle and ships the pickled result back (the reference ships
    serialized task classBody the same way, executor/TasksRunnerService.java).

Listeners (topics) ride the dedicated pubsub connection.
"""
from __future__ import annotations

import logging
import pickle
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from redisson_tpu.client.codec import Codec, DEFAULT_CODEC

logger = logging.getLogger(__name__)
from redisson_tpu.net.client import NodeClient
from redisson_tpu.net.resp import RespError

# Client-process shared infrastructure for lock-watchdog renewals: ONE wheel
# timer schedules ticks, a small pool runs the renewal RPCs (network calls
# must not block the wheel thread).  The reference does the same with the
# ServiceManager's HashedWheelTimer + executor — never a thread per lock.
import threading as _threading

_renewal_timer = None
_renewal_pool = None
_renewal_guard = _threading.Lock()
# first-enable CAS for RemoteSurface.enable_tracking (shared across facades:
# the op is once-per-facade, contention is nil)
_tracking_enable_lock = _threading.Lock()


def _client_renewal_infra():
    global _renewal_timer, _renewal_pool
    with _renewal_guard:
        if _renewal_timer is None:
            from concurrent.futures import ThreadPoolExecutor

            from redisson_tpu.utils.timer import HashedWheelTimer

            _renewal_timer = HashedWheelTimer()
            _renewal_pool = ThreadPoolExecutor(
                max_workers=4, thread_name_prefix="rtpu-renew"
            )
        return _renewal_timer, _renewal_pool


# ObjectRef resolution (RedissonReference over the wire): server-side
# handles pickle as inert ObjectRef descriptors (objects/base.py
# __reduce__); the receiving client rebinds them to LIVE handles through
# its own factories so references read back as objects on every surface.
_REF_FACTORIES = {
    "Map": "get_map", "MapCache": "get_map_cache",
    # LocalCachedMap must rebind as a local-cached handle: resolving it as a
    # plain map would mutate without publishing invalidations, leaving every
    # other client's near cache silently stale
    "LocalCachedMap": "get_local_cached_map",
    "Set": "get_set", "SetCache": "get_set_cache",
    "RList": "get_list", "Queue": "get_queue", "Deque": "get_deque",
    "BlockingQueue": "get_blocking_queue", "BlockingDeque": "get_blocking_deque",
    "PriorityQueue": "get_priority_queue", "PriorityDeque": "get_priority_deque",
    "PriorityBlockingQueue": "get_priority_blocking_queue",
    "PriorityBlockingDeque": "get_priority_blocking_deque",
    "RingBuffer": "get_ring_buffer",
    # DelayedQueue deliberately absent: its factory takes the DESTINATION
    # queue handle, not a name — a by-name rebind can't reconstruct it, so
    # its references stay inert (name + type still identify it)
    "TransferQueue": "get_transfer_queue",
    "ScoredSortedSet": "get_scored_sorted_set",
    "SortedSet": "get_sorted_set", "LexSortedSet": "get_lex_sorted_set",
    "ListMultimap": "get_list_multimap", "SetMultimap": "get_set_multimap",
    "ListMultimapCache": "get_list_multimap_cache",
    "SetMultimapCache": "get_set_multimap_cache",
    "BoundedBlockingQueue": "get_bounded_blocking_queue",
    "Bucket": "get_bucket", "AtomicLong": "get_atomic_long",
    "AtomicDouble": "get_atomic_double", "IdGenerator": "get_id_generator",
    "BitSet": "get_bit_set", "BloomFilter": "get_bloom_filter",
    "HyperLogLog": "get_hyper_log_log", "Geo": "get_geo",
    "TimeSeries": "get_time_series", "Stream": "get_stream",
    "JsonBucket": "get_json_bucket", "BinaryStream": "get_binary_stream",
    "Lock": "get_lock", "FairLock": "get_fair_lock", "SpinLock": "get_spin_lock",
    "FencedLock": "get_fenced_lock", "Semaphore": "get_semaphore",
    "CountDownLatch": "get_count_down_latch", "RateLimiter": "get_rate_limiter",
}

# classes whose handles never decode user values with their codec
# (synchronizers, numeric counters, raw-bit state): the ref's recorded
# codec — every handle carries one, usually the default — is irrelevant,
# so their factories are called name-only.  Everything else MUST honor the
# reference's codec or fail loudly (see resolve_ref).
_CODEC_FREE = {
    "Lock", "FairLock", "SpinLock", "FencedLock", "Semaphore",
    "CountDownLatch", "RateLimiter", "AtomicLong", "AtomicDouble",
    "IdGenerator", "BitSet",
}


def resolve_ref(client, ref):
    """ObjectRef -> live handle via the client's factory; unknown classes
    stay inert (the descriptor itself is still useful: name + type)."""
    from redisson_tpu.client.codec import _codec_from_spec

    factory = getattr(client, _REF_FACTORIES.get(ref.cls, ""), None)
    if factory is None:
        return ref
    codec = _codec_from_spec(ref.codec)
    if ref.codec is not None and codec is None and ref.cls not in _CODEC_FREE:
        # the reference recorded a codec its spec cannot rebuild
        # (CompositeCodec halves, parameterized codecs): resolving with the
        # default codec would silently misdecode — stay inert instead
        return ref
    if (
        codec is not None
        and type(codec) is type(DEFAULT_CODEC)
        and getattr(codec, "inner", None) is None
    ):
        # every handle records a codec, usually the default: passing the
        # default along changes nothing, and name-only keeps codec-less
        # surfaces (async proxies) resolving
        codec = None
    if codec is None or ref.cls in _CODEC_FREE:
        return factory(ref.name)
    # a factory that cannot honor the reference's NON-default codec must
    # FAIL here, not silently decode with the default one — the async
    # surface raises TypeError for exactly that (aio.py make()); swallowing
    # it would turn a StringCodec list into wrongly-JSON-decoded values
    # with no trace
    return factory(ref.name, codec)


def _resolve_refs(client, value):
    """Resolve ObjectRefs at the top level and one container level deep —
    the shapes object methods actually return (scalars, lists, dicts)."""
    from redisson_tpu.client.codec import ObjectRef

    if client is None:
        return value
    if isinstance(value, ObjectRef):
        return resolve_ref(client, value)
    if isinstance(value, list):
        return [resolve_ref(client, v) if isinstance(v, ObjectRef) else v for v in value]
    if isinstance(value, tuple):
        return tuple(resolve_ref(client, v) if isinstance(v, ObjectRef) else v for v in value)
    if isinstance(value, dict):
        return {
            (resolve_ref(client, k) if isinstance(k, ObjectRef) else k):
            (resolve_ref(client, v) if isinstance(v, ObjectRef) else v)
            for k, v in value.items()
        }
    return value


def _unwrap(reply: Any, client=None) -> Any:
    from redisson_tpu.net.safe_pickle import safe_loads

    if isinstance(reply, RespError):
        raise reply
    if isinstance(reply, (bytes, bytearray)) and reply[:1] in (b"R", b"E"):
        payload = safe_loads(bytes(reply[1:]))
        if reply[:1] == b"E":
            raise payload
        return _resolve_refs(client, payload)
    return reply


def _unwrap_many(reply: Any, client=None) -> List[Any]:
    """Decode an OBJCALLM reply: list of results with per-op exceptions left
    AS VALUES (batch semantics — the caller decides what to raise)."""
    from redisson_tpu.net.safe_pickle import safe_loads

    if isinstance(reply, RespError):
        raise reply
    if not (isinstance(reply, (bytes, bytearray)) and reply[:1] == b"M"):
        raise RespError("ERR bad OBJCALLM reply frame")
    return [_resolve_refs(client, r) for _tag, r in safe_loads(bytes(reply[1:]))]


class RemoteObjectProxy:
    """Generic remote handle: every method call becomes one OBJCALL.

    A non-default `codec` travels with every call (OBJCALL's optional codec
    frame arg) so the server-side handle encodes keys/values exactly like
    the caller's — the reference's getMap(name, codec) contract."""

    def __init__(self, client: "RemoteRedisson", factory: str, name: str,
                 codec: Optional[Codec] = None):
        self._client = client
        self._factory = factory
        self._name = name
        self._codec = codec

    @property
    def name(self) -> str:
        return self._name

    def drain_to(self, collection: list, max_elements: Optional[int] = None) -> int:
        """Out-param methods cannot cross the RPC boundary (the server would
        fill a pickled COPY of `collection`); re-expressed as one poll_many
        wire call whose reply fills the caller's collection locally —
        the reference's drainTo is the same client-side loop shape."""
        items = self.poll_many(max_elements if max_elements is not None else 1 << 62)
        collection.extend(items)
        return len(items)

    def add_entry_listener(self, kind: str, fn):
        """MapCache entry events ride pubsub channels
        (`redisson_map_cache_<kind>:{name}`), so a remote listener is a wire
        SUBSCRIBE — callbacks cannot cross RPC as OBJCALL args.  fn is
        called as fn(key, value, old_value), same as the embedded handle."""
        from redisson_tpu.client.objects.map import MapCache
        from redisson_tpu.net.safe_pickle import safe_loads

        if kind not in MapCache.EVENT_KINDS:  # fail fast like the embedded handle
            raise ValueError(f"unknown entry event kind: {kind!r}")
        ch = f"redisson_map_cache_{kind}:{self._name}"

        def wire_listener(_channel: str, payload: bytes) -> None:
            # guarded: an exception here would kill the shared pubsub reader
            # thread and silently end ALL push delivery on this connection
            try:
                fn(*safe_loads(payload))
            except Exception:  # noqa: BLE001 — listener faults must not stop the reader
                logger.exception("entry listener for %s failed", ch)

        # subscribe on the shard that owns the MAP (not the channel string):
        # the engine-hub publish happens on the master serving the map's
        # slot, so that is where has_listeners() must see this subscriber
        self._client.pubsub_for(self._name).subscribe(ch, wire_listener)
        return (ch, wire_listener)

    def remove_entry_listener(self, token) -> None:
        ch, wire_listener = token
        self._client.pubsub_for(self._name).remove_listener(ch, wire_listener)

    def __getattr__(self, method: str) -> Callable:
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            return self._client.objcall(
                self._factory, self._name, method, args, kwargs, codec=self._codec
            )

        call.__name__ = method
        return call


def int64_blob(keys) -> bytes:
    """The blob wire form for integer key batches (BF.MADD64 family): one
    little-endian i64 buffer — shared by every sync/async blob handle so
    the wire shape cannot drift between surfaces."""
    return np.ascontiguousarray(keys, dtype="<i8").tobytes()


def bool_reply(out) -> np.ndarray:
    """Decode a blob command's per-key byte reply into a bool array."""
    return np.frombuffer(out, np.uint8).astype(bool)


def reserve_exists(err: "RespError") -> bool:
    """True when BF.RESERVE failed because the filter ALREADY EXISTS (the
    RedisBloom 'item exists' wording) — any other error must propagate."""
    return "item exists" in str(err)


class _ObjcallFallback:
    """Unknown methods on the CONCRETE fast-path handles fall through to
    OBJCALL on the matching factory: the typed verbs stay the hot path,
    while the full embedded surface (lifecycle ops, conditional expiry,
    future additions) is reachable without hand-mirroring every method."""

    _FALLBACK_FACTORY: str = ""

    def __getattr__(self, method: str):
        if method.startswith("_") or not self._FALLBACK_FACTORY:
            raise AttributeError(method)

        def call(*args, **kwargs):
            return self._client.objcall(
                self._FALLBACK_FACTORY, self.name, method, args, kwargs,
                # the handle's codec travels like the generic proxy's:
                # a custom-codec handle must not fall back to the default
                codec=getattr(self, "_codec", None),
            )

        call.__name__ = method
        return call


class RemoteBloomFilter(_ObjcallFallback):
    """Hot-path bloom handle (BF.* wire commands; int batches ride blobs)."""

    _FALLBACK_FACTORY = "get_bloom_filter"

    def __init__(self, client: "RemoteRedisson", name: str, codec: Optional[Codec]):
        self._client = client
        self.name = name
        self._codec = codec or DEFAULT_CODEC

    def try_init(self, expected_insertions: int, false_probability: float) -> bool:
        try:
            self._client.execute(
                "BF.RESERVE", self.name, repr(false_probability), expected_insertions
            )
            return True
        except RespError as e:
            if reserve_exists(e):
                return False  # already initialized: the documented False
            raise  # bad params / routing exhaustion must not masquerade

    def _encode_keys(self, objs) -> List[bytes]:
        if isinstance(objs, (bytes, str, int, float)):
            objs = [objs]
        return [o if isinstance(o, bytes) else self._codec.encode(o) for o in objs]

    def add(self, obj) -> bool:
        if isinstance(obj, np.ndarray):
            # embedded-handle parity (objects/bloom.py BloomFilter.add): an
            # array argument is a BATCH — the old path encoded the array to
            # a key list and silently added only its first element
            return bool(self.add_each(obj).any())
        return bool(self._client.execute("BF.ADD", self.name, self._encode_keys(obj)[0]))

    def add_all(self, objs) -> int:
        return int(self.add_each(objs).sum())

    def add_each(self, objs) -> np.ndarray:
        if isinstance(objs, np.ndarray) and objs.dtype.kind in "iu":
            out = self._client.execute("BF.MADD64", self.name, int64_blob(objs))
            return bool_reply(out)
        reply = self._client.execute("BF.MADD", self.name, *self._encode_keys(objs))
        return np.asarray(reply, dtype=bool)

    def contains(self, obj) -> bool:
        return bool(self._client.execute("BF.EXISTS", self.name, self._encode_keys(obj)[0]))

    def contains_each(self, objs) -> np.ndarray:
        if isinstance(objs, np.ndarray) and objs.dtype.kind in "iu":
            out = self._client.execute("BF.MEXISTS64", self.name, int64_blob(objs))
            return bool_reply(out)
        reply = self._client.execute("BF.MEXISTS", self.name, *self._encode_keys(objs))
        return np.asarray(reply, dtype=bool)

    def count_contains(self, objs) -> int:
        return int(self.contains_each(objs).sum())


class RemoteBloomFilterArray(_ObjcallFallback):
    """Multi-tenant bloom bank over the wire (BFA.* blob commands)."""

    _FALLBACK_FACTORY = "get_bloom_filter_array"

    def __init__(self, client: "RemoteRedisson", name: str):
        self._client = client
        self.name = name

    def try_init(self, tenants: int, expected_insertions: int, false_probability: float) -> bool:
        try:
            self._client.execute(
                "BFA.RESERVE", self.name, tenants, expected_insertions, repr(false_probability)
            )
            return True
        except RespError:
            return False

    def _blobs(self, tenant_ids, keys) -> Tuple[bytes, bytes]:
        t = np.ascontiguousarray(np.asarray(tenant_ids), dtype="<i4").tobytes()
        k = np.ascontiguousarray(np.asarray(keys), dtype="<i8").tobytes()
        return t, k

    def add_each(self, tenant_ids, keys) -> np.ndarray:
        t, k = self._blobs(tenant_ids, keys)
        out = self._client.execute("BFA.MADD64", self.name, t, k)
        return np.frombuffer(out, np.uint8).astype(bool)

    def contains(self, tenant_ids, keys) -> np.ndarray:
        t, k = self._blobs(tenant_ids, keys)
        out = self._client.execute("BFA.MEXISTS64", self.name, t, k)
        return np.frombuffer(out, np.uint8).astype(bool)


class RemoteHyperLogLogArray(_ObjcallFallback):
    """Multi-tenant HLL bank over the wire (HLLA.* blob commands — the
    sketch-blob discipline of the bloom bank applied to the HLL bank)."""

    _FALLBACK_FACTORY = "get_hyper_log_log_array"

    def __init__(self, client: "RemoteRedisson", name: str):
        self._client = client
        self.name = name

    def try_init(self, tenants: int) -> bool:
        return bool(self._client.execute("HLLA.RESERVE", self.name, tenants))

    @staticmethod
    def _pair_blobs(a, b) -> Tuple[bytes, bytes]:
        return (
            np.ascontiguousarray(np.asarray(a), dtype="<i4").tobytes(),
            np.ascontiguousarray(np.asarray(b), dtype="<i4").tobytes(),
        )

    def add(self, tenant_ids, keys) -> None:
        t = np.ascontiguousarray(np.asarray(tenant_ids), dtype="<i4").tobytes()
        k = np.ascontiguousarray(np.asarray(keys), dtype="<i8").tobytes()
        self._client.execute("HLLA.MADD64", self.name, t, k)

    def merge_rows(self, dst_ids, src_ids) -> None:
        d, s = self._pair_blobs(dst_ids, src_ids)
        self._client.execute("HLLA.MERGEROWS", self.name, d, s)

    def estimate_all(self) -> np.ndarray:
        out = self._client.execute("HLLA.ESTIMATE", self.name)
        return np.frombuffer(out, "<f8").copy()

    def estimate_union_pairs(self, a_ids, b_ids) -> np.ndarray:
        a, b = self._pair_blobs(a_ids, b_ids)
        out = self._client.execute("HLLA.ESTPAIRS", self.name, a, b)
        return np.frombuffer(out, "<f8").copy()


class RemoteHyperLogLog(_ObjcallFallback):
    _FALLBACK_FACTORY = "get_hyper_log_log"
    def __init__(self, client: "RemoteRedisson", name: str, codec: Optional[Codec]):
        self._client = client
        self.name = name
        self._codec = codec or DEFAULT_CODEC

    def add(self, obj) -> bool:
        data = obj if isinstance(obj, bytes) else self._codec.encode(obj)
        return bool(self._client.execute("PFADD", self.name, data))

    def add_all(self, objs) -> bool:
        if isinstance(objs, np.ndarray) and objs.dtype.kind in "iu":
            blob = np.ascontiguousarray(objs, dtype="<i8").tobytes()
            return bool(self._client.execute("PFADD64", self.name, blob))
        encoded = [o if isinstance(o, bytes) else self._codec.encode(o) for o in objs]
        return bool(self._client.execute("PFADD", self.name, *encoded))

    def count(self) -> int:
        return int(self._client.execute("PFCOUNT", self.name))

    def count_with(self, *names: str) -> int:
        return int(self._client.execute("PFCOUNT", self.name, *names))

    def merge_with(self, *names: str) -> None:
        self._client.execute("PFMERGE", self.name, *names)


class RemoteBitSet(_ObjcallFallback):
    _FALLBACK_FACTORY = "get_bit_set"
    def __init__(self, client: "RemoteRedisson", name: str):
        self._client = client
        self.name = name

    def set(self, index: int, value: bool = True) -> bool:
        return bool(self._client.execute("SETBIT", self.name, index, 1 if value else 0))

    def get(self, index: int) -> bool:
        return bool(self._client.execute("GETBIT", self.name, index))

    def set_each(self, indexes, value: bool = True) -> np.ndarray:
        if not value:
            proxy = RemoteObjectProxy(self._client, "get_bit_set", self.name)
            return proxy.set_each(np.asarray(indexes), False)
        reply = self._client.execute("SETBITS", self.name, *[int(i) for i in indexes])
        return np.asarray(reply, dtype=bool)

    def get_each(self, indexes) -> np.ndarray:
        reply = self._client.execute("GETBITS", self.name, *[int(i) for i in indexes])
        return np.asarray(reply, dtype=bool)

    def cardinality(self) -> int:
        return int(self._client.execute("BITCOUNT", self.name))

    def or_(self, *others: str) -> None:
        self._client.execute("BITOP", "OR", self.name, self.name, *others)

    def and_(self, *others: str) -> None:
        self._client.execute("BITOP", "AND", self.name, self.name, *others)

    def xor(self, *others: str) -> None:
        self._client.execute("BITOP", "XOR", self.name, self.name, *others)


class RemoteBucket(_ObjcallFallback):
    _FALLBACK_FACTORY = "get_bucket"
    def __init__(self, client: "RemoteRedisson", name: str, codec: Optional[Codec]):
        self._client = client
        self.name = name
        self._codec = codec or DEFAULT_CODEC

    def set(self, value: Any, ttl: Optional[float] = None) -> None:
        args = ["SET", self.name, self._codec.encode(value)]
        if ttl is not None:
            args += ["PX", int(ttl * 1000)]
        self._client.execute(*args)

    def get(self) -> Any:
        data = self._client.execute("GET", self.name)
        return None if data is None else self._codec.decode(bytes(data))

    def try_set(self, value: Any, ttl: Optional[float] = None) -> bool:
        args = ["SET", self.name, self._codec.encode(value), "NX"]
        if ttl is not None:
            args += ["PX", int(ttl * 1000)]
        return self._client.execute(*args) is not None

    def delete(self) -> bool:
        return bool(self._client.execute("DEL", self.name))


class RemoteBuckets:
    """RBuckets over the wire (RedissonBuckets.java): every per-name op
    routes by ITS name (cluster-correct — the embedded handle's in-process
    loop becomes per-slot routing for free), and the MSETNX-style try_set
    rides an optimistic transaction so the all-or-nothing contract holds
    atomically even across shards (version preconditions at commit)."""

    def __init__(self, client, codec: Optional[Codec] = None):
        self._client = client
        self._codec = codec

    def get(self, *names: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for nm in names:
            v = self._client.get_bucket(nm, self._codec).get()
            if v is not None:
                out[nm] = v
        return out

    def set(self, values: Dict[str, Any]) -> None:
        for nm, v in values.items():
            self._client.get_bucket(nm, self._codec).set(v)

    def try_set(self, values: Dict[str, Any]) -> bool:
        from redisson_tpu.services.transactions import (
            TransactionException,
        )

        for _attempt in range(3):
            tx = self._client.create_transaction()
            if not tx.get_buckets(self._codec).try_set(values):
                tx.rollback()
                return False
            try:
                tx.commit()
                return True
            except TransactionException:
                continue  # a racer created/changed a key: re-probe
        return False


class RemoteTopic:
    def __init__(self, client: "RemoteRedisson", name: str, codec: Optional[Codec]):
        self._client = client
        self.name = name
        self._codec = codec or DEFAULT_CODEC

    def publish(self, message: Any) -> int:
        # same node the subscribers attached to via pubsub_for(name)
        return self._client.publish_for(self.name, self.name, self._codec.encode(message))

    def add_listener(self, listener: Callable[[str, Any], None]) -> Callable[[str, bytes], None]:
        codec = self._codec

        def wire_listener(channel: str, payload: bytes) -> None:
            try:
                value = codec.decode(payload)
            except Exception:  # noqa: BLE001 — non-codec publishers (raw bytes)
                value = payload
            listener(channel, value)

        self._client.pubsub_for(self.name).subscribe(self.name, wire_listener)
        return wire_listener

    def remove_listener(self, token) -> None:
        """RTopic.removeListener(id): detach ONE listener by the token
        add_listener returned (the wire wrapper)."""
        self._client.pubsub_for(self.name).remove_listener(self.name, token)

    def remove_all_listeners(self) -> None:
        self._client.pubsub_for(self.name).unsubscribe(self.name)


class BatchOptions:
    """api/BatchOptions.java parity: execution mode, response timeout,
    retry policy, syncSlaves, skipResult.

    Modes: "IN_MEMORY" (default — ops queue client-side, flush as per-shard
    OBJCALLM frames + coalesced sketch blobs) and "IN_MEMORY_ATOMIC" (the
    MULTI/EXEC analog — the whole group executes under engine.locked_many
    server-side with no interleaving; cluster rule as in the reference:
    every touched object must colocate on one shard, use {hashtags})."""

    IN_MEMORY = "IN_MEMORY"
    IN_MEMORY_ATOMIC = "IN_MEMORY_ATOMIC"

    def __init__(self):
        self.execution_mode = self.IN_MEMORY
        self.response_timeout: Optional[float] = None   # None = client default
        self.retry_attempts: Optional[int] = None       # reads-only retries
        self.retry_interval: float = 0.5
        self.sync_slaves: bool = False                  # WAIT analog: REPLFLUSH
        self.skip_result: bool = False

    @classmethod
    def defaults(cls) -> "BatchOptions":
        return cls()

    def atomic(self) -> "BatchOptions":
        self.execution_mode = self.IN_MEMORY_ATOMIC
        return self


class _BatchObjectProxy:
    """Batch-scoped handle: every method call QUEUES an op and returns its
    result index (resolved by execute())."""

    def __init__(self, batch: "RemoteBatch", factory: str, name: str, codec=None):
        self._batch = batch
        self._factory = factory
        self._name = name
        self._codec = codec

    def __getattr__(self, method: str):
        if method.startswith("_"):
            raise AttributeError(method)

        def call(*args, **kwargs):
            return self._batch._enqueue(
                ("objcall", self._name,
                 (self._factory, self._name, method, args, kwargs, self._codec))
            )

        call.__name__ = method
        return call


class RemoteBatch:
    """RBatch over the wire (CommandBatchService.java:87-151,211-540 at the
    wire layer): the FULL object surface queues through batch-scoped
    proxies and flushes as per-shard OBJCALLM frames (atomic mode:
    OBJCALLMA under the server's locked_many), while same-object bloom
    sketch ops still pre-coalesce into single blob commands — the fastest
    wire form for the north-star workload.

    Results come back in submission order.  Writes keep at-most-once: a
    response timeout raises instead of re-sending (the objcall_many rule)."""

    def __init__(self, client: "RemoteRedisson", options: Optional[BatchOptions] = None):
        self._client = client
        self._options = options or BatchOptions.defaults()
        self._ops: List[Tuple[str, str, Any]] = []  # (kind, name, payload)
        self._executed = False

    # -- batch-scoped handles ------------------------------------------------

    def get_bloom_filter(self, name: str):
        batch = self

        class _B:
            def contains_async(self, keys):
                return batch._enqueue(("bf.contains", name, np.asarray(keys)))

            def add_async(self, keys):
                return batch._enqueue(("bf.add", name, np.asarray(keys)))

        return _B()

    def __getattr__(self, factory: str):
        if factory in _GENERIC_FACTORIES or factory in (
            "get_bucket", "get_bit_set", "get_hyper_log_log", "get_atomic_long",
        ):
            def make(name: str, codec=None, *_a, **_k) -> _BatchObjectProxy:
                return _BatchObjectProxy(self, factory, name, codec)

            return make
        raise AttributeError(factory)

    def _enqueue(self, op: Tuple[str, str, Any]) -> int:
        if self._executed:
            raise RuntimeError("batch already executed")
        self._ops.append(op)
        return len(self._ops) - 1

    # -- execution -------------------------------------------------------------

    def execute(self) -> List[Any]:
        if self._executed:
            raise RuntimeError("batch already executed")
        self._executed = True
        opts = self._options
        timeout = opts.response_timeout
        results: List[Any] = [None] * len(self._ops)

        atomic = opts.execution_mode == BatchOptions.IN_MEMORY_ATOMIC
        # 1) sketch blob fast path: group bf ops per (kind, name).  In
        # ATOMIC mode bf ops must join the locked group instead — the blob
        # commands run outside OBJCALLMA's locked_many, which would let a
        # concurrent writer interleave between the "atomic" batch's sketch
        # and generic ops (the embedded Batch locks bloom groups too)
        blob_groups: Dict[Tuple[str, str], List[int]] = {}
        objcall_idx: List[int] = []
        for i, (kind, name, payload) in enumerate(self._ops):
            if kind in ("bf.contains", "bf.add") and not atomic:
                blob_groups.setdefault((kind, name), []).append(i)
            elif kind in ("bf.contains", "bf.add"):
                method = "contains_each" if kind == "bf.contains" else "add_each"
                self._ops[i] = (
                    "objcall", name,
                    ("get_bloom_filter", name, method, (np.asarray(payload),), {}, None),
                )
                objcall_idx.append(i)
            else:
                objcall_idx.append(i)
        commands: List[Tuple] = []
        layout: List[Tuple[List[int], List[int]]] = []
        for (kind, name), idxs in blob_groups.items():
            keys = np.concatenate([np.asarray(self._ops[i][2]).reshape(-1) for i in idxs])
            blob = np.ascontiguousarray(keys, dtype="<i8").tobytes()
            cmd = "BF.MEXISTS64" if kind == "bf.contains" else "BF.MADD64"
            commands.append((cmd, name, blob))
            layout.append((idxs, [np.asarray(self._ops[i][2]).size for i in idxs]))

        attempts = (opts.retry_attempts if opts.retry_attempts is not None else 0) + 1
        last: Optional[BaseException] = None
        for attempt in range(attempts):
            try:
                if commands:
                    replies = self._client.execute_many(commands, timeout=timeout)
                else:
                    replies = []
                break
            except TimeoutError:
                # the frame was WRITTEN and may have executed: re-sending
                # would double-apply the adds (at-most-once; TimeoutError is
                # an OSError subclass, so this clause must come first)
                raise
            except (ConnectionError, OSError) as e:
                last = e  # pre-write failure: safe to retry
                time.sleep(min(self._options.retry_interval * (attempt + 1), 2.0))
        else:
            assert last is not None
            raise last
        for (idxs, sizes), reply in zip(layout, replies):
            if isinstance(reply, RespError):
                raise reply
            flags = np.frombuffer(reply, np.uint8).astype(bool)
            off = 0
            for i, sz in zip(idxs, sizes):
                results[i] = flags[off : off + sz]
                off += sz

        # 2) generic surface: per-shard OBJCALLM / atomic OBJCALLMA
        if objcall_idx:
            ops = [self._ops[i][2] for i in objcall_idx]
            replies = self._client.objcall_many_batch(ops, atomic=atomic, timeout=timeout)
            for i, r in zip(objcall_idx, replies):
                if isinstance(r, BaseException):
                    raise r
                results[i] = r

        # 3) syncSlaves (WAIT analog): force the replication stream flush on
        # every touched shard before returning
        if opts.sync_slaves:
            names = {name for _k, name, _p in self._ops if name}
            self._client.sync_replication(names, timeout=timeout)

        if opts.skip_result:
            return []
        return results


class RemoteKeys:
    """RKeys over the wire — the full embedded Keys surface on typed verbs
    (RedissonKeys.java roles)."""

    def __init__(self, client: "RemoteRedisson"):
        self._client = client

    def get_keys(self, pattern: str = "*") -> List[str]:
        return [k.decode() for k in self._client.execute("KEYS", pattern)]

    def delete(self, *names: str) -> int:
        return int(self._client.execute("DEL", *names))

    def unlink(self, *names: str) -> int:
        return int(self._client.execute("UNLINK", *names))

    def delete_by_pattern(self, pattern: str) -> int:
        names = self.get_keys(pattern)
        return self.delete(*names) if names else 0

    def count(self) -> int:
        return int(self._client.execute("DBSIZE"))

    def count_exists(self, *names: str) -> int:
        """ONE variadic EXISTS per shard owner (Redis + cmd_exists both sum
        args) instead of a round trip per name; tx_groups collapses to a
        single frame on the single-node client."""
        if not names:
            return 0
        return sum(
            int(self._client.execute("EXISTS", *group))
            for group in self._client.tx_groups(list(names)).values()
        )

    def random_key(self) -> Optional[str]:
        k = self._client.execute("RANDOMKEY")
        return None if k is None else bytes(k).decode()

    def expire(self, name: str, seconds: float) -> bool:
        return bool(self._client.execute("PEXPIRE", name, int(seconds * 1000)))

    def remain_time_to_live(self, name: str) -> Optional[float]:
        ms = int(self._client.execute("PTTL", name))
        return None if ms < 0 else ms / 1000.0

    def flushdb(self) -> None:
        self._client.execute("FLUSHALL")

    def flushall(self) -> None:
        self._client.execute("FLUSHALL")


class RemoteLock(RemoteObjectProxy):
    """Lock proxy with the watchdog in the CLIENT process: a dead client
    stops renewing and the server-side lease expires (the reference runs
    scheduleExpirationRenewal in the client JVM for the same reason,
    RedissonBaseLock.java:127-189).

    Contended acquisition PARKS on the lock's unlock channel and retries on
    the push (RedissonLock.java:120-144 + pubsub/LockPubSub.java — the
    reference parks in the client JVM on a pubsub latch); a bounded poll
    remains as the safety net for a publish lost between the failed try and
    the subscribe (and for lease-expiry takeovers, which publish nothing).
    A blocking server-side lock() would pin a server worker thread for the
    whole wait and collide with the command response timeout."""

    _WATCHDOG_LEASE = 30.0
    _SAFETY_POLL = 0.25  # park cap: lost-publish / lease-expiry safety net

    def __init__(self, client: "RemoteRedisson", factory: str, name: str):
        super().__init__(client, factory, name)
        object.__setattr__(self, "_renew_timer", None)
        object.__setattr__(self, "_held_as", None)  # identity captured at acquire

    def _try_once(self, lease_time) -> bool:
        return self._client.objcall(
            self._factory, self._name, "try_lock", (0.0, lease_time), {}
        )

    class _UnlockPark:
        """Subscription to the unlock channel for ONE contended wait: the
        push sets the event; park() waits push-or-timeout."""

        def __init__(self, client, name: str):
            from redisson_tpu.client.objects.lock import unlock_channel

            self._event = _threading.Event()
            self._channel = unlock_channel(name)
            self._pubsub = None
            self._listener = lambda _ch, _msg: self._event.set()
            try:
                self._pubsub = client.pubsub_for(name)
                self._pubsub.subscribe(self._channel, self._listener)
            except Exception:  # noqa: BLE001 — no pubsub? pure polling still works
                self._pubsub = None

        def park(self, timeout: float) -> None:
            self._event.wait(timeout)
            self._event.clear()

        def close(self) -> None:
            if self._pubsub is not None:
                try:
                    self._pubsub.remove_listener(self._channel, self._listener)
                except Exception:  # noqa: BLE001
                    pass

    def lock(self, lease_time=None) -> None:
        if self._try_once(lease_time):
            if lease_time is None:
                self._start_client_watchdog()
            return
        park = self._UnlockPark(self._client, self._name)
        try:
            while not self._try_once(lease_time):
                park.park(self._SAFETY_POLL)
            if lease_time is None:
                self._start_client_watchdog()
        finally:
            park.close()

    def try_lock(self, wait_time: float = 0.0, lease_time=None) -> bool:
        import time as _time

        if self._try_once(lease_time):
            if lease_time is None:
                self._start_client_watchdog()
            return True
        if wait_time <= 0:
            return False
        deadline = _time.monotonic() + wait_time
        park = self._UnlockPark(self._client, self._name)
        try:
            while True:
                if self._try_once(lease_time):
                    if lease_time is None:
                        self._start_client_watchdog()
                    return True
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                park.park(min(self._SAFETY_POLL, remaining))
        finally:
            park.close()

    def unlock(self) -> None:
        self._stop_client_watchdog()
        self._client.objcall(self._factory, self._name, "unlock", (), {})
        # reentrant holds: if this caller still owns the lock after the
        # unlock, renewal must continue (the reference keeps a per-lock
        # renewal entry count, RedissonBaseLock.unscheduleExpirationRenewal)
        if self._client.objcall(
            self._factory, self._name, "renew_lease", (self._WATCHDOG_LEASE,), {}
        ):
            self._start_client_watchdog()

    def force_unlock(self) -> bool:
        self._stop_client_watchdog()
        return self._client.objcall(self._factory, self._name, "force_unlock", (), {})

    def _start_client_watchdog(self) -> None:
        self._stop_client_watchdog()
        # renewal fires on pool threads, whose get_ident() differs from the
        # acquiring thread — capture the acquirer's identity NOW and renew
        # under it, or the server would refuse every tick
        held_as = self._client.caller_id()
        object.__setattr__(self, "_held_as", held_as)
        timer, pool = _client_renewal_infra()

        def renew():
            try:
                still_held = self._client.objcall(
                    self._factory, self._name, "renew_lease",
                    (self._WATCHDOG_LEASE,), {}, caller=held_as,
                )
            except Exception:  # noqa: BLE001 — connection loss ends renewal
                still_held = False
            if still_held and self.__dict__.get("_held_as") == held_as:
                t = timer.new_timeout(
                    lambda: pool.submit(renew), self._WATCHDOG_LEASE / 3
                )
                object.__setattr__(self, "_renew_timer", t)

        # the wheel tick only ENQUEUES the renewal; the RPC runs on the pool
        # (a network call must never block the shared wheel thread)
        t = timer.new_timeout(lambda: pool.submit(renew), self._WATCHDOG_LEASE / 3)
        object.__setattr__(self, "_renew_timer", t)

    def _stop_client_watchdog(self) -> None:
        t = self.__dict__.get("_renew_timer")
        object.__setattr__(self, "_held_as", None)
        if t is not None:
            t.cancel()
            object.__setattr__(self, "_renew_timer", None)


# factories served via OBJCALL generic proxies (full L5'/L6' surface)
_GENERIC_FACTORIES = {
    "get_map", "get_map_cache", "get_set", "get_set_cache", "get_sorted_set",
    "get_lex_sorted_set", "get_scored_sorted_set", "get_list", "get_queue",
    "get_deque", "get_blocking_queue", "get_blocking_deque", "get_priority_queue",
    "get_priority_deque", "get_priority_blocking_queue", "get_priority_blocking_deque",
    "get_ring_buffer", "get_transfer_queue", "get_list_multimap", "get_set_multimap",
    "get_list_multimap_cache", "get_set_multimap_cache",
    "get_atomic_long", "get_atomic_double", "get_id_generator", "get_lock",
    "get_fair_lock", "get_spin_lock", "get_fenced_lock", "get_semaphore",
    "get_count_down_latch", "get_rate_limiter", "get_permit_expirable_semaphore",
    "get_stream", "get_time_series",
    "get_geo", "get_binary_stream", "get_json_bucket", "get_buckets",
    "get_bounded_blocking_queue", "get_sharded_bloom_filter_array",
    "get_sharded_hll_array", "get_sharded_bit_set",
}


class RemoteLocalCachedMap:
    """RLocalCachedMap over the wire: a client-side near cache fed by the
    shared invalidation channel (`redisson_local_cache:{name}`).

    Protocol interop with the embedded handle (client/objects/localcache.py):
    messages are (kind, cache_id, payload) tuples; this handle MUTATES the
    plain map and PUBLISHES its own messages carrying its own cache_id — so
    originator exclusion works exactly like the reference's excludedId scheme
    (a client's own writes never evict its own fresh cache entries).  Both
    the subscription and the mutations route by the MAP NAME's slot, so on a
    cluster the invalidation feed lives on the shard that owns the data.
    The map-key codec MUST match the server's default codec (keys align by
    encoded bytes).
    """

    def __init__(self, client, name: str, options=None, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.localcache import (
            LocalCachedMapOptions,
            SyncStrategy,
            _LocalCache,
        )

        self._client = client
        self.name = name
        self._opts = options or LocalCachedMapOptions.defaults()
        self._codec = codec or DEFAULT_CODEC
        self._cache = _LocalCache(self._opts)
        self._cache_id = uuid.uuid4().hex
        self._disabled: set = set()  # active tx-commit disable requests
        self._channel = f"redisson_local_cache:{name}"
        # mutations ride the PLAIN map: this handle owns its own broadcasts
        self._proxy = RemoteObjectProxy(client, "get_map", name)
        self._sync_strategy = self._opts.sync_strategy
        # TRACKING mode (ISSUE 7): coherence rides the server-assisted
        # invalidation plane — no topic subscription, no write broadcasts.
        # Every OBJCALL read registers the map name on its (tracked) data
        # connection server-side; any write by anyone pushes an invalidate
        # down the facade's feed, which clears this handle's cache.
        self._tracking_mode = self._sync_strategy == SyncStrategy.TRACKING
        self._sync = (
            self._sync_strategy != SyncStrategy.NONE and not self._tracking_mode
        )
        # generation counter: a fetch only populates the cache if no
        # invalidation arrived while it was in flight (the wire analog of the
        # embedded handle's read+populate under the record lock)
        self._gen = 0
        self.hits = 0
        self.misses = 0
        self._pubsub = None
        self._tracking_listener = None
        if self._tracking_mode:
            plane = getattr(client, "tracking", None)
            if plane is None:
                raise RuntimeError(
                    "SyncStrategy.TRACKING requires the facade's tracking "
                    "plane: call client.enable_tracking() first"
                )
            self._tracking_plane = plane
            self._tracking_listener = plane.add_name_listener(
                name, self._on_tracking_invalidate
            )
        elif self._sync:
            # subscribe on the shard that owns the MAP (not the channel
            # string): that is where OBJCALL mutations execute and publish
            self._pubsub = client.pubsub_for(name)
            self._pubsub.subscribe(self._channel, self._on_wire_sync)

    def _on_tracking_invalidate(self, _name) -> None:
        # record-level granularity: any write to the map drops the whole
        # near copy (the plane cannot see which entry changed); _gen guards
        # in-flight fetches exactly like the topic path
        self._gen += 1
        self._cache.clear()

    # -- invalidation feed ----------------------------------------------------

    def _on_wire_sync(self, _channel: str, payload) -> None:
        from redisson_tpu.net.safe_pickle import safe_loads

        try:
            msg = safe_loads(bytes(payload)) if isinstance(payload, (bytes, bytearray)) else payload
        except Exception:  # noqa: BLE001 — unknown frame: drop all, stay safe
            self._gen += 1
            self._cache.clear()
            return
        kind, sender = msg[0], msg[1]
        if sender == self._cache_id:
            return  # own write (excludedId scheme)
        self._gen += 1
        if kind == "inv":
            for ek in msg[2]:
                self._cache.invalidate(ek)
        elif kind == "upd":
            for ek, ev in msg[2]:
                self._cache.put(ek, self._codec.decode_map_value(ev))
        elif kind == "clear":
            self._cache.clear()
        elif kind == "disable":
            # transaction commit handshake (LocalCachedMapDisable analog)
            self._disabled.add(sender)
            self._cache.clear()
            t = _threading.Timer(30.0, self._disabled.discard, (sender,))
            t.daemon = True
            t.start()  # failsafe: committer died before the enable
        elif kind == "enable":
            self._disabled.discard(sender)
            self._cache.clear()

    def _broadcast(self, kind: str, payload) -> None:
        if not self._sync:
            return
        from redisson_tpu.client.objects.localcache import SyncStrategy

        if kind == "upd" and self._sync_strategy != SyncStrategy.UPDATE:
            kind, payload = "inv", [ek for ek, _ in payload]
        blob = pickle.dumps((kind, self._cache_id, payload), protocol=4)
        # route by the MAP name, not the channel string: subscribers attached
        # on the map's slot owner (see __init__), and the channel's own slot
        # differs from the map's
        self._client.publish_for(self.name, self._channel, blob)

    def _ek(self, key) -> bytes:
        return self._codec.encode_map_key(key)

    # -- reads (near cache first) ---------------------------------------------

    def get(self, key):
        if self._disabled:
            # tx-commit window: read through, never serve or populate
            return self._proxy.get(key)
        ek = self._ek(key)
        hit, value = self._cache.get(ek)
        if hit:
            self.hits += 1
            return value
        self.misses += 1
        gen = self._gen
        value = self._proxy.get(key)
        if value is not None and self._gen == gen and not self._disabled:
            # no invalidation raced the fetch: safe to populate
            self._cache.put(ek, value)
        return value

    def get_all(self, keys) -> Dict:
        if self._disabled:
            return self._proxy.get_all(list(keys))
        out, missing = {}, []
        for k in keys:
            hit, v = self._cache.get(self._ek(k))
            if hit:
                self.hits += 1
                out[k] = v
            else:
                self.misses += 1
                missing.append(k)
        if missing:
            gen = self._gen
            fetched = self._proxy.get_all(missing)
            if self._gen == gen and not self._disabled:
                for k, v in fetched.items():
                    self._cache.put(self._ek(k), v)
            out.update(fetched)
        return out

    # -- transaction commit handshake ----------------------------------------

    def tx_disable(self, req_id: str) -> None:
        """Near-cache disable broadcast for a transaction commit
        (LocalCachedMapDisable analog); sender = the REQUEST id so no
        subscriber — including this handle — is excluded."""
        self._disabled.add(req_id)
        self._cache.clear()
        if self._sync:
            blob = pickle.dumps(("disable", req_id, None), protocol=4)
            self._client.publish_for(self.name, self._channel, blob)

    def tx_enable(self, req_id: str) -> None:
        self._disabled.discard(req_id)
        self._cache.clear()
        if self._sync:
            blob = pickle.dumps(("enable", req_id, None), protocol=4)
            self._client.publish_for(self.name, self._channel, blob)

    def cached_size(self) -> int:
        return len(self._cache)

    # -- writes (mutate shared map, update own cache, notify peers) -----------

    def _seed_own_write(self) -> bool:
        """May a write populate its own cache?  TRACKING mode: NO — without
        NOLOOP the server pops (or, for a write with no prior read, never
        held) our registration when it applies the write, so nothing
        guarantees a later foreign write ever invalidates the seed; WITH
        NOLOOP the self-pushes that would order concurrent own-writes are
        suppressed, and the map-wide ``_gen`` guard cannot tell two own
        writers apart — the loser of the server-side race could cache its
        overwritten value with nothing left to correct it (review fix; the
        tracked-handle seed in TrackedBucket.set survives this because the
        NearCache generation is per NAME and invalidate drops entries).
        Topic mode seeds like the reference (excludedId scheme)."""
        return not self._tracking_mode

    def _own_invalidate(self, eks) -> None:
        """Drop our local copies after an own write, bumping ``_gen`` FIRST:
        a concurrent get() that fetched the PRE-write value must fail its
        populate guard, or it would re-cache the stale value right after
        this invalidate — and under tracking+NOLOOP the suppressed
        self-push would never correct it (review fix)."""
        self._gen += 1
        for ek in eks:
            self._cache.invalidate(ek)

    def _invalidate_on_error(self, eks) -> None:
        """A raised wire write may still have APPLIED (lost reply) — drop
        the local copies: under tracking+NOLOOP the self-push is suppressed
        and in topic mode the broadcast never went out, so nothing else
        would ever correct a stale cached value."""
        self._own_invalidate(eks)

    def put(self, key, value):
        # gen-guarded like get(): an invalidation landing between the wire
        # write and the populate (our own push, or a foreign writer's)
        # voids the populate instead of caching over it
        gen = self._gen
        seed = self._seed_own_write()
        try:
            old = self._proxy.put(key, value)
        except BaseException:
            self._invalidate_on_error([self._ek(key)])
            raise
        ek = self._ek(key)
        if not seed:
            self._own_invalidate([ek])
        elif self._gen == gen and not self._disabled:
            self._cache.put(ek, value)
        self._broadcast("upd", [(ek, self._codec.encode_map_value(value))])
        return old

    def fast_put(self, key, value) -> bool:
        gen = self._gen
        seed = self._seed_own_write()
        try:
            created = self._proxy.fast_put(key, value)
        except BaseException:
            self._invalidate_on_error([self._ek(key)])
            raise
        ek = self._ek(key)
        if not seed:
            self._own_invalidate([ek])
        elif self._gen == gen and not self._disabled:
            self._cache.put(ek, value)
        self._broadcast("upd", [(ek, self._codec.encode_map_value(value))])
        return created

    def put_all(self, entries: Dict) -> None:
        gen = self._gen
        seed = self._seed_own_write()
        try:
            self._proxy.put_all(entries)
        except BaseException:
            self._invalidate_on_error([self._ek(k) for k in entries])
            raise
        payload = []
        populate = seed and self._gen == gen and not self._disabled
        if not seed:
            self._own_invalidate([self._ek(k) for k in entries])
        for k, v in entries.items():
            ek = self._ek(k)
            if populate:
                self._cache.put(ek, v)
            payload.append((ek, self._codec.encode_map_value(v)))
        self._broadcast("upd", payload)

    def remove(self, key):
        ek = self._ek(key)
        try:
            old = self._proxy.remove(key)
        finally:
            self._own_invalidate([ek])
        self._broadcast("inv", [ek])
        return old

    def fast_remove(self, *keys) -> int:
        eks = [self._ek(k) for k in keys]
        try:
            n = self._proxy.fast_remove(*keys)
        finally:
            self._own_invalidate(eks)
        self._broadcast("inv", eks)
        return n

    def clear(self) -> None:
        try:
            self._proxy.clear()
        finally:
            self._gen += 1  # void in-flight populates (see _own_invalidate)
            self._cache.clear()
        if self._sync:
            blob = pickle.dumps(("clear", self._cache_id), protocol=4)
            self._client.publish_for(self.name, self._channel, blob)

    def destroy(self) -> None:
        """Detach the invalidation listener (RObject.destroy parity) — keep
        the shared channel alive for other handles on the same connection."""
        if self._pubsub is not None:
            self._pubsub.remove_listener(self._channel, self._on_wire_sync)
            self._pubsub = None
        if self._tracking_listener is not None:
            self._tracking_plane.remove_name_listener(
                self.name, self._tracking_listener
            )
            self._tracking_listener = None
        self._cache.clear()

    def __getattr__(self, method: str):
        # everything else (size, contains_key, read_all_keys, ...) rides the
        # plain OBJCALL proxy with no near-cache involvement
        return getattr(self._proxy, method)


class RemoteSurface:
    """Handle-factory surface shared by the single-node client and the
    cluster client: every factory only talks through the transport seam
    (execute / execute_many / objcall / pubsub_for / caller_id), so the same
    handle classes ride either routing."""

    # the CLIENT TRACKING near-cache plane (tracking/nearcache.py), None
    # until enable_tracking() arms it
    tracking = None

    def enable_tracking(self, **kw) -> "Any":
        """Arm server-assisted client tracking for this facade: every pooled
        data connection redirects its invalidation stream to the node's
        dedicated feed connection, and the returned ``ClientTracking``
        plane's handles (``get_bucket``/``get_map``/``get_set``/
        ``get_bloom_filter``) answer repeat reads from a process-local
        near cache until someone writes.  Idempotent (kwargs of the first
        call win) — including under concurrent first calls: construction
        arms feeds and registers invalidation listeners, so a racing loser
        plane would leak its listeners for the process lifetime."""
        plane = self.__dict__.get("tracking")
        if plane is None:
            with _tracking_enable_lock:
                plane = self.__dict__.get("tracking")
                if plane is None:
                    from redisson_tpu.tracking.nearcache import ClientTracking

                    plane = self.__dict__["tracking"] = ClientTracking(self, **kw)
        return plane

    def caller_id(self) -> str:
        """This thread's synchronizer identity (uuid:threadId — the
        reference's LockName, RedissonBaseLock.getLockName)."""
        import threading as _threading
        import uuid as _uuid

        if not hasattr(self, "_client_uuid"):
            object.__setattr__(self, "_client_uuid", _uuid.uuid4().hex)
        return f"{self._client_uuid}:{_threading.get_ident()}"

    def objcall(
        self,
        factory: str,
        name: str,
        method: str,
        args: tuple,
        kwargs: dict,
        caller: Optional[str] = None,
        codec: Optional[Codec] = None,
    ) -> Any:
        payload = pickle.dumps((args, kwargs))
        frame = [
            "OBJCALL", factory, name, method, payload, caller or self.caller_id(),
        ]
        if codec is not None:
            frame.append(pickle.dumps(codec))
        reply = self.execute(*frame)
        return _unwrap(reply, self)

    def objcall_many(
        self, ops: List[Tuple], caller: Optional[str] = None,
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """MANY object ops in ONE wire frame + ONE pickle (OBJCALLM — the
        CommandBatchService flush for the generic object surface).  ops =
        [(factory, name, method, args, kwargs[, codec_blob]), ...]; returns
        results aligned with ops, exceptions as values.  The cluster client
        overrides this with per-shard grouping."""
        payload = pickle.dumps([tuple(op) for op in ops])
        reply = self.execute(
            "OBJCALLM", payload, caller or self.caller_id(), timeout=timeout
        )
        return _unwrap_many(reply, self)

    def objcall_many_batch(
        self, ops: List[Tuple], atomic: bool = False, timeout: Optional[float] = None
    ) -> List[Any]:
        """RemoteBatch's generic flush: OBJCALLM, or OBJCALLMA for atomic
        groups (server runs the whole frame under engine.locked_many — the
        MULTI/EXEC analog).  Single-node surface: one frame either way.
        Ops may carry a trailing Codec object; it ships pickled per the
        OBJCALL codec-frame contract."""
        wire_ops = [self._normalize_batch_op(op) for op in ops]
        cmd = "OBJCALLMA" if atomic else "OBJCALLM"
        payload = pickle.dumps(wire_ops)
        reply = self.execute(cmd, payload, self.caller_id(), timeout=timeout)
        return _unwrap_many(reply, self)

    @staticmethod
    def _normalize_batch_op(op: Tuple) -> Tuple:
        op = tuple(op)
        if len(op) > 5:
            codec = op[5]
            if codec is None:
                return op[:5]
            return op[:5] + (pickle.dumps(codec),)
        return op

    def sync_replication(self, names, timeout: Optional[float] = None) -> None:
        """BatchOptions.syncSlaves analog (the WAIT command role): force the
        replication stream to flush before returning, so a replica read
        after the batch sees its writes.  Single-node surface: one
        REPLFLUSH; the cluster client overrides per touched shard."""
        self.execute("REPLFLUSH", timeout=timeout)

    def replication_state(self, timeout: Optional[float] = None) -> dict:
        """Parsed REPLSTATE (ISSUE 17): {role, applied_offset, staleness_ms,
        view_epoch}.  staleness_ms is time since the node's last applied
        replication push/heartbeat (-1 = never synced); a master answers 0.
        The bounded-staleness read plane's observability probe — soak and
        bench harvest replica lag through this."""
        role, offset, stale_ms, epoch = self.execute(
            "REPLSTATE", timeout=timeout
        )
        return {
            "role": role.decode() if isinstance(role, (bytes, bytearray))
            else str(role),
            "applied_offset": int(offset),
            "staleness_ms": int(stale_ms),
            "view_epoch": int(epoch),
        }

    # -- transactions (transaction/RedissonTransaction.java over the wire) ----

    def create_transaction(self, timeout: Optional[float] = None, options=None):
        from redisson_tpu.services.transactions import (
            RemoteTransaction,
            TransactionOptions,
        )

        if options is None:
            options = TransactionOptions.defaults()
        if timeout is not None:
            options.timeout = timeout
        return RemoteTransaction(self, options)

    def tx_groups(self, names: List[str]) -> Dict[Any, List[str]]:
        """Commit grouping seam: which TXEXEC frame carries which names.
        Single node = one frame; the cluster client groups per slot owner."""
        return {None: list(names)}

    def txexec(
        self, group_key, versions: Dict[str, int], ops: List[Tuple],
        timeout: Optional[float] = None,
    ) -> List[Any]:
        """One atomic commit frame: version preconditions + buffered ops
        under the server's locked_many (see registry cmd_txexec)."""
        reply = self.execute(
            "TXEXEC", pickle.dumps(versions), pickle.dumps(ops),
            self.caller_id(), timeout=timeout,
        )
        return _unwrap_many(reply, self)

    # -- hot-path handles ----------------------------------------------------

    def get_bloom_filter(self, name: str, codec: Optional[Codec] = None) -> "RemoteBloomFilter":
        return RemoteBloomFilter(self, self._map_name(name), codec)

    def get_bloom_filter_array(self, name: str) -> "RemoteBloomFilterArray":
        return RemoteBloomFilterArray(self, self._map_name(name))

    def get_hyper_log_log(self, name: str, codec: Optional[Codec] = None) -> "RemoteHyperLogLog":
        return RemoteHyperLogLog(self, self._map_name(name), codec)

    def get_hyper_log_log_array(self, name: str) -> "RemoteHyperLogLogArray":
        return RemoteHyperLogLogArray(self, self._map_name(name))

    def get_bit_set(self, name: str) -> "RemoteBitSet":
        return RemoteBitSet(self, self._map_name(name))

    def get_bucket(self, name: str, codec: Optional[Codec] = None) -> "RemoteBucket":
        return RemoteBucket(self, self._map_name(name), codec)

    def get_buckets(self, codec: Optional[Codec] = None) -> "RemoteBuckets":
        return RemoteBuckets(self, codec)

    def get_topic(self, name: str, codec: Optional[Codec] = None) -> "RemoteTopic":
        return RemoteTopic(self, self._map_name(name), codec)

    def get_local_cached_map(
        self, name: str, codec: Optional[Codec] = None, options=None
    ) -> "RemoteLocalCachedMap":
        return RemoteLocalCachedMap(self, self._map_name(name), options=options, codec=codec)

    def create_batch(self, options: Optional["BatchOptions"] = None) -> "RemoteBatch":
        return RemoteBatch(self, options)

    def add_connection_listener(self, listener):
        """Register for edge-triggered per-node connect/disconnect events
        (ConnectionEventsHub.java); both facades own an events hub."""
        return self.events_hub.add_listener(listener)

    def remove_connection_listener(self, listener) -> None:
        self.events_hub.remove_listener(listener)

    def get_elements_subscribe_service(self):
        """Resilient blocking-consumer subscriptions (ElementsSubscribeService
        analog): take-loops that re-subscribe across failovers.  setdefault
        keeps the init race-safe: two racing callers must share ONE service
        or the loser's subscription registry becomes unreachable."""
        from redisson_tpu.services.elements import ElementsSubscribeService

        return self.__dict__.setdefault(
            "_elements_service", ElementsSubscribeService(self)
        )

    def get_keys(self) -> "RemoteKeys":
        return RemoteKeys(self)

    def get_live_object_service(self):
        """RLiveObjectService over the wire: the service drives this client's
        own object factories, so every live-object key (map, index sets,
        score sets — all {Cls:...}-hashtagged) routes per key exactly like
        the reference's live objects against a cluster."""
        from redisson_tpu.services.liveobject import LiveObjectService

        return LiveObjectService(self)

    # -- generic surface -----------------------------------------------------

    _LOCK_FACTORIES = {"get_lock", "get_fair_lock", "get_spin_lock", "get_fenced_lock"}

    def _map_name(self, name: str) -> str:
        """NameMapper on the NETWORKED surface: remote handles carry the
        STORED key so OBJCALL payloads, blob fast paths, and pubsub channel
        names (lock unlock channels, invalidation topics) all agree with
        what the server persists."""
        mapper = getattr(getattr(self, "config", None), "name_mapper", None)
        return mapper.map(name) if mapper is not None else name

    def __getattr__(self, factory: str):
        if factory in self._LOCK_FACTORIES:

            def make_lock(name: str, *_a, **_k) -> RemoteLock:
                return RemoteLock(self, factory, self._map_name(name))

            return make_lock
        if factory in _GENERIC_FACTORIES:

            def make(name: str, codec: Optional[Codec] = None, *_a, **_k) -> RemoteObjectProxy:
                return RemoteObjectProxy(self, factory, self._map_name(name), codec)

            return make
        raise AttributeError(factory)


class RemoteRedisson(RemoteSurface):
    """Client-mode facade (the RedissonClient role for a remote data plane)."""

    def __init__(self, address: str, config=None, **node_kw):
        from redisson_tpu.config import Config

        self.config = config or Config()
        ssc = self.config.single_server_config
        kw: Dict[str, Any] = {}
        if ssc is not None:
            kw.update(
                password=ssc.password,
                username=ssc.username,
                client_name=ssc.client_name,
                pool_size=ssc.connection_pool_size,
                min_idle=ssc.connection_minimum_idle_size,
                timeout=ssc.timeout,
                connect_timeout=ssc.connect_timeout,
                retry_attempts=ssc.retry_attempts,
                retry_interval=ssc.retry_interval,
                ping_interval=ssc.ping_connection_interval,
                ssl_context=ssc.build_ssl_context(),
            )
        kw.update(node_kw)
        # config-level SPIs ride every connection of this facade
        kw.setdefault("credentials_resolver", self.config.credentials_resolver)
        kw.setdefault("command_mapper", self.config.command_mapper)
        # ConnectionEventsHub (connection/ConnectionEventsHub.java):
        # edge-triggered connect/disconnect fan-out for this facade
        from redisson_tpu.net.detectors import ConnectionEventsHub

        self.events_hub = kw.setdefault("events_hub", ConnectionEventsHub())
        self.node = NodeClient(address, **kw)

    @classmethod
    def create(cls, config) -> "RemoteRedisson":
        ssc = config.use_single_server()
        return cls(ssc.address, config=config)

    # -- transport seam (handles call these; ClusterRedisson overrides with
    #    slot routing — the CommandAsyncExecutor boundary of the wire client)

    def execute(self, *args, timeout: Optional[float] = None) -> Any:
        return self.node.execute(*args, timeout=timeout)

    def execute_many(self, commands, timeout: Optional[float] = None):
        return self.node.execute_many(commands, timeout=timeout)

    def pubsub_for(self, name: str):
        """Pubsub connection serving `name`'s channel (single node: the one)."""
        return self.node.pubsub()

    def publish_for(self, routing_name: str, channel, payload) -> int:
        """Publish on the node that serves `routing_name`'s subscriptions.

        Must pair with pubsub_for: server pubsub hubs are node-local, so a
        publish landing on any other node is silently lost.  Single node:
        trivially the one node; the cluster override routes by slot."""
        return int(self.execute("PUBLISH", channel, payload) or 0)

    # -- admin ---------------------------------------------------------------

    def ping(self) -> bool:
        return self.node.execute("PING") in (b"PONG", "PONG")

    def info(self) -> str:
        return bytes(self.node.execute("INFO")).decode()

    def shutdown(self) -> None:
        # cancel element subscriptions FIRST: their daemon loops would
        # otherwise retry the closed transport forever
        svc = getattr(self, "_elements_service", None)
        if svc is not None:
            svc.shutdown()
        plane = self.__dict__.get("tracking")
        if plane is not None:
            plane.close()
        self.node.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
