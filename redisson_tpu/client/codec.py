"""Codec SPI: pluggable value serialization, mirroring the reference's
codec layer (``org/redisson/client/codec/Codec.java``, ``BaseCodec.java`` and
the ~20 implementations under ``org/redisson/codec/`` — SURVEY.md §2.4).

A codec turns user values into bytes at the object-handle boundary; sketch
objects additionally feed those bytes to the vectorized hash (the reference
does exactly this: codec encode -> HighwayHash, RedissonBloomFilter.java:90-97).

Default codec is JSON (reference default: JsonJacksonCodec), with a typed
fallback to pickle for non-JSON-able values (reference's JDK-serialization
codec analog).  Map-key vs map-value codecs can differ via CompositeCodec.
Compression wrappers (Zlib here; LZ4/Snappy in the reference) nest any inner
codec.
"""
from __future__ import annotations

import json
import pickle
import struct
import zlib
from typing import Any


class Codec:
    """Encoder/decoder pair. Subclasses must be stateless & thread-safe."""

    name = "codec"

    def encode(self, value: Any) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes) -> Any:
        raise NotImplementedError

    # map key/value split points (CompositeCodec overrides)
    def encode_map_key(self, value: Any) -> bytes:
        return self.encode(value)

    def decode_map_key(self, data: bytes) -> Any:
        return self.decode(data)

    def encode_map_value(self, value: Any) -> bytes:
        return self.encode(value)

    def decode_map_value(self, data: bytes) -> Any:
        return self.decode(data)


class JsonCodec(Codec):
    """Default codec (parity: codec/JsonJacksonCodec.java).

    JSON with a one-byte tag; values JSON can't express fall back to pickle
    (tag 'P') so arbitrary Python objects still round-trip, like the
    reference's default typing support.
    """

    name = "json"

    def encode(self, value: Any) -> bytes:
        try:
            return b"J" + json.dumps(value, separators=(",", ":"), sort_keys=True).encode()
        except (TypeError, ValueError):
            return b"P" + pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def decode(self, data: bytes) -> Any:
        tag, body = data[:1], data[1:]
        if tag == b"J":
            return json.loads(body)
        if tag == b"P":
            return pickle.loads(body)
        raise ValueError(f"unknown JsonCodec tag {tag!r}")


class PickleCodec(Codec):
    """Binary python-native codec (parity: codec/SerializationCodec.java)."""

    name = "pickle"

    def encode(self, value: Any) -> bytes:
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def decode(self, data: bytes) -> Any:
        return pickle.loads(data)


class StringCodec(Codec):
    """UTF-8 strings (parity: client/codec/StringCodec.java)."""

    name = "string"

    def encode(self, value: Any) -> bytes:
        if isinstance(value, bytes):
            return value
        return str(value).encode()

    def decode(self, data: bytes) -> Any:
        return data.decode()


class BytesCodec(Codec):
    """Raw bytes passthrough (parity: client/codec/ByteArrayCodec.java)."""

    name = "bytes"

    def encode(self, value: Any) -> bytes:
        if isinstance(value, (bytes, bytearray, memoryview)):
            return bytes(value)
        raise TypeError(f"BytesCodec requires bytes, got {type(value)}")

    def decode(self, data: bytes) -> Any:
        return data


class LongCodec(Codec):
    """Signed 64-bit integers (parity: client/codec/LongCodec.java)."""

    name = "long"

    def encode(self, value: Any) -> bytes:
        return struct.pack("<q", int(value))

    def decode(self, data: bytes) -> Any:
        return struct.unpack("<q", data)[0]


class DoubleCodec(Codec):
    """Float64 (parity: client/codec/DoubleCodec.java)."""

    name = "double"

    def encode(self, value: Any) -> bytes:
        return struct.pack("<d", float(value))

    def decode(self, data: bytes) -> Any:
        return struct.unpack("<d", data)[0]


class CompositeCodec(Codec):
    """Different codecs for map key / map value (parity: codec/CompositeCodec.java)."""

    name = "composite"

    def __init__(self, map_key_codec: Codec, map_value_codec: Codec, value_codec: Codec | None = None):
        self.key_codec = map_key_codec
        self.value_codec_ = map_value_codec
        self.plain = value_codec or map_value_codec

    def encode(self, value):
        return self.plain.encode(value)

    def decode(self, data):
        return self.plain.decode(data)

    def encode_map_key(self, value):
        return self.key_codec.encode(value)

    def decode_map_key(self, data):
        return self.key_codec.decode(data)

    def encode_map_value(self, value):
        return self.value_codec_.encode(value)

    def decode_map_value(self, data):
        return self.value_codec_.decode(data)


class ZlibCodec(Codec):
    """Compression wrapper around an inner codec (parity: codec/LZ4Codec.java /
    SnappyCodecV2.java — wrap-any-codec pattern; zlib is the in-stdlib stand-in)."""

    name = "zlib"

    def __init__(self, inner: Codec | None = None, level: int = 1):
        self.inner = inner or JsonCodec()
        self.level = level

    def encode(self, value):
        return zlib.compress(self.inner.encode(value), self.level)

    def decode(self, data):
        return self.inner.decode(zlib.decompress(data))


try:  # optional, gated: msgpack is not in the baked image
    import msgpack  # type: ignore

    class MsgPackCodec(Codec):  # pragma: no cover - optional dep
        name = "msgpack"

        def encode(self, value):
            return msgpack.packb(value)

        def decode(self, data):
            return msgpack.unpackb(data)

except ImportError:  # pragma: no cover
    MsgPackCodec = None  # type: ignore


class Bz2Codec(Codec):
    """bz2 compression wrapper (higher ratio / slower than Zlib — the LZ4-vs-
    Snappy trade of the reference's two compression codecs)."""

    name = "bz2"

    # no module-object attributes: codecs must PICKLE (they travel with
    # OBJCALL frames per the getMap(name, codec) contract)
    def __init__(self, inner: Codec | None = None):
        self.inner = inner or JsonCodec()

    def encode(self, value):
        import bz2

        return bz2.compress(self.inner.encode(value))

    def decode(self, data):
        import bz2

        return self.inner.decode(bz2.decompress(data))


class LzmaCodec(Codec):
    """xz/lzma compression wrapper."""

    name = "lzma"

    def __init__(self, inner: Codec | None = None):
        self.inner = inner or JsonCodec()

    def encode(self, value):
        import lzma

        return lzma.compress(self.inner.encode(value))

    def decode(self, data):
        import lzma

        return self.inner.decode(lzma.decompress(data))


class Lz4Codec(Codec):
    """LZ4 block compression wrapper — the reference's recommended
    compression codec (codec/LZ4Codec.java).  Backed by the pure-python
    block implementation in utils/lz4block.py (standard block format:
    interoperable with any LZ4 block decoder); the frame is a 4-byte
    BIG-ENDIAN uncompressed length + the block — LZ4Codec.java writes the
    length with Netty ``ByteBuf.writeInt`` (network byte order), so the
    frame is byte-compatible with reference-written values."""

    name = "lz4"

    def __init__(self, inner: Codec | None = None):
        self.inner = inner or JsonCodec()

    def encode(self, value):
        from redisson_tpu.utils import lz4block

        raw = self.inner.encode(value)
        return len(raw).to_bytes(4, "big") + lz4block.compress(raw)

    def decode(self, data):
        from redisson_tpu.utils import lz4block

        be = int.from_bytes(data[:4], "big")
        try:
            raw = lz4block.decompress(data[4:], be)
        except ValueError as e:
            # at-rest compat: frames written before the wire-compat fix
            # carried the length little-endian; exactly one byte order
            # passes the decompressor's size check, so the retry is
            # unambiguous.  A genuinely corrupt frame surfaces the ORIGINAL
            # (big-endian, current-format) error, never the retry's.
            le = int.from_bytes(data[:4], "little")
            if le == be:
                raise
            try:
                raw = lz4block.decompress(data[4:], le)
            except ValueError:
                raise e from None
        return self.inner.decode(raw)


class ProtobufCodec(Codec):
    """Protocol-buffers codec for one message class (parity:
    codec/ProtobufCodec.java — values must be instances of `message_cls`)."""

    name = "protobuf"

    def __init__(self, message_cls):
        self.message_cls = message_cls

    def encode(self, value):
        if not isinstance(value, self.message_cls):
            raise TypeError(
                f"ProtobufCodec({self.message_cls.__name__}) cannot encode {type(value).__name__}"
            )
        return value.SerializeToString()

    def decode(self, data):
        msg = self.message_cls()
        msg.ParseFromString(bytes(data))
        return msg


class CborCodec(Codec):
    """CBOR binary codec (parity: codec/CborJacksonCodec.java) — a pure
    RFC 8949 core-type subset (int, bytes, str, list, dict, bool, None,
    float64), self-contained because the image carries no cbor library.
    Interoperable with any standards-compliant CBOR decoder for these
    types."""

    name = "cbor"

    def encode(self, value: Any) -> bytes:
        out = bytearray()
        self._enc(value, out)
        return bytes(out)

    @staticmethod
    def _head(major: int, arg: int, out: bytearray) -> None:
        if arg < 24:
            out.append((major << 5) | arg)
        elif arg < 0x100:
            out.append((major << 5) | 24); out += arg.to_bytes(1, "big")
        elif arg < 0x10000:
            out.append((major << 5) | 25); out += arg.to_bytes(2, "big")
        elif arg < 0x100000000:
            out.append((major << 5) | 26); out += arg.to_bytes(4, "big")
        else:
            out.append((major << 5) | 27); out += arg.to_bytes(8, "big")

    def _enc(self, v: Any, out: bytearray) -> None:
        if v is False:
            out.append(0xF4)
        elif v is True:
            out.append(0xF5)
        elif v is None:
            out.append(0xF6)
        elif isinstance(v, int):
            if not (-(1 << 64) <= v < (1 << 64)):
                raise TypeError(
                    "CBOR integer out of uint64 argument range "
                    "(RFC 8949 bignum tags are not supported)"
                )
            if v >= 0:
                self._head(0, v, out)
            else:
                self._head(1, -1 - v, out)
        elif isinstance(v, float):
            out.append(0xFB); out += struct.pack(">d", v)
        elif isinstance(v, (bytes, bytearray, memoryview)):
            b = bytes(v); self._head(2, len(b), out); out += b
        elif isinstance(v, str):
            b = v.encode(); self._head(3, len(b), out); out += b
        elif isinstance(v, (list, tuple)):
            self._head(4, len(v), out)
            for item in v:
                self._enc(item, out)
        elif isinstance(v, dict):
            self._head(5, len(v), out)
            for k, val in v.items():
                self._enc(k, out); self._enc(val, out)
        else:
            raise TypeError(f"CborCodec cannot encode {type(v).__name__}")

    def decode(self, data: bytes) -> Any:
        try:
            v, i = self._dec(bytes(data), 0)
        except (IndexError, struct.error):
            raise ValueError("truncated CBOR input") from None
        if i != len(data):
            raise ValueError("trailing bytes after CBOR value")
        return v

    @staticmethod
    def _arg(data: bytes, i: int):
        info = data[i] & 0x1F
        i += 1
        if info < 24:
            return info, i
        n = {24: 1, 25: 2, 26: 4, 27: 8}.get(info)
        if n is None:
            raise ValueError(f"unsupported CBOR additional info {info}")
        if i + n > len(data):  # a short slice would silently mis-decode
            raise ValueError("truncated CBOR input")
        return int.from_bytes(data[i:i + n], "big"), i + n

    def _dec(self, data: bytes, i: int):
        major = data[i] >> 5
        if major == 7:
            b = data[i]
            if b == 0xF4:
                return False, i + 1
            if b == 0xF5:
                return True, i + 1
            if b == 0xF6:
                return None, i + 1
            if b == 0xFB:
                return struct.unpack(">d", data[i + 1:i + 9])[0], i + 9
            raise ValueError(f"unsupported CBOR simple/float byte {b:#x}")
        arg, i = self._arg(data, i)
        if major == 0:
            return arg, i
        if major == 1:
            return -1 - arg, i
        if major in (2, 3):
            if i + arg > len(data):
                raise ValueError("truncated CBOR input")
            chunk = data[i:i + arg]
            return (chunk if major == 2 else chunk.decode()), i + arg
        if major == 4:
            out = []
            for _ in range(arg):
                v, i = self._dec(data, i)
                out.append(v)
            return out, i
        if major == 5:
            d = {}
            for _ in range(arg):
                k, i = self._dec(data, i)
                v, i = self._dec(data, i)
                d[k] = v
            return d, i
        raise ValueError(f"unsupported CBOR major type {major}")


DEFAULT_CODEC = JsonCodec()

_REGISTRY = {
    c.name: c
    for c in [
        JsonCodec(), PickleCodec(), StringCodec(), BytesCodec(), LongCodec(),
        DoubleCodec(), ZlibCodec(), Bz2Codec(), LzmaCodec(), CborCodec(),
    ]
}
if MsgPackCodec is not None:
    _REGISTRY["msgpack"] = MsgPackCodec()


def by_name(name: str) -> Codec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown codec '{name}' (have {sorted(_REGISTRY)})") from None


# -- object references (RedissonReference analog) -----------------------------

_RREF_MAGIC = b"\x00RREF1\x00"
_RREF_MODULE_PREFIX = "redisson_tpu.client.objects."


class ObjectRef:
    """Inert descriptor decoded where no engine is available (e.g. a pickled
    codec shipped to a worker process): identifies the referenced object
    without binding a live handle."""

    __slots__ = ("module", "cls", "name", "codec")

    def __init__(self, module: str, cls: str, name: str, codec: str):
        self.module, self.cls, self.name, self.codec = module, cls, name, codec

    def __repr__(self):
        return f"ObjectRef({self.cls}:{self.name})"

    def __eq__(self, other):
        return isinstance(other, ObjectRef) and (
            (self.module, self.cls, self.name) == (other.module, other.cls, other.name)
        )

    def __hash__(self):
        return hash((self.module, self.cls, self.name))


def _codec_spec(codec) -> object:
    """Serialize a codec as a rebuildable spec: class name + nested inner
    chain (compression wrappers).  Codecs whose configuration a spec cannot
    carry (CompositeCodec's two halves, parameterized codecs) rebuild as
    None -> the handle falls back to the default codec."""
    if codec is None:
        return None
    spec: dict = {"cls": type(codec).__name__}
    inner = getattr(codec, "inner", None)
    if isinstance(inner, Codec):
        spec["inner"] = _codec_spec(inner)
    return spec


def _codec_from_spec(spec) -> "Codec | None":
    if not isinstance(spec, dict):
        return None
    cls = globals().get(spec.get("cls", ""))
    if not (isinstance(cls, type) and issubclass(cls, Codec)):
        return None
    if cls is ReferenceCodec:  # never nested on purpose; unwrap defensively
        return _codec_from_spec(spec.get("inner"))
    inner = _codec_from_spec(spec.get("inner")) if spec.get("inner") else None
    try:
        return cls(inner) if inner is not None else cls()
    except TypeError:
        return None  # constructor needs config a spec can't carry


def _is_ref(data) -> bool:
    """Magic-prefix test without copying the (possibly large) payload.
    Non-bytes inputs (counter records store raw ints; codecs pass them
    through) are never references."""
    if not isinstance(data, (bytes, bytearray, memoryview)):
        return False
    return bytes(data[: len(_RREF_MAGIC)]) == _RREF_MAGIC


class ReferenceCodec(Codec):
    """RedissonReference support (liveobject/core/RedissonObjectBuilder.java,
    RedissonReference.java): storing an RObject handle INSIDE another object
    persists a typed reference — module/class/name/codec — not a serialized
    copy of its state; reading it back yields a LIVE handle bound to the same
    engine.  Every handle's codec is wrapped with this at construction
    (client/objects/base.py), so references work uniformly across maps,
    buckets, queues, and nested combinations.

    Non-handle values pass straight through to the inner codec; the magic
    prefix contains a NUL so neither JSON nor pickle output can collide with
    it (a raw BytesCodec payload theoretically could — same caveat class as
    the reference's codec-specific reference handling)."""

    name = "reference"

    def __init__(self, inner: Codec, engine=None):
        self.inner = inner
        self._engine = engine

    def __reduce__(self):
        # engines never cross process boundaries; a shipped codec decodes
        # references as inert ObjectRef descriptors
        return (ReferenceCodec, (self.inner, None))

    def encode(self, value: Any) -> bytes:
        from redisson_tpu.client.objects.base import RObject

        if isinstance(value, RObject):
            cls = type(value)
            inner = getattr(value, "_codec", None)
            if isinstance(inner, ReferenceCodec):
                inner = inner.inner
            payload = {
                "m": cls.__module__,
                "c": cls.__name__,
                # LOGICAL name: the decode path rebuilds through a factory
                # whose ctor re-applies the NameMapper (a stored key here
                # would double-map)
                "n": value._unmap_name(value._name),
                "codec": _codec_spec(inner),
            }
            return _RREF_MAGIC + json.dumps(payload).encode()
        return self.inner.encode(value)

    def decode(self, data: bytes) -> Any:
        if not _is_ref(data):
            return self.inner.decode(data)
        payload = json.loads(bytes(data)[len(_RREF_MAGIC) :])
        if self._engine is None:
            return ObjectRef(payload["m"], payload["c"], payload["n"], payload["codec"])
        # forged/foreign payloads must fail LOUDLY even when they would
        # otherwise fall into the inert-descriptor path below
        _validate_ref_module(payload["m"])
        if payload.get("codec") is not None and _codec_from_spec(payload["codec"]) is None:
            # recorded codec is unrebuildable from its spec (CompositeCodec
            # halves, parameterized codecs): a live handle would silently
            # decode with the DEFAULT codec — stay an inert descriptor, the
            # same contract as remote resolve_ref
            return ObjectRef(payload["m"], payload["c"], payload["n"], payload["codec"])
        return _build_handle(self._engine, payload)

    # references are opaque to map key/value splitting
    def encode_map_key(self, value: Any) -> bytes:
        from redisson_tpu.client.objects.base import RObject

        if isinstance(value, RObject):
            return self.encode(value)
        return self.inner.encode_map_key(value)

    def decode_map_key(self, data: bytes) -> Any:
        if _is_ref(data):
            return self.decode(data)
        return self.inner.decode_map_key(data)

    def encode_map_value(self, value: Any) -> bytes:
        from redisson_tpu.client.objects.base import RObject

        if isinstance(value, RObject):
            return self.encode(value)
        return self.inner.encode_map_value(value)

    def decode_map_value(self, data: bytes) -> Any:
        if _is_ref(data):
            return self.decode(data)
        return self.inner.decode_map_value(data)


def _validate_ref_module(module) -> None:
    """Import safety: only classes under redisson_tpu.client.objects resolve
    (a stored blob must never become an arbitrary import gadget)."""
    if not str(module).startswith(_RREF_MODULE_PREFIX):
        raise ValueError(f"reference to non-object module '{module}'")


def _build_handle(engine, payload: dict):
    """Rebuild a live handle from a reference payload.

    Import safety: see _validate_ref_module; additionally the class must be
    an RObject subclass."""
    import importlib

    from redisson_tpu.client.objects.base import RObject

    module = payload["m"]
    _validate_ref_module(module)
    cls = getattr(importlib.import_module(module), payload["c"], None)
    if cls is None or not (isinstance(cls, type) and issubclass(cls, RObject)):
        raise ValueError(f"reference to unknown object class '{payload['c']}'")
    codec = _codec_from_spec(payload.get("codec"))
    return cls(engine, payload["n"], codec)
