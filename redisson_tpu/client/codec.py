"""Codec SPI: pluggable value serialization, mirroring the reference's
codec layer (``org/redisson/client/codec/Codec.java``, ``BaseCodec.java`` and
the ~20 implementations under ``org/redisson/codec/`` — SURVEY.md §2.4).

A codec turns user values into bytes at the object-handle boundary; sketch
objects additionally feed those bytes to the vectorized hash (the reference
does exactly this: codec encode -> HighwayHash, RedissonBloomFilter.java:90-97).

Default codec is JSON (reference default: JsonJacksonCodec), with a typed
fallback to pickle for non-JSON-able values (reference's JDK-serialization
codec analog).  Map-key vs map-value codecs can differ via CompositeCodec.
Compression wrappers (Zlib here; LZ4/Snappy in the reference) nest any inner
codec.
"""
from __future__ import annotations

import json
import pickle
import struct
import zlib
from typing import Any


class Codec:
    """Encoder/decoder pair. Subclasses must be stateless & thread-safe."""

    name = "codec"

    def encode(self, value: Any) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes) -> Any:
        raise NotImplementedError

    # map key/value split points (CompositeCodec overrides)
    def encode_map_key(self, value: Any) -> bytes:
        return self.encode(value)

    def decode_map_key(self, data: bytes) -> Any:
        return self.decode(data)

    def encode_map_value(self, value: Any) -> bytes:
        return self.encode(value)

    def decode_map_value(self, data: bytes) -> Any:
        return self.decode(data)


class JsonCodec(Codec):
    """Default codec (parity: codec/JsonJacksonCodec.java).

    JSON with a one-byte tag; values JSON can't express fall back to pickle
    (tag 'P') so arbitrary Python objects still round-trip, like the
    reference's default typing support.
    """

    name = "json"

    def encode(self, value: Any) -> bytes:
        try:
            return b"J" + json.dumps(value, separators=(",", ":"), sort_keys=True).encode()
        except (TypeError, ValueError):
            return b"P" + pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def decode(self, data: bytes) -> Any:
        tag, body = data[:1], data[1:]
        if tag == b"J":
            return json.loads(body)
        if tag == b"P":
            return pickle.loads(body)
        raise ValueError(f"unknown JsonCodec tag {tag!r}")


class PickleCodec(Codec):
    """Binary python-native codec (parity: codec/SerializationCodec.java)."""

    name = "pickle"

    def encode(self, value: Any) -> bytes:
        return pickle.dumps(value, protocol=pickle.HIGHEST_PROTOCOL)

    def decode(self, data: bytes) -> Any:
        return pickle.loads(data)


class StringCodec(Codec):
    """UTF-8 strings (parity: client/codec/StringCodec.java)."""

    name = "string"

    def encode(self, value: Any) -> bytes:
        if isinstance(value, bytes):
            return value
        return str(value).encode()

    def decode(self, data: bytes) -> Any:
        return data.decode()


class BytesCodec(Codec):
    """Raw bytes passthrough (parity: client/codec/ByteArrayCodec.java)."""

    name = "bytes"

    def encode(self, value: Any) -> bytes:
        if isinstance(value, (bytes, bytearray, memoryview)):
            return bytes(value)
        raise TypeError(f"BytesCodec requires bytes, got {type(value)}")

    def decode(self, data: bytes) -> Any:
        return data


class LongCodec(Codec):
    """Signed 64-bit integers (parity: client/codec/LongCodec.java)."""

    name = "long"

    def encode(self, value: Any) -> bytes:
        return struct.pack("<q", int(value))

    def decode(self, data: bytes) -> Any:
        return struct.unpack("<q", data)[0]


class DoubleCodec(Codec):
    """Float64 (parity: client/codec/DoubleCodec.java)."""

    name = "double"

    def encode(self, value: Any) -> bytes:
        return struct.pack("<d", float(value))

    def decode(self, data: bytes) -> Any:
        return struct.unpack("<d", data)[0]


class CompositeCodec(Codec):
    """Different codecs for map key / map value (parity: codec/CompositeCodec.java)."""

    name = "composite"

    def __init__(self, map_key_codec: Codec, map_value_codec: Codec, value_codec: Codec | None = None):
        self.key_codec = map_key_codec
        self.value_codec_ = map_value_codec
        self.plain = value_codec or map_value_codec

    def encode(self, value):
        return self.plain.encode(value)

    def decode(self, data):
        return self.plain.decode(data)

    def encode_map_key(self, value):
        return self.key_codec.encode(value)

    def decode_map_key(self, data):
        return self.key_codec.decode(data)

    def encode_map_value(self, value):
        return self.value_codec_.encode(value)

    def decode_map_value(self, data):
        return self.value_codec_.decode(data)


class ZlibCodec(Codec):
    """Compression wrapper around an inner codec (parity: codec/LZ4Codec.java /
    SnappyCodecV2.java — wrap-any-codec pattern; zlib is the in-stdlib stand-in)."""

    name = "zlib"

    def __init__(self, inner: Codec | None = None, level: int = 1):
        self.inner = inner or JsonCodec()
        self.level = level

    def encode(self, value):
        return zlib.compress(self.inner.encode(value), self.level)

    def decode(self, data):
        return self.inner.decode(zlib.decompress(data))


try:  # optional, gated: msgpack is not in the baked image
    import msgpack  # type: ignore

    class MsgPackCodec(Codec):  # pragma: no cover - optional dep
        name = "msgpack"

        def encode(self, value):
            return msgpack.packb(value)

        def decode(self, data):
            return msgpack.unpackb(data)

except ImportError:  # pragma: no cover
    MsgPackCodec = None  # type: ignore


class Bz2Codec(Codec):
    """bz2 compression wrapper (higher ratio / slower than Zlib — the LZ4-vs-
    Snappy trade of the reference's two compression codecs)."""

    name = "bz2"

    def __init__(self, inner: Codec | None = None):
        import bz2 as _bz2

        self._bz2 = _bz2
        self.inner = inner or JsonCodec()

    def encode(self, value):
        return self._bz2.compress(self.inner.encode(value))

    def decode(self, data):
        return self.inner.decode(self._bz2.decompress(data))


class LzmaCodec(Codec):
    """xz/lzma compression wrapper."""

    name = "lzma"

    def __init__(self, inner: Codec | None = None):
        import lzma as _lzma

        self._lzma = _lzma
        self.inner = inner or JsonCodec()

    def encode(self, value):
        return self._lzma.compress(self.inner.encode(value))

    def decode(self, data):
        return self.inner.decode(self._lzma.decompress(data))


class ProtobufCodec(Codec):
    """Protocol-buffers codec for one message class (parity:
    codec/ProtobufCodec.java — values must be instances of `message_cls`)."""

    name = "protobuf"

    def __init__(self, message_cls):
        self.message_cls = message_cls

    def encode(self, value):
        if not isinstance(value, self.message_cls):
            raise TypeError(
                f"ProtobufCodec({self.message_cls.__name__}) cannot encode {type(value).__name__}"
            )
        return value.SerializeToString()

    def decode(self, data):
        msg = self.message_cls()
        msg.ParseFromString(bytes(data))
        return msg


DEFAULT_CODEC = JsonCodec()

_REGISTRY = {
    c.name: c
    for c in [
        JsonCodec(), PickleCodec(), StringCodec(), BytesCodec(), LongCodec(),
        DoubleCodec(), ZlibCodec(), Bz2Codec(), LzmaCodec(),
    ]
}
if MsgPackCodec is not None:
    _REGISTRY["msgpack"] = MsgPackCodec()


def by_name(name: str) -> Codec:
    try:
        return _REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown codec '{name}' (have {sorted(_REGISTRY)})") from None
