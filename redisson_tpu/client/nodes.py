"""Nodes admin API: per-node PING / INFO / TIME / MEMORY.

Parity target: ``org/redisson/redisnode/`` (RedisNodes, RedisNode,
RedissonClusterNodes — SURVEY.md §2.7): an administrative surface listing the
topology's nodes and exposing health/metrics calls against each.

Two node flavors here, matching the two deployment modes:
  * EmbeddedNode — one per JAX device of the local process.  "INFO" reports
    the device's HBM statistics (`device.memory_stats()` on TPU), platform,
    and the store's record count; "PING" round-trips a tiny computation
    through the device so it actually proves the chip is alive (the
    reference's PING proves the socket + event loop, ours proves the
    dispatch path).
  * RemoteNode — wraps a NodeClient and issues the wire PING/INFO/TIME/
    MEMORY commands the server registry exposes.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional


class BaseNode:
    id: str
    address: str

    def ping(self, timeout: float = 5.0) -> bool:  # pragma: no cover - interface
        raise NotImplementedError

    def time(self) -> float:  # pragma: no cover - interface
        raise NotImplementedError

    def info(self) -> Dict[str, Any]:  # pragma: no cover - interface
        raise NotImplementedError

    def memory(self) -> Dict[str, Any]:  # pragma: no cover - interface
        raise NotImplementedError


class EmbeddedNode(BaseNode):
    """One local JAX device viewed as a topology node."""

    def __init__(self, engine, device):
        self._engine = engine
        self.device = device
        self.id = f"{device.platform}:{device.id}"
        self.address = f"device://{device.platform}/{device.id}"

    def ping(self, timeout: float = 5.0) -> bool:
        import jax
        import jax.numpy as jnp
        import numpy as np

        try:
            x = jax.device_put(jnp.arange(4, dtype=jnp.int32), self.device)
            return int(np.asarray(x).sum()) == 6
        except Exception:
            return False

    def time(self) -> float:
        return time.time()

    def info(self) -> Dict[str, Any]:
        d = self.device
        out: Dict[str, Any] = {
            "id": self.id,
            "platform": d.platform,
            "device_kind": getattr(d, "device_kind", "unknown"),
            "process_index": getattr(d, "process_index", 0),
            "keys": len(self._engine.store),
        }
        out.update(self.memory())
        return out

    def memory(self) -> Dict[str, Any]:
        stats: Dict[str, Any] = {}
        try:
            ms = self.device.memory_stats() or {}
            stats["bytes_in_use"] = ms.get("bytes_in_use")
            stats["bytes_limit"] = ms.get("bytes_limit")
            stats["peak_bytes_in_use"] = ms.get("peak_bytes_in_use")
        except Exception:
            # CPU backend has no memory_stats; report nothing rather than lie
            pass
        return stats


class RemoteNode(BaseNode):
    """A server process reached over the wire protocol."""

    def __init__(self, node_client):
        self._nc = node_client
        self.address = getattr(node_client, "address", "?")
        self.id = self.address

    def ping(self, timeout: float = 5.0) -> bool:
        try:
            return self._nc.execute("PING", timeout=timeout) in (b"PONG", "PONG")
        except Exception:
            return False

    def time(self) -> float:
        reply = self._nc.execute("TIME")
        # RESP TIME returns [seconds, microseconds]
        sec, usec = (int(x) for x in reply)
        return sec + usec / 1e6

    def info(self) -> Dict[str, Any]:
        raw = self._nc.execute("INFO")
        text = raw.decode() if isinstance(raw, (bytes, bytearray)) else str(raw)
        out: Dict[str, Any] = {}
        for line in text.splitlines():
            if ":" in line and not line.startswith("#"):
                k, _, v = line.partition(":")
                out[k.strip()] = v.strip()
        return out

    def memory(self) -> Dict[str, Any]:
        reply = self._nc.execute("MEMORY", "STATS")
        if isinstance(reply, (list, tuple)):
            it = iter(reply)
            return {
                (k.decode() if isinstance(k, (bytes, bytearray)) else str(k)): v
                for k, v in zip(it, it)
            }
        return {"raw": reply}


class NodesGroup:
    """RedisNodes analog: enumerate + health-check the topology's nodes."""

    def __init__(self, nodes: List[BaseNode]):
        self._nodes = list(nodes)

    @classmethod
    def embedded(cls, engine) -> "NodesGroup":
        import jax

        return cls([EmbeddedNode(engine, d) for d in jax.devices()])

    @classmethod
    def remote(cls, *node_clients) -> "NodesGroup":
        return cls([RemoteNode(nc) for nc in node_clients])

    def nodes(self) -> List[BaseNode]:
        return list(self._nodes)

    def node(self, node_id: str) -> Optional[BaseNode]:
        for n in self._nodes:
            if n.id == node_id:
                return n
        return None

    def ping_all(self, timeout: float = 5.0) -> bool:
        """True iff EVERY node answers (RedisNodes.pingAll contract)."""
        return all(n.ping(timeout) for n in self._nodes)

    def __len__(self) -> int:
        return len(self._nodes)

    def __iter__(self):
        return iter(self._nodes)
