"""Shared cluster-routing core: pure slot/redirect logic consumed by BOTH
the sync (`client/cluster.py`) and async (`client/aio.py`) cluster clients.

Parity target: the routing half of ``command/RedisExecutor.java:113-560``
(slot calculation, MOVED/ASK/TRYAGAIN classification) and the view parsing
of ``cluster/ClusterConnectionManager.java:84-180`` — extracted so the two
client flavors cannot drift (VERDICT r2 #5: "extract the routing core so
both consume it").

Everything here is pure (no I/O, no locks): inputs are command tuples and
CLUSTER SLOTS reply rows; outputs are slots, write flags, and redirect
decisions.  The clients own connections, retries, and timing.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from redisson_tpu.net import commands as C
from redisson_tpu.net.resp import RespError
from redisson_tpu.utils.crc16 import MAX_SLOT, calc_slot

# keyless commands whose answer is the union over every master — the RKeys
# scatter-gather surface (CommandAsyncService readAllAsync/writeAllAsync)
ALL_SHARD = {"KEYS": "concat", "DBSIZE": "sum", "FLUSHALL": "ok"}

# multi-key commands that are one atomic compound op server-side:
# all keys must colocate on one shard (Redis CROSSSLOT rule)
SAME_SLOT = {
    "PFMERGE", "BITOP", "RENAME", "MGET", "MSET", "MSETNX",
    "SMOVE", "LMOVE", "RPOPLPUSH",
    "SINTER", "SUNION", "SDIFF",
    "SINTERSTORE", "SUNIONSTORE", "SDIFFSTORE", "SINTERCARD",
    "ZUNIONSTORE", "ZINTERSTORE",
    "COPY", "RENAMENX", "SORT", "GEOSEARCHSTORE",
    "ZDIFF", "ZINTER", "ZUNION", "ZDIFFSTORE", "ZRANGESTORE",
    "LMPOP", "ZMPOP", "BLMPOP", "BZMPOP", "BLPOP", "BRPOP", "BLMOVE", "BRPOPLPUSH",
    "BZPOPMIN", "BZPOPMAX", "XREAD", "XREADGROUP",
}
# (MGET/MSET follow real Redis cluster semantics: multi-key commands
#  spanning slots raise CROSSSLOT; use {hashtags} or the RBuckets
#  handles, which split per shard client-side)

# sentinel slot meaning "cross-slot but splittable" (DEL/UNLINK grouping)
SPLIT = -1


def route(cmd: str, args: tuple) -> Tuple[Optional[int], bool]:
    """(slot | None | SPLIT, is_write) for one command.

    None = keyless (any node); SPLIT = multi-key spanning slots where the
    caller groups per shard.  PUBLISH routes by channel slot as a write —
    subscriptions live on the channel's slot-owner master, so a publish
    must land there or fan-out silently drops."""
    cu = cmd.upper()
    if cu in ("PUBLISH", "SPUBLISH") and args:
        ch = args[0]
        return calc_slot(ch if isinstance(ch, bytes) else str(ch).encode()), True
    keys = C.command_keys(cmd, list(args))
    write = C.is_write(cmd, list(args))
    if not keys:
        return None, write
    slots = {calc_slot(k if isinstance(k, bytes) else str(k).encode()) for k in keys}
    if len(slots) > 1:
        if cu in SAME_SLOT:
            raise RespError(
                f"CROSSSLOT keys of {cmd} map to different slots; use a "
                "{hashtag} to colocate them"
            )
        return SPLIT, write
    return slots.pop(), write


# Keyless READ verbs a replica serves (ISSUE 18): the FT search surface is
# read-classified and keyless (indexes are named, not keyed — net/commands
# SPECS), and the server's check_routing admits keyless reads on replicas,
# so the read-only legs of FT.MSEARCH / execute_many fan-outs may ride the
# replica plane.  The admin/introspection remainder of the keyless surface
# stays master-routed.
FT_REPLICA_READS = frozenset((
    "FT.SEARCH", "FT.MSEARCH", "FT.AGGREGATE", "FT.INFO",
))


def replica_readable(cmd: str, args: tuple) -> bool:
    """True when a READONLY replica may serve this command (ISSUE 17): the
    client-side mirror of the server's check_routing admission — keyed
    (slot-routed, single slot) and read-classified, plus the keyless FT
    read verbs (FT_REPLICA_READS).  Other keyless commands route to
    masters (admin surface), writes always do, and split multi-key reads
    re-enter per group where each group is re-checked."""
    try:
        slot, write = route(cmd, args)
    except RespError:
        return False  # CROSSSLOT surfaces on the normal path
    if write:
        return False
    if slot is None:
        return cmd.upper() in FT_REPLICA_READS
    return slot != SPLIT


def parse_view(view_rows: List[Any]) -> Tuple[List[Optional[str]], Dict[str, None]]:
    """CLUSTER SLOTS reply -> (slot->addr table, ordered master addr set)."""
    new_slots: List[Optional[str]] = [None] * MAX_SLOT
    masters: Dict[str, None] = {}
    for row in view_rows:
        lo, hi, (host, port, _nid) = int(row[0]), int(row[1]), row[2]
        host = host.decode() if isinstance(host, bytes) else host
        addr = f"{host}:{int(port)}"
        masters[addr] = None
        for s in range(lo, hi + 1):
            new_slots[s] = addr
    return new_slots, masters


def classify_redirect(err: RespError) -> Tuple[Optional[str], Optional[str]]:
    """(kind, target_addr) where kind is "moved" | "ask" | "tryagain" | None.

    MOVED refreshes topology and re-routes; ASK is a one-shot hop into a
    migration window WITHOUT a view update; TRYAGAIN backs off (multi-key
    op spanning a half-drained window)."""
    msg = str(err)
    if msg.startswith("MOVED "):
        parts = msg.split()
        return "moved", parts[2] if len(parts) > 2 else None
    if msg.startswith("ASK "):
        parts = msg.split()
        return "ask", parts[2] if len(parts) > 2 else None
    if msg.startswith("TRYAGAIN"):
        return "tryagain", None
    return None, None


def is_redirect(err: RespError) -> bool:
    return classify_redirect(err)[0] is not None


def group_by_slot_owner(
    slot_table: List[Optional[str]], names: List[Any]
) -> Dict[Optional[str], List[int]]:
    """Index positions grouped by owning master address (OBJCALLM / batch
    per-shard grouping — the executeBatchedAsync discipline)."""
    groups: Dict[Optional[str], List[int]] = {}
    for i, name in enumerate(names):
        if name:
            kb = name if isinstance(name, bytes) else str(name).encode()
            addr = slot_table[calc_slot(kb)]
        else:
            addr = None
        groups.setdefault(addr, []).append(i)
    return groups


# blob sketch verbs whose same-verb frame runs the server may fuse into one
# stacked-bank kernel dispatch (server/verbs/sketch.py coalesce_bloom_run —
# the adaptive coalescing plane, ISSUE 2).  Listed HERE because run shape is
# routing-adjacent pure logic: clients that order a shard's frame to keep
# same-verb commands adjacent (the natural order of a fan-out batch) get
# maximal runs server-side for free.
COALESCIBLE_BLOB_VERBS = frozenset((b"BF.MADD64", b"BF.MEXISTS64"))


def coalescible_frame_runs(cmds: List[Any]) -> List[Tuple[int, int]]:
    """Maximal [start, end) runs (len >= 2) of CONSECUTIVE same-verb
    coalescible blob commands in one pipelined frame.  Pure scan: the server
    frame loop replaces each run with a single fused dispatch; everything
    outside the runs dispatches per command, so frame order is untouched."""
    def verb_of(cmd) -> Optional[bytes]:
        # malformed frames carry non-bytes elements (nested arrays, ints);
        # they are NOT runs — the per-command path replies their errors
        if (
            isinstance(cmd, list)
            and cmd
            and isinstance(cmd[0], (bytes, bytearray))
        ):
            return bytes(cmd[0]).upper()
        return None

    out: List[Tuple[int, int]] = []
    i, n = 0, len(cmds)
    while i < n:
        verb = verb_of(cmds[i])
        if verb not in COALESCIBLE_BLOB_VERBS:
            i += 1
            continue
        j = i + 1
        while j < n and verb_of(cmds[j]) == verb:
            j += 1
        if j - i >= 2:
            out.append((i, j))
        i = j
    return out


def group_by_slot(keys: List[Any]) -> Dict[int, List[Any]]:
    """Keys grouped by slot (cross-slot DEL/UNLINK splitting: one multi-key
    sub-command per slot, NEVER one round trip per key)."""
    groups: Dict[int, List[Any]] = {}
    for key in keys:
        kb = key if isinstance(key, bytes) else str(key).encode()
        groups.setdefault(calc_slot(kb), []).append(key)
    return groups
