"""Keys: keyspace administration (RKeys analog).

Parity target: ``org/redisson/RedissonKeys.java`` (545 LoC) — SCAN-based key
iteration, DEL/UNLINK batched per shard, EXPIRE, RANDOMKEY, COUNT, FLUSHDB.
The reference fans these out per master entry via readBatchedAsync /
SlotCallback (``command/CommandAsyncService.java:575-640``); in-process the
store is one registry, and in mesh mode the same surface fans out per shard.
"""
from __future__ import annotations

import random
import time
from typing import Iterable, Iterator, List, Optional


class Keys:
    def __init__(self, engine):
        self._engine = engine

    def _map(self, name: str) -> str:
        """NameMapper applies to the admin surface too (the reference maps
        in RedissonKeys the same way): callers pass LOGICAL names."""
        mapper = getattr(self._engine.config, "name_mapper", None)
        return mapper.map(name) if mapper is not None else name

    def _unmap(self, key: str) -> str:
        mapper = getattr(self._engine.config, "name_mapper", None)
        return mapper.unmap(key) if mapper is not None else key

    def _map_pattern(self, pattern: Optional[str]) -> Optional[str]:
        # patterns are LOGICAL too: prefix mappers compose naturally
        # ("cfg*" -> "t:cfg*"); identity mappers are no-ops
        return None if pattern is None else self._map(pattern)

    def get_keys(self, pattern: Optional[str] = None) -> List[str]:
        """LOGICAL names in and out — results must round-trip into
        get_bucket()/delete() without double-prefixing."""
        return [self._unmap(k) for k in self._engine.store.keys(self._map_pattern(pattern))]

    def get_keys_stream(self, pattern: Optional[str] = None, chunk: int = 10) -> Iterator[str]:
        """Cursor-style iteration (SCAN analog; chunk mirrors COUNT)."""
        for name in self._engine.store.keys(self._map_pattern(pattern)):
            yield self._unmap(name)

    def count(self) -> int:
        return len(self._engine.store.keys())

    def count_exists(self, *names: str) -> int:
        return sum(1 for n in names if self._engine.store.exists(self._map(n)))

    def random_key(self) -> Optional[str]:
        keys = self._engine.store.keys()
        return self._unmap(random.choice(keys)) if keys else None

    def delete(self, *names: str) -> int:
        n = 0
        for nm in names:
            key = self._map(nm)
            with self._engine.locked(key):
                if self._engine.store.delete(key):
                    n += 1
        return n

    def delete_by_pattern(self, pattern: str) -> int:
        n = 0
        for key in self._engine.store.keys(self._map_pattern(pattern)):
            with self._engine.locked(key):
                if self._engine.store.delete(key):
                    n += 1
        return n

    def unlink(self, *names: str) -> int:
        # no async reclamation distinction in-process; same as delete
        return self.delete(*names)

    def expire(self, name: str, seconds: float) -> bool:
        return self._engine.store.expire(self._map(name), time.time() + seconds)

    def remain_time_to_live(self, name: str) -> Optional[float]:
        return self._engine.store.ttl(self._map(name))

    def flushdb(self) -> None:
        self._engine.store.flushall()

    def flushall(self) -> None:
        self._engine.store.flushall()
