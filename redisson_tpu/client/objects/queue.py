"""Queue family: FIFO/LIFO/blocking/bounded/delayed/priority/ring/transfer.

Parity targets (SURVEY.md §2.5):
  * RQueue / RDeque — LPUSH/RPOP list semantics.
  * RBlockingQueue / RBlockingDeque — ``RedissonBlockingQueue.java``: BLPOP/
    BLMOVE; blocking ops park on a wait entry and survive "reconnects".
  * RBoundedBlockingQueue — ``RedissonBoundedBlockingQueue.java`` (410 LoC):
    capacity enforced via a semaphore-like channel.
  * RDelayedQueue — ``RedissonDelayedQueue.java`` (527 LoC): target queue +
    timeout-ordered buffer + transfer timer (QueueTransferTask.java:83-118).
  * RPriorityQueue/Deque — ``RedissonPriorityQueue.java`` (476 LoC).
  * RRingBuffer — capped queue evicting oldest.
  * RTransferQueue — ``RedissonTransferQueue.java`` (731 LoC): producers may
    wait for consumption.

Blocking is a host-side control-plane concern (SURVEY.md §7.3 item 3):
condition-variable wait entries play the role of the pubsub wakeup channels.
"""
from __future__ import annotations

import heapq
import threading
import time
from typing import Any, Iterable, List, Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.pubsub import WaitEntry
from redisson_tpu.core.store import StateRecord


class Queue(RExpirable):
    _kind = "queue"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=[])
        )

    def _e(self, v) -> bytes:
        return self._codec.encode(v)

    def _d(self, raw: bytes):
        return self._codec.decode(raw)

    def offer(self, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.append(self._e(value))
            self._touch_version(rec)
        self._signal()
        return True

    def add(self, value) -> bool:
        if not self.offer(value):
            raise OverflowError("queue full")
        return True

    def poll(self):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            raw = rec.host.pop(0)
            self._touch_version(rec)
            return self._d(raw)

    def poll_many(self, limit: int) -> List:
        out = []
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            while rec.host and len(out) < limit:
                out.append(self._d(rec.host.pop(0)))
            if out:
                self._touch_version(rec)
        return out

    def peek(self):
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return None
        return self._d(rec.host[0])

    def element(self):
        v = self.peek()
        if v is None:
            raise LookupError("queue is empty")
        return v

    def remove_head(self):
        v = self.poll()
        if v is None:
            raise LookupError("queue is empty")
        return v

    def contains(self, value) -> bool:
        rec = self._engine.store.get(self._name)
        return rec is not None and self._e(value) in rec.host

    def remove(self, value) -> bool:
        e = self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            try:
                rec.host.remove(e)
            except ValueError:
                return False
            self._touch_version(rec)
            return True

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)

    def is_empty(self) -> bool:
        return self.size() == 0

    def read_all(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(e) for e in list(rec.host)]

    def clear(self) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.clear()
            self._touch_version(rec)

    def poll_last_and_offer_first_to(self, dest_name: str):
        """RPOPLPUSH (RQueue.pollLastAndOfferFirstTo)."""
        # construct the dest handle FIRST: its ctor applies the NameMapper,
        # and the lock must cover the mapped key it will actually mutate
        dest = type(self)(self._engine, dest_name, self._codec)
        with self._engine.locked_many((self._name, dest._name)):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            raw = rec.host.pop()
            drec = dest._rec_or_create()
            drec.host.insert(0, raw)
            self._touch_version(rec)
            self._touch_version(drec)
        dest._signal()
        return self._d(raw)

    # wakeup plumbing shared with blocking subclasses
    def _wait_entry(self) -> WaitEntry:
        return self._engine.queue_wait_entry(self._name)

    def _signal(self):
        self._wait_entry().signal(all_=True)

    def __len__(self):
        return self.size()


class Deque(Queue):
    _kind = "deque"

    def add_first(self, value) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.insert(0, self._e(value))
            self._touch_version(rec)
        self._signal()

    def add_last(self, value) -> None:
        self.offer(value)

    def offer_first(self, value) -> bool:
        self.add_first(value)
        return True

    def offer_last(self, value) -> bool:
        return self.offer(value)

    def poll_first(self):
        return self.poll()

    def poll_last(self):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            raw = rec.host.pop()
            self._touch_version(rec)
            return self._d(raw)

    def peek_first(self):
        return self.peek()

    def peek_last(self):
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return None
        return self._d(rec.host[-1])

    # -- RDeque round-4 surface: XX pushes + cross-deque moves ---------------

    def add_first_if_exists(self, *values) -> int:
        """RDeque.addFirstIfExists (LPUSHX): push only onto an EXISTING
        deque; returns the new size (0 = absent, nothing pushed)."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None or not rec.host:
                return 0
            for v in values:
                rec.host.insert(0, self._e(v))
            self._touch_version(rec)
        self._signal()
        return self.size()

    def add_last_if_exists(self, *values) -> int:
        """RDeque.addLastIfExists (RPUSHX)."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None or not rec.host:
                return 0
            for v in values:
                rec.host.append(self._e(v))
            self._touch_version(rec)
        self._signal()
        return self.size()

    def move(self, dest_name: str, src_end: str = "LEFT", dest_end: str = "LEFT"):
        """RDeque.move (LMOVE src dest LEFT|RIGHT LEFT|RIGHT): atomic
        cross-deque transfer; returns the moved value or None."""
        if src_end.upper() not in ("LEFT", "RIGHT") or dest_end.upper() not in ("LEFT", "RIGHT"):
            raise ValueError("ends must be LEFT or RIGHT")
        dest = Deque(self._engine, dest_name, self._codec)
        names = [self._name, dest._name]
        with self._engine.locked_many(names):
            rec = self._engine.store.get(self._name)
            if rec is None or not rec.host:
                return None
            raw = rec.host.pop(0) if src_end.upper() == "LEFT" else rec.host.pop()
            self._touch_version(rec)
            drec = dest._rec_or_create()
            if dest_end.upper() == "LEFT":
                drec.host.insert(0, raw)
            else:
                drec.host.append(raw)
            dest._touch_version(drec)
        dest._signal()
        return self._d(raw)

    def add_first_to(self, dest_name: str):
        """RDeque.addFirstTo: pop this deque's HEAD onto dest's head."""
        return self.move(dest_name, "LEFT", "LEFT")

    def add_last_to(self, dest_name: str):
        """RDeque.addLastTo: pop this deque's HEAD onto dest's tail."""
        return self.move(dest_name, "LEFT", "RIGHT")


class BlockingQueue(Queue):
    """RBlockingQueue: take/poll(timeout) park on the wait entry and are woken
    by offers (the BLPOP + pubsub-wakeup pattern, SURVEY.md §3.3)."""

    _kind = "blocking_queue"

    def take(self):
        return self.poll_blocking(None)

    def _poll_blocking_impl(self, poll_fn, timeout: Optional[float]):
        """One wait policy for every blocking-poll flavor: park the FULL
        remaining budget (the offer side signals the wait entry)."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            v = poll_fn()
            if v is not None:
                return v
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return None
            self._wait_entry().wait_for(remaining if remaining is not None else 1.0)

    def poll_blocking(self, timeout: Optional[float]):
        return self._poll_blocking_impl(self.poll, timeout)

    def poll_from_any(self, timeout: Optional[float], *other_names: str):
        """BLPOP across several queues (RBlockingQueue.pollFromAny).
        Handles are built ONCE from logical names (the ctor applies the
        NameMapper; re-feeding self._name through it would double-map),
        and the returned name is the logical one the caller passed."""
        pairs = [(self._unmap_name(self._name), self)] + [
            (nm, BlockingQueue(self._engine, nm, self._codec)) for nm in other_names
        ]
        deadline = None if timeout is None else time.time() + timeout
        while True:
            for nm, h in pairs:
                v = h.poll()
                if v is not None:
                    return nm, v
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return None, None
            self._wait_entry().wait_for(min(0.05, remaining) if remaining else 0.05)

    def poll_last_and_offer_first_to_blocking(self, dest_name: str, timeout: Optional[float]):
        """BRPOPLPUSH."""
        deadline = None if timeout is None else time.time() + timeout
        while True:
            v = self.poll_last_and_offer_first_to(dest_name)
            if v is not None:
                return v
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return None
            self._wait_entry().wait_for(remaining if remaining is not None else 1.0)

    def drain_to(self, collection: list, max_elements: Optional[int] = None) -> int:
        items = self.poll_many(max_elements if max_elements is not None else 1 << 62)
        collection.extend(items)
        return len(items)


class BlockingDeque(BlockingQueue, Deque):
    _kind = "blocking_deque"

    def take_first(self):
        return self.take()

    def take_last(self):
        while True:
            v = self.poll_last()
            if v is not None:
                return v
            self._wait_entry().wait_for(1.0)

    def poll_last_blocking(self, timeout: Optional[float]):
        """Tail-end bounded blocking poll (pollLastAsync with timeout — the
        subscribeOnLastElements feed); shares poll_blocking's wait policy."""
        return self._poll_blocking_impl(self.poll_last, timeout)


class BoundedBlockingQueue(BlockingQueue):
    """RBoundedBlockingQueue: capacity gate on offer (semaphore channel in the
    reference, RedissonBoundedBlockingQueue.java)."""

    _kind = "bounded_blocking_queue"

    def try_set_capacity(self, capacity: int) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if "capacity" in rec.meta:
                return False
            rec.meta["capacity"] = capacity
            return True

    def _capacity(self, rec) -> int:
        return rec.meta.get("capacity", 1 << 62)

    def offer(self, value, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.time() + timeout
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                if len(rec.host) < self._capacity(rec):
                    rec.host.append(self._e(value))
                    self._touch_version(rec)
                    self._signal()
                    return True
            if timeout is None:
                return False
            remaining = deadline - time.time()
            if remaining <= 0:
                return False
            self._wait_entry().wait_for(remaining)

    def put(self, value) -> None:
        while not self.offer(value, timeout=1.0):
            pass

    def poll(self):
        v = super().poll()
        if v is not None:
            self._signal()  # wake producers waiting for space
        return v


class PriorityQueue(Queue):
    """RPriorityQueue: heap-ordered by value (or key function)."""

    _kind = "priority_queue"

    def __init__(self, engine, name, codec=None, key=None):
        super().__init__(engine, name, codec)
        self._key = key

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=[])
        )

    def _hk(self, value):
        return self._key(value) if self._key else value

    def offer(self, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            heapq.heappush(rec.host, (self._hk(value), self._e(value)))
            self._touch_version(rec)
        self._signal()
        return True

    def poll(self):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            _, raw = heapq.heappop(rec.host)
            self._touch_version(rec)
            return self._d(raw)

    def peek(self):
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return None
        return self._d(rec.host[0][1])

    def read_all(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(raw) for _, raw in sorted(rec.host)]

    # The heap stores (sort_key, raw) tuples, not flat raw values, so every
    # list-shaped op inherited from Queue must be re-expressed over tuples.

    def poll_many(self, limit: int) -> List:
        out = []
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            while rec.host and len(out) < limit:
                _, raw = heapq.heappop(rec.host)
                out.append(self._d(raw))
            if out:
                self._touch_version(rec)
        return out

    def contains(self, value) -> bool:
        e = self._e(value)
        rec = self._engine.store.get(self._name)
        return rec is not None and any(raw == e for _, raw in rec.host)

    def remove(self, value) -> bool:
        e = self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for i, (_, raw) in enumerate(rec.host):
                if raw == e:
                    rec.host.pop(i)
                    heapq.heapify(rec.host)
                    self._touch_version(rec)
                    return True
            return False

    def poll_last_and_offer_first_to(self, dest_name: str):
        """Moves the comparator-greatest element to the head of `dest_name`
        (RPOPLPUSH shape; the destination is a priority queue of the same
        type, so "first" means heap order there too)."""
        dest = type(self)(self._engine, dest_name, self._codec, self._key)
        with self._engine.locked_many((self._name, dest._name)):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            i = max(range(len(rec.host)), key=lambda j: rec.host[j])
            hk, raw = rec.host.pop(i)
            heapq.heapify(rec.host)
            drec = dest._rec_or_create()
            heapq.heappush(drec.host, (hk, raw))
            self._touch_version(rec)
            self._touch_version(drec)
        dest._signal()
        return self._d(raw)


class PriorityDeque(PriorityQueue):
    """RPriorityDeque (`RedissonPriorityDeque.java`): deque view over the
    comparator order.  Positional inserts are meaningless on a heap, so
    addFirst/addLast raise — the reference throws
    UnsupportedOperationException("use add or put method")."""

    def add_first(self, value):
        raise NotImplementedError("use add/offer — order is comparator-defined")

    def add_last(self, value):
        raise NotImplementedError("use add/offer — order is comparator-defined")

    offer_first = add_first
    offer_last = add_last

    def poll_first(self):
        return self.poll()

    def peek_first(self):
        return self.peek()

    def poll_last(self):
        """Removes the comparator-greatest element (heap max)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            i = max(range(len(rec.host)), key=lambda j: rec.host[j])
            _, raw = rec.host.pop(i)
            heapq.heapify(rec.host)
            self._touch_version(rec)
            return self._d(raw)

    def peek_last(self):
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return None
        return self._d(max(rec.host)[1])

    def read_all_descending(self) -> List:
        """descendingIterator materialized."""
        return list(reversed(self.read_all()))


class PriorityBlockingQueue(PriorityQueue, BlockingQueue):
    """RPriorityBlockingQueue: heap order + parked take/poll(timeout).
    MRO gives heap offer/poll from PriorityQueue and the wait-entry parking
    from BlockingQueue; cross-queue polls are unsupported exactly like the
    reference (`RedissonPriorityBlockingQueue.java` pollFromAny)."""

    def poll_from_any(self, timeout, *other_names):
        raise NotImplementedError("use poll method")

    def poll_last_and_offer_first_to_blocking(self, dest_name, timeout):
        raise NotImplementedError("use poll method")


class PriorityBlockingDeque(PriorityBlockingQueue, PriorityDeque):
    """RPriorityBlockingDeque: blocking + deque views of the heap."""

    def take_first(self):
        return self.poll_blocking(None)

    def take_last(self):
        return self.poll_last_blocking(None)

    def poll_first_blocking(self, timeout: Optional[float]):
        return self.poll_blocking(timeout)

    def poll_last_blocking(self, timeout: Optional[float]):
        return self._poll_blocking_impl(self.poll_last, timeout)


class RingBuffer(Queue):
    """RRingBuffer: fixed capacity, overwrites oldest when full."""

    _kind = "ring_buffer"

    def try_set_capacity(self, capacity: int) -> bool:
        if capacity <= 0:
            # a zero bound would make every offer silently drop its element
            raise ValueError("capacity must be positive")
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if "capacity" in rec.meta:
                return False
            rec.meta["capacity"] = capacity
            self._touch_version(rec)  # the bound must replicate
            return True

    def set_capacity(self, capacity: int) -> None:
        """RRingBuffer.setCapacity: change the bound unconditionally;
        shrinking evicts oldest elements (the buffer's overflow rule)."""
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.meta["capacity"] = capacity
            excess = len(rec.host) - capacity
            if excess > 0:
                del rec.host[:excess]  # one splice, not O(n^2) pops
            self._touch_version(rec)  # meta changed even when nothing trimmed

    def capacity(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else rec.meta.get("capacity", 0)

    def offer(self, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            cap = rec.meta.get("capacity")
            if cap is None:
                raise RuntimeError("RingBuffer capacity is not set (trySetCapacity first)")
            rec.host.append(self._e(value))
            while len(rec.host) > cap:
                rec.host.pop(0)
            self._touch_version(rec)
        self._signal()
        return True

    def remaining_capacity(self) -> int:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return 0
        return max(0, rec.meta.get("capacity", 0) - len(rec.host))


class DelayedQueue(Queue):
    """RDelayedQueue: elements become visible in the target queue after their
    delay (RedissonDelayedQueue.java: timeout ZSET + QueueTransferTask)."""

    _kind = "delayed_queue"

    def __init__(self, engine, name, codec=None, destination: Optional[Queue] = None):
        super().__init__(engine, name, codec)
        self._dest = destination

    def offer(self, value, delay: float = 0.0) -> bool:
        fire_at = time.time() + delay
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            heapq.heappush(rec.host, (fire_at, self._e(value)))
            self._touch_version(rec)
        self._schedule_transfer(delay)
        return True

    def _schedule_transfer(self, delay: float):
        # shared wheel timer (QueueTransferTask rides the reference's
        # HashedWheelTimer the same way) — not a thread per offer; the
        # transfer itself runs on the timer pool (it takes record locks)
        self._engine.schedule_timeout(self.transfer_due, max(0.0, delay))

    def transfer_due(self) -> int:
        """QueueTransferTask.pushTask analog: move due elements to the target."""
        if self._dest is None:
            return 0
        moved = 0
        now = time.time()
        with self._engine.locked_many((self._name, self._dest._name)):
            rec = self._rec_or_create()
            drec = self._dest._rec_or_create()
            while rec.host and rec.host[0][0] <= now:
                _, raw = heapq.heappop(rec.host)
                drec.host.append(raw)
                moved += 1
            if moved:
                self._touch_version(rec)
                self._touch_version(drec)
        if moved:
            self._dest._signal()
        return moved

    def poll(self):
        """Poll the *buffer* (not-yet-due elements), earliest first."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            _, raw = heapq.heappop(rec.host)
            self._touch_version(rec)
            return self._d(raw)

    def read_all(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(raw) for _, raw in sorted(rec.host)]


class TransferQueue(BlockingQueue):
    """RTransferQueue: transfer() blocks until a consumer takes the element."""

    _kind = "transfer_queue"

    def try_transfer(self, value) -> bool:
        """Hand off only if a consumer is already waiting."""
        we = self._wait_entry()
        with we.cond:
            waiting = len(we.cond._waiters) > 0  # type: ignore[attr-defined]
        if not waiting:
            return False
        self.offer(value)
        return True

    def transfer(self, value, timeout: Optional[float] = None) -> bool:
        """Blocks until the element is consumed."""
        marker = self._e(value)
        self.offer(value)
        deadline = None if timeout is None else time.time() + timeout
        while True:
            rec = self._engine.store.get(self._name)
            if rec is None or marker not in rec.host:
                return True
            if deadline is not None and time.time() >= deadline:
                with self._engine.locked(self._name):
                    rec = self._rec_or_create()
                    if marker in rec.host:
                        rec.host.remove(marker)
                        return False
                return True
            time.sleep(0.005)
