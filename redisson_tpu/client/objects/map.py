"""Map / MapCache: the hash-object family.

Parity targets:
  * RMap — ``org/redisson/RedissonMap.java`` (1,916 LoC): put/get/fastPut/
    putIfAbsent/addAndGet/remove/replace/getAll/putAll/readAll*, HSCAN-style
    iteration, MapLoader read-through and MapWriter write-through/behind
    (``MapWriterTask.java``, ``WriteBehindService.java``).
  * RMapCache — ``RedissonMapCache.java`` (3,249 LoC, the largest reference
    file): per-entry TTL and max-idle via companion expiry structures, entry
    listeners, EvictionScheduler cleanup.

Design: keys/values are codec-encoded at the boundary (exactly the reference
contract — equality is *encoded* equality), stored in a host dict inside the
record; compound ops run under the record lock (Lua-atomicity equivalent).
MapCache keeps (value, expire_at, max_idle, last_access) per entry with lazy
reaping on access plus the EvictionScheduler's periodic sweep.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, List, Optional, Tuple

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class MapLoader:
    """Read-through SPI (org/redisson/api/map/MapLoader)."""

    def load(self, key: Any) -> Any:  # pragma: no cover - interface
        raise NotImplementedError

    def load_all_keys(self) -> Iterable[Any]:  # pragma: no cover - interface
        return []


class MapWriter:
    """Write-through SPI (org/redisson/api/map/MapWriter)."""

    def write(self, entries: Dict[Any, Any]) -> None:  # pragma: no cover
        raise NotImplementedError

    def delete(self, keys: Iterable[Any]) -> None:  # pragma: no cover
        raise NotImplementedError


class MapOptions:
    """RMap options (org/redisson/api/MapOptions): loader/writer + write mode."""

    WRITE_THROUGH = "WRITE_THROUGH"
    WRITE_BEHIND = "WRITE_BEHIND"

    def __init__(
        self,
        loader: Optional[MapLoader] = None,
        writer: Optional[MapWriter] = None,
        write_mode: str = WRITE_THROUGH,
        write_behind_delay: float = 1.0,
        write_behind_batch_size: int = 50,
    ):
        self.loader = loader
        self.writer = writer
        self.write_mode = write_mode
        self.write_behind_delay = write_behind_delay
        self.write_behind_batch_size = write_behind_batch_size


class Map(RExpirable):
    _kind = "map"

    @property
    def _scan_view_safe(self) -> bool:
        """True when the value set is fully described by (nonce, version) —
        the key for staged device scan views (services/mapreduce._WcScanView).
        Loader-backed maps are excluded: read-through loads insert values
        without a version bump."""
        return self._options.loader is None

    def __init__(self, engine, name, codec=None, options: Optional[MapOptions] = None):
        super().__init__(engine, name, codec)
        self._options = options or MapOptions()
        self._wb_lock = threading.Lock()
        self._wb_queue: List[Tuple[str, Any, Any]] = []  # (op, key, value)
        self._wb_timer: Optional[threading.Timer] = None

    # -- plumbing -----------------------------------------------------------

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={})
        )

    def _ek(self, key) -> bytes:
        return self._codec.encode_map_key(key)

    def _ev(self, value) -> bytes:
        return self._codec.encode_map_value(value)

    def _dk(self, data: bytes):
        return self._codec.decode_map_key(data)

    def _dv(self, data: bytes):
        return self._codec.decode_map_value(data)

    def _raw_get(self, rec, ek: bytes):
        return rec.host.get(ek)

    def _raw_get_for_update(self, rec, ek: bytes):
        """NON-TOUCHING value fetch: write paths reading the old value, and
        sampling/warm-up probes (random_keys/random_entries/load_all).
        Same as _raw_get here; MapCache overrides it to skip access
        tracking — none of those callers may refresh max-idle clocks or
        count as LFU reads."""
        return self._raw_get(rec, ek)

    def _raw_put(self, rec, ek: bytes, ev: bytes):
        rec.host[ek] = ev

    def _raw_del(self, rec, ek: bytes) -> bool:
        return rec.host.pop(ek, None) is not None

    def _load_through(self, rec, key, ek: bytes):
        if self._options.loader is None:
            return None
        loaded = self._options.loader.load(key)
        if loaded is not None:
            self._raw_put(rec, ek, self._ev(loaded))
        return loaded

    def _write_through(self, op: str, key, value=None):
        w = self._options.writer
        if w is None:
            return
        if self._options.write_mode == MapOptions.WRITE_BEHIND:
            with self._wb_lock:
                self._wb_queue.append((op, key, value))
                if self._wb_timer is None:
                    # shared wheel timer; the flush runs on the timer pool
                    # (user MapWriter code may block on I/O and wheel
                    # callbacks must stay short)
                    self._wb_timer = self._engine.schedule_timeout(
                        self._flush_write_behind,
                        self._options.write_behind_delay,
                    )
        elif op == "write":
            w.write({key: value})
        else:
            w.delete([key])

    def _flush_write_behind(self):
        """WriteBehindService.java analog: batch queued writes/deletes."""
        with self._wb_lock:
            queue, self._wb_queue = self._wb_queue, []
            self._wb_timer = None
        writes: Dict[Any, Any] = {}
        deletes: List[Any] = []
        for op, key, value in queue:
            if op == "write":
                writes[key] = value
                if key in deletes:
                    deletes.remove(key)
            else:
                writes.pop(key, None)
                deletes.append(key)
        w = self._options.writer
        if w is not None:
            if writes:
                w.write(writes)
            if deletes:
                w.delete(deletes)

    def flush_write_behind(self):
        """Test/shutdown hook: drain the write-behind queue now."""
        with self._wb_lock:
            t = self._wb_timer
        if t is not None:
            t.cancel()
        self._flush_write_behind()

    # -- read surface -------------------------------------------------------

    def get(self, key):
        ek = self._ek(key)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            raw = self._raw_get(rec, ek)
            if raw is None:
                loaded = self._load_through(rec, key, ek)
                return loaded
            return self._dv(raw)

    def get_all(self, keys: Iterable) -> Dict:
        out = {}
        for k in keys:
            v = self.get(k)
            if v is not None:
                out[k] = v
        return out

    def contains_key(self, key) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return self._raw_get(rec, self._ek(key)) is not None

    def contains_value(self, value) -> bool:
        ev = self._ev(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return any(raw == ev for raw in rec.host.values())

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)

    def is_empty(self) -> bool:
        return self.size() == 0

    def read_all_keys(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._dk(ek) for ek in list(rec.host.keys())]

    def read_all_values(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._dv(ev) for ev in list(rec.host.values())]

    def read_all_entry_set(self) -> List[Tuple]:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [(self._dk(k), self._dv(v)) for k, v in list(rec.host.items())]

    def read_all_map(self) -> Dict:
        return dict(self.read_all_entry_set())

    def key_iterator(self, pattern: Optional[str] = None, chunk: int = 10) -> Iterator:
        """HSCAN-cursor analog (iterator/*.java): snapshot-chunked iteration."""
        import fnmatch

        for k in self.read_all_keys():
            if pattern is None or fnmatch.fnmatchcase(str(k), pattern):
                yield k

    def entry_iterator(self) -> Iterator[Tuple]:
        yield from self.read_all_entry_set()

    # -- write surface ------------------------------------------------------

    def put(self, key, value):
        """Returns previous value (RMap.put)."""
        ek, ev = self._ek(key), self._ev(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = self._raw_get_for_update(rec, ek)
            self._raw_put(rec, ek, ev)
            self._touch_version(rec)
        self._write_through("write", key, value)
        return None if old is None else self._dv(old)

    def fast_put(self, key, value) -> bool:
        """True if key is new (RMap.fastPut — skips old-value fetch)."""
        ek, ev = self._ek(key), self._ev(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            is_new = ek not in rec.host
            self._raw_put(rec, ek, ev)
            self._touch_version(rec)
        self._write_through("write", key, value)
        return is_new

    def put_if_absent(self, key, value):
        """Returns existing value, or None if the put happened."""
        ek, ev = self._ek(key), self._ev(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = self._raw_get_for_update(rec, ek)
            if old is not None:
                return self._dv(old)
            self._raw_put(rec, ek, ev)
            self._touch_version(rec)
        self._write_through("write", key, value)
        return None

    def fast_put_if_absent(self, key, value) -> bool:
        return self.put_if_absent(key, value) is None

    def put_all(self, entries: Dict) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for k, v in entries.items():
                self._raw_put(rec, self._ek(k), self._ev(v))
            self._touch_version(rec)
        for k, v in entries.items():
            self._write_through("write", k, v)

    def remove(self, key):
        """Returns removed value (RMap.remove)."""
        ek = self._ek(key)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = self._raw_get_for_update(rec, ek)
            if old is None:
                return None
            self._raw_del(rec, ek)
            self._touch_version(rec)
        self._write_through("delete", key)
        return self._dv(old)

    def fast_remove(self, *keys) -> int:
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for k in keys:
                if self._raw_del(rec, self._ek(k)):
                    n += 1
            if n:
                self._touch_version(rec)
        for k in keys:
            self._write_through("delete", k)
        return n

    def remove_if_equals(self, key, expected) -> bool:
        """RMap.remove(key, value) conditional."""
        ek, ev = self._ek(key), self._ev(expected)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if self._raw_get_for_update(rec, ek) != ev:
                return False
            self._raw_del(rec, ek)
            self._touch_version(rec)
        self._write_through("delete", key)
        return True

    def replace(self, key, value):
        """Set only if present; returns previous value."""
        ek, ev = self._ek(key), self._ev(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = self._raw_get_for_update(rec, ek)
            if old is None:
                return None
            self._raw_put(rec, ek, ev)
            self._touch_version(rec)
        self._write_through("write", key, value)
        return self._dv(old)

    def replace_if_equals(self, key, expected, update) -> bool:
        ek = self._ek(key)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if self._raw_get_for_update(rec, ek) != self._ev(expected):
                return False
            self._raw_put(rec, ek, self._ev(update))
            self._touch_version(rec)
        self._write_through("write", key, update)
        return True

    # -- java.util.Map compute family (RMap.compute*/merge; BaseMapTest
    # -- testCompute*/testMerge).  Built on the public ops under ONE record
    # -- lock so MapWriter/MapLoader/TTL semantics inherit; the functions
    # -- are plain callables (over the wire they travel pickled in the
    # -- OBJCALL frame, the serialized-task discipline).

    def compute(self, key, remapping):
        """remapping(key, old_or_None) -> new value, or None to remove."""
        with self._engine.locked(self._name):
            old = self.get(key)
            new = remapping(key, old)
            if new is None:
                if old is not None:
                    self.fast_remove(key)
                return None
            self.fast_put(key, new)
            return new

    def compute_if_absent(self, key, mapping):
        """mapping(key) computes a value only when absent; returns the
        current value either way (None when mapping returned None)."""
        with self._engine.locked(self._name):
            old = self.get(key)
            if old is not None:
                return old
            new = mapping(key)
            if new is not None:
                self.fast_put(key, new)
            return new

    def compute_if_present(self, key, remapping):
        with self._engine.locked(self._name):
            old = self.get(key)
            if old is None:
                return None
            new = remapping(key, old)
            if new is None:
                self.fast_remove(key)
                return None
            self.fast_put(key, new)
            return new

    def merge(self, key, value, remapping):
        """RMap.merge: absent -> value; present -> remapping(old, value);
        a None result removes the entry."""
        with self._engine.locked(self._name):
            old = self.get(key)
            new = value if old is None else remapping(old, value)
            if new is None:
                self.fast_remove(key)
                return None
            self.fast_put(key, new)
            return new

    # -- XX-style conditional puts (RMap.putIfExists/fastPutIfExists) --------
    # presence checks use _raw_get_for_update like replace(): a write-path
    # probe must neither read-through-load from a MapLoader (the XX contract
    # is about the HASH's contents) nor touch MapCache access tracking

    def put_if_exists(self, key, value):
        """Write only over an EXISTING entry; returns the previous value
        (None = absent, nothing written)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old_raw = self._raw_get_for_update(rec, self._ek(key))
            if old_raw is None:
                return None
            self.fast_put(key, value)
            return self._dv(old_raw)

    def fast_put_if_exists(self, key, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if self._raw_get_for_update(rec, self._ek(key)) is None:
                return False
            self.fast_put(key, value)
            return True

    def fast_replace(self, key, value) -> bool:
        """RMap.fastReplace: replace() without returning the old value."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if self._raw_get_for_update(rec, self._ek(key)) is None:
                return False
            self.fast_put(key, value)
            return True

    # -- per-key synchronizers (RMap.getLock(key)/getReadWriteLock(key)/
    # -- getSemaphore/getPermitExpirableSemaphore/getFairLock/
    # -- getCountDownLatch — entry-granular coordination, names derived
    # -- from the encoded key's hash like the reference's suffix scheme)

    def _key_object_name(self, key, kind: str) -> str:
        import hashlib

        h = hashlib.sha1(self._ek(key)).hexdigest()[:16]
        return f"{self._name}:{h}:{kind}"

    def get_lock(self, key):
        from redisson_tpu.client.objects.lock import Lock

        return Lock(self._engine, self._key_object_name(key, "lock"))

    def get_fair_lock(self, key):
        from redisson_tpu.client.objects.lock import FairLock

        return FairLock(self._engine, self._key_object_name(key, "fairlock"))

    def get_read_write_lock(self, key):
        from redisson_tpu.client.objects.lock import ReadWriteLock

        return ReadWriteLock(self._engine, self._key_object_name(key, "rwlock"))

    def get_semaphore(self, key):
        from redisson_tpu.client.objects.semaphore import Semaphore

        return Semaphore(self._engine, self._key_object_name(key, "semaphore"))

    def get_permit_expirable_semaphore(self, key):
        from redisson_tpu.client.objects.semaphore import PermitExpirableSemaphore

        return PermitExpirableSemaphore(
            self._engine, self._key_object_name(key, "psemaphore")
        )

    def get_count_down_latch(self, key):
        from redisson_tpu.client.objects.semaphore import CountDownLatch

        return CountDownLatch(self._engine, self._key_object_name(key, "latch"))

    # -- pattern scans (RMap.keySet/values/entrySet(pattern)) ----------------
    # str(k) matching keeps these agreeing with key_iterator(pattern) for
    # non-string keys; the key-only scan never decodes values

    def _entries_by_pattern(self, pattern: str):
        import fnmatch

        return [
            (k, v) for k, v in self.read_all_entry_set()
            if fnmatch.fnmatchcase(str(k), pattern)
        ]

    def key_set_by_pattern(self, pattern: str) -> List:
        import fnmatch

        return [
            k for k in self.read_all_keys()
            if fnmatch.fnmatchcase(str(k), pattern)
        ]

    def values_by_pattern(self, pattern: str) -> List:
        return [v for _k, v in self._entries_by_pattern(pattern)]

    def entry_set_by_pattern(self, pattern: str) -> List[Tuple[Any, Any]]:
        return self._entries_by_pattern(pattern)

    def add_and_get(self, key, delta):
        """Numeric field increment (RMap.addAndGet / HINCRBY Lua)."""
        ek = self._ek(key)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            raw = self._raw_get_for_update(rec, ek)
            cur = 0 if raw is None else self._dv(raw)
            if not isinstance(cur, (int, float)):
                raise TypeError(f"value at {key!r} is not numeric")
            new = cur + delta
            self._raw_put(rec, ek, self._ev(new))
            self._touch_version(rec)
        self._write_through("write", key, new)
        return new

    def clear(self) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.clear()
            self._touch_version(rec)

    def value_size(self, key) -> int:
        """Encoded byte size of one value (RMap.valueSize / HSTRLEN)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            raw = self._raw_get(rec, self._ek(key))
            return 0 if raw is None else len(raw)

    def random_keys(self, count: int) -> List:
        """HRANDFIELD-style sample of distinct LIVE keys (RMap.randomKeys) —
        the non-touching probe applies MapCache expiry without refreshing
        access tracking."""
        import random as _random

        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            keys = [
                k for k in list(rec.host.keys())
                # non-touching probe: sampling must not refresh max-idle
                # clocks or inflate LFU hit counts for every live entry
                if self._raw_get_for_update(rec, k) is not None
            ]
        return [self._dk(k) for k in _random.sample(keys, min(count, len(keys)))]

    def random_entries(self, count: int) -> Dict:
        """RMap.randomEntries — live entries only (expired cells reaped)."""
        import random as _random

        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            items = [
                (k, raw) for k in list(rec.host.keys())
                if (raw := self._raw_get_for_update(rec, k)) is not None
            ]
        picked = _random.sample(items, min(count, len(items)))
        return {self._dk(k): self._dv(raw) for k, raw in picked}

    def load_all(self, replace_existing: bool = False) -> int:
        """Warm the map from its MapLoader (RMap.loadAll); returns #loaded."""
        loader = self._options.loader
        if loader is None:
            return 0
        n = 0
        for key in loader.load_all_keys():
            ek = self._ek(key)
            if not replace_existing:
                with self._engine.locked(self._name):
                    rec = self._rec_or_create()
                    if self._raw_get_for_update(rec, ek) is not None:
                        continue
            # the loader may hit a slow backing store: NEVER under the
            # record lock, or every concurrent op on this map stalls per key
            loaded = loader.load(key)
            if loaded is None:
                continue
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                if not replace_existing and self._raw_get_for_update(rec, ek) is not None:
                    continue  # raced in while we were loading: keep it
                self._raw_put(rec, ek, self._ev(loaded))
                self._touch_version(rec)
                n += 1
        return n

    # dict-protocol sugar
    def __getitem__(self, key):
        v = self.get(key)
        if v is None:
            raise KeyError(key)
        return v

    def __setitem__(self, key, value):
        self.fast_put(key, value)

    def __contains__(self, key):
        return self.contains_key(key)

    def __len__(self):
        return self.size()


class MapCache(Map):
    """RMapCache: per-entry TTL / max-idle (RedissonMapCache.java).

    Entry layout: host[ek] = [ev, expire_at | None, max_idle | None,
    last_access, hit_count].  Expired entries are reaped lazily on access and
    by the EvictionScheduler sweep (eviction.py).  Four-element cells from
    older checkpoints are read transparently (hit_count treated as 0).

    Entry listeners (created/updated/removed/expired) publish on the
    reference's channel names (`RedissonMapCache.java:1767-1787`:
    `redisson_map_cache_<kind>:{name}`) through the engine hub, so embedded
    listeners AND wire pubsub subscribers observe the same events.  Delivery
    is async on the engine's single-worker events pool: mutation order is
    preserved, and user listeners never run under the record lock.

    Size-bounded mode (`trySetMaxSize`/`setMaxSize` + EvictionMode LRU|LFU,
    `RedissonMapCache.java:91-137`): inserts beyond max_size evict the
    least-recently-used (last_access) or least-frequently-used (hit_count)
    live entries, which are announced as `removed` events.
    """

    _kind = "map_cache"
    # TTL/max-idle expiry removes entries WITHOUT bumping the record version
    # (lazy reap on access), so (nonce, version) cannot key a scan view here
    _scan_view_safe = False

    EVENT_KINDS = ("created", "updated", "removed", "expired")

    def _now(self):
        return time.time()

    # -- entry events --------------------------------------------------------

    def entry_event_channel(self, kind: str) -> str:
        return f"redisson_map_cache_{kind}:{self._name}"

    def _emit(self, kind: str, ek: bytes, raw, old_raw=None) -> None:
        """Queue one listener event for async FIFO delivery.  No-op without
        subscribers so the unlistened hot path never pays decode cost."""
        hub = self._engine.pubsub
        ch = self.entry_event_channel(kind)
        if not hub.has_listeners(ch):
            return
        key = self._dk(ek)
        value = None if raw is None else self._dv(raw)
        old = None if old_raw is None else self._dv(old_raw)
        try:
            self._engine.events_pool.submit(hub.publish, ch, (key, value, old))
        except RuntimeError:
            pass  # engine shutting down: events are best-effort

    def add_entry_listener(self, kind: str, fn) -> Tuple[str, int]:
        """RMapCache.addListener analog; `kind` selects the listener
        interface (EntryCreated/Updated/Removed/ExpiredListener).  `fn` is
        called as fn(key, value, old_value); old_value is non-None only for
        'updated'.  Returns a token for remove_entry_listener."""
        if kind not in self.EVENT_KINDS:
            raise ValueError(f"unknown entry event kind: {kind!r}")
        ch = self.entry_event_channel(kind)
        lid = self._engine.pubsub.subscribe(ch, lambda _ch, msg: fn(*msg))
        return (kind, lid)

    def remove_entry_listener(self, token) -> None:
        kind, lid = token
        self._engine.pubsub.unsubscribe(self.entry_event_channel(kind), lid)

    # -- cell machinery ------------------------------------------------------

    def _live(self, rec, ek, touch=True):
        cell = rec.host.get(ek)
        if cell is None:
            return None
        now = self._now()
        if cell[1] is not None and now >= cell[1]:
            del rec.host[ek]
            self._emit("expired", ek, cell[0])
            return None
        if cell[2] is not None and now - cell[3] >= cell[2]:
            del rec.host[ek]
            self._emit("expired", ek, cell[0])
            return None
        if touch:
            cell[3] = now
            if len(cell) > 4:
                cell[4] += 1
        return cell[0]

    def _store_cell(self, rec, ek: bytes, ev: bytes, exp=None, max_idle=None):
        """Write one cell, emitting created|updated and enforcing max_size;
        returns the previous live raw value (None if absent)."""
        old = self._live(rec, ek, touch=False)
        # an update carries the access frequency forward: LFU must rank by
        # read history, and a write resetting it would turn the hottest key
        # into the next eviction victim
        prev = rec.host.get(ek)
        hits = prev[4] if (old is not None and prev is not None and len(prev) > 4) else 0
        rec.host[ek] = [ev, exp, max_idle, self._now(), hits]
        if old is None:
            self._emit("created", ek, ev)
            self._enforce_max_size(rec, keep=ek)
        else:
            self._emit("updated", ek, ev, old)
        return old

    def _raw_get(self, rec, ek: bytes):
        return self._live(rec, ek)

    def _raw_get_for_update(self, rec, ek: bytes):
        # writes fetch the old value WITHOUT touching access tracking:
        # a put must not refresh max-idle or count as an LFU hit
        return self._live(rec, ek, touch=False)

    def contains_value(self, value) -> bool:
        """Cells are [value, exp, idle, ...] lists — the base class's raw
        comparison never matches; compare the LIVE value per cell
        (RMapCache.containsValue skips expired entries the same way)."""
        ev = self._ev(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return any(
                self._live(rec, ek, touch=False) == ev
                for ek in list(rec.host.keys())
            )

    def _raw_put(self, rec, ek: bytes, ev: bytes):
        self._store_cell(rec, ek, ev)

    def _raw_del(self, rec, ek: bytes) -> bool:
        live = self._live(rec, ek, touch=False)
        if live is None:
            return False
        del rec.host[ek]
        self._emit("removed", ek, live)
        return True

    # -- size-bounded mode ---------------------------------------------------

    def try_set_max_size(self, max_size: int, mode: str = "LRU") -> bool:
        """Set the bound only if none exists yet (RMapCache.trySetMaxSize)."""
        self._check_max_size(max_size, mode)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if "max_size" in rec.meta:
                return False
            rec.meta["max_size"] = max_size
            rec.meta["eviction_mode"] = mode
            self._touch_version(rec)  # the bound must replicate/ship
            return True

    def set_max_size(self, max_size: int, mode: str = "LRU") -> None:
        """Set/replace the bound; an already-over-bound map is trimmed on
        the spot (the reference trims on the next write)."""
        self._check_max_size(max_size, mode)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.meta["max_size"] = max_size
            rec.meta["eviction_mode"] = mode
            self._enforce_max_size(rec)
            self._touch_version(rec)

    def get_max_size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else rec.meta.get("max_size", 0)

    @staticmethod
    def _check_max_size(max_size: int, mode: str) -> None:
        # 0 = unbounded (RedissonMapCache.trySetMaxSizeAsync only rejects
        # negatives); the set-once contract uses key PRESENCE, not truthiness
        if max_size < 0:
            raise ValueError("maxSize should not be negative")
        if mode not in ("LRU", "LFU"):
            raise ValueError(f"unknown eviction mode: {mode!r}")

    def _enforce_max_size(self, rec, keep: Optional[bytes] = None) -> None:
        mx = rec.meta.get("max_size") or 0
        if mx <= 0 or len(rec.host) <= mx:
            return
        # reap dead cells FIRST (emitting their honest 'expired' events):
        # counting them toward the bound would evict live entries while
        # expired ones hold the capacity
        for ek in list(rec.host.keys()):
            self._live(rec, ek, touch=False)
        if len(rec.host) <= mx:
            return
        lfu = rec.meta.get("eviction_mode") == "LFU"

        def rank(item):
            cell = item[1]
            if lfu:
                return cell[4] if len(cell) > 4 else 0
            return cell[3]  # last_access

        victims = sorted(
            (kv for kv in rec.host.items() if kv[0] != keep), key=rank
        )[: len(rec.host) - mx]
        for vek, vcell in victims:
            del rec.host[vek]
            self._emit("removed", vek, vcell[0])

    def put_with_ttl(
        self,
        key,
        value,
        ttl: Optional[float] = None,
        max_idle: Optional[float] = None,
    ):
        """RMapCache.put(key, value, ttl, maxIdle); returns previous value."""
        ek, ev = self._ek(key), self._ev(value)
        now = self._now()
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = self._store_cell(rec, ek, ev, now + ttl if ttl else None, max_idle)
            self._touch_version(rec)
        self._write_through("write", key, value)
        return None if old is None else self._dv(old)

    def put_if_absent_with_ttl(
        self, key, value, ttl: Optional[float] = None, max_idle: Optional[float] = None
    ):
        ek, ev = self._ek(key), self._ev(value)
        now = self._now()
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = self._live(rec, ek, touch=False)
            if old is not None:
                return self._dv(old)
            self._store_cell(rec, ek, ev, now + ttl if ttl else None, max_idle)
            self._touch_version(rec)
        self._write_through("write", key, value)
        return None

    def remain_time_to_live_entry(self, key) -> Optional[float]:
        """Remaining TTL of one entry; None if absent or no TTL."""
        ek = self._ek(key)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if self._live(rec, ek, touch=False) is None:
                return None
            exp = rec.host[ek][1]
            return None if exp is None else max(0.0, exp - self._now())

    def size(self) -> int:
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            for ek in list(rec.host.keys()):
                self._live(rec, ek, touch=False)
            return len(rec.host)

    def read_all_entry_set(self):
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return []
            out = []
            for ek in list(rec.host.keys()):
                ev = self._live(rec, ek, touch=False)
                if ev is not None:
                    out.append((self._dk(ek), self._dv(ev)))
            return out

    def read_all_keys(self):
        return [k for k, _ in self.read_all_entry_set()]

    def read_all_values(self):
        return [v for _, v in self.read_all_entry_set()]

    def reap_expired(self) -> int:
        """EvictionScheduler sweep entry point; returns entries removed."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            before = len(rec.host)
            for ek in list(rec.host.keys()):
                self._live(rec, ek, touch=False)
            return before - len(rec.host)
