"""Lock family: reentrant distributed locks.

Parity targets (SURVEY.md §2.5, §3.3):
  * RLock — ``org/redisson/RedissonLock.java:102-149,214-224,337-360`` +
    ``RedissonBaseLock.java:106-189``: reentrancy keyed by (client-id,
    thread-id), lease with watchdog renewal every lease/3, unlock message
    wakes waiters on ``redisson_lock__channel:{name}``.
  * RFairLock — ``RedissonFairLock.java``: FIFO grant order via a pending
    queue + per-waiter timeouts.
  * RReadWriteLock — ``RedissonReadWriteLock.java``: shared readers /
    exclusive writer, both reentrant; write-lock downgrade allowed.
  * RFencedLock — ``RedissonFencedLock.java``: monotonically increasing
    fencing token returned on acquire.
  * RSpinLock — ``RedissonSpinLock.java``: exponential-backoff polling, no
    wakeup channel.
  * RMultiLock / RedLock — ``RedissonMultiLock.java`` (512 LoC): acquire N
    locks within a wait budget, unlock all on failure.

The acquisition template is the reference's exactly: atomically
try-compare-and-mutate under the record lock (the Lua), park on a shared wait
entry (the pubsub channel), re-try on wakeup, renew/expire leases (the
watchdog) — with condition variables in place of network pubsub.
"""
from __future__ import annotations

import threading
import time
import uuid
from typing import Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord

DEFAULT_LEASE = 30.0  # lockWatchdogTimeout default (config/Config.java:71)


def unlock_channel(name: str) -> str:
    """Canonical unlock-wakeup channel for a lock name — the ONE definition
    both the engine publisher and the remote client's park subscribe to
    (pubsub/LockPubSub.java's redisson_lock__channel:{name})."""
    return f"redisson_lock__channel:{name}"


def _holder_id(engine) -> str:
    """uuid:threadId — the reference's LockName (RedissonBaseLock.getLockName).
    A remote caller's identity (set via engine.impersonate) wins, so locks
    taken over the wire belong to the client thread, not the server worker."""
    override = engine.holder_override()
    if override is not None:
        return override
    eid = getattr(engine, "_client_uuid", None)
    if eid is None:
        with _UUID_INIT_LOCK:
            eid = getattr(engine, "_client_uuid", None)
            if eid is None:
                eid = engine._client_uuid = uuid.uuid4().hex
    return f"{eid}:{threading.get_ident()}"


_UUID_INIT_LOCK = threading.Lock()


class Lock(RExpirable):
    _kind = "lock"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(kind=self._kind, host={"owner": None, "count": 0, "lease_until": None, "token": 0}),
        )

    def _wait(self):
        return self._engine.wait_entry(f"__lock__:{self._name}")

    def unlock_channel(self) -> str:
        """The wakeup channel remote waiters park on (the reference's
        redisson_lock__channel:{name}, pubsub/LockPubSub.java)."""
        return unlock_channel(self._name)

    def _publish_unlock(self) -> None:
        # wake REMOTE waiters parked on the unlock channel (LockPubSub's
        # UNLOCK_MESSAGE); in-process waiters ride _wait().signal()
        self._engine.pubsub.publish(self.unlock_channel(), b"0")

    def _expired(self, h) -> bool:
        return h["lease_until"] is not None and time.time() >= h["lease_until"]

    def _try_acquire(self, lease_time: Optional[float]) -> Optional[float]:
        """One atomic attempt (the tryLockInnerAsync Lua,
        RedissonLock.java:214-224).  None = acquired; else remaining ttl."""
        me = _holder_id(self._engine)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            h = rec.host
            if h["owner"] is None or h["count"] == 0 or self._expired(h):
                h["owner"] = me
                h["count"] = 1
                h["token"] += 1
                h["lease_until"] = time.time() + (lease_time or DEFAULT_LEASE)
                self._touch_version(rec)
                return None
            if h["owner"] == me:
                h["count"] += 1
                h["lease_until"] = time.time() + (lease_time or DEFAULT_LEASE)
                self._touch_version(rec)
                return None
            return max(0.0, (h["lease_until"] or time.time()) - time.time())

    def lock(self, lease_time: Optional[float] = None) -> None:
        """Blocking acquire (RedissonLock.lock:102-149 loop)."""
        while True:
            ttl = self._try_acquire(lease_time)
            if ttl is None:
                self._start_watchdog(lease_time)
                return
            self._wait().wait_for(min(ttl, 1.0) if ttl > 0 else 0.05)

    def try_lock(
        self, wait_time: float = 0.0, lease_time: Optional[float] = None
    ) -> bool:
        deadline = time.time() + wait_time
        while True:
            ttl = self._try_acquire(lease_time)
            if ttl is None:
                self._start_watchdog(lease_time)
                return True
            remaining = deadline - time.time()
            if remaining <= 0:
                return False
            self._wait().wait_for(min(remaining, ttl if ttl > 0 else 0.05, 1.0))

    def _start_watchdog(self, lease_time: Optional[float]):
        """scheduleExpirationRenewal (RedissonBaseLock.java:127-189): only when
        no explicit lease was given, renew every DEFAULT_LEASE/3 while held.

        Never started for impersonated (remote OBJCALL) holders: the
        reference's watchdog lives in the CLIENT process precisely so a dead
        client stops renewing and the lease expires — a server-side renewal
        under the client's identity would pin the lock forever.  Remote
        holders renew client-side (RemoteRedisson lock wrapper)."""
        if lease_time is not None or self._engine.holder_override() is not None:
            return
        me = _holder_id(self._engine)
        name = self._name
        engine = self._engine

        def renew() -> bool:
            with engine.locked(name):
                rec = engine.store.get(name)
                if rec is None or rec.host["owner"] != me or rec.host["count"] == 0:
                    return False  # stop renewing
                rec.host["lease_until"] = time.time() + DEFAULT_LEASE
            return True

        # one renewal per (lock, holder) on the SHARED wheel timer — never a
        # timer thread per lock (weak finding: 10k locks = 10k threads)
        engine.start_renewal(name, me, renew, DEFAULT_LEASE / 3)

    def renew_lease(self, lease_time: float = DEFAULT_LEASE) -> bool:
        """One explicit lease extension if still held by the caller — the
        remote client's watchdog tick (the PEXPIRE Lua of
        RedissonBaseLock.renewExpiration, driven client-side over the wire)."""
        me = _holder_id(self._engine)
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None or rec.host["owner"] != me or rec.host["count"] == 0:
                return False
            rec.host["lease_until"] = time.time() + lease_time
            return True

    def unlock(self) -> None:
        """RedissonLock.unlock:337-360: decrement reentrancy; on zero, release
        and publish the wakeup."""
        me = _holder_id(self._engine)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            h = rec.host
            if h["owner"] != me or h["count"] == 0:
                raise RuntimeError(
                    f"attempt to unlock lock '{self._name}' not held by current "
                    f"thread (IllegalMonitorStateException analog)"
                )
            h["count"] -= 1
            if h["count"] == 0:
                h["owner"] = None
                h["lease_until"] = None
            self._touch_version(rec)
            released = h["count"] == 0
        if released:
            # cancelExpirationRenewal (RedissonBaseLock.java) — don't leave a
            # pending wheel entry to discover the release a tick later
            self._engine.cancel_renewal(self._name, me)
            self._wait().signal()
            self._publish_unlock()

    def force_unlock(self) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            held = rec.host["count"] > 0
            rec.host.update(owner=None, count=0, lease_until=None)
            self._touch_version(rec)
        self._engine.cancel_renewal(self._name)  # every holder's watchdog
        self._wait().signal(all_=True)
        self._publish_unlock()
        return held

    def is_locked(self) -> bool:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return False
        h = rec.host
        return h["count"] > 0 and not self._expired(h)

    def is_held_by_current_thread(self) -> bool:
        rec = self._engine.store.get(self._name)
        return (
            rec is not None
            and rec.host["owner"] == _holder_id(self._engine)
            and rec.host["count"] > 0
            and not self._expired(rec.host)
        )

    def get_hold_count(self) -> int:
        rec = self._engine.store.get(self._name)
        if rec is None or rec.host["owner"] != _holder_id(self._engine):
            return 0
        return rec.host["count"]

    def remain_time_to_live_lock(self) -> Optional[float]:
        rec = self._engine.store.get(self._name)
        if rec is None or rec.host["lease_until"] is None:
            return None
        return max(0.0, rec.host["lease_until"] - time.time())

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()
        return False


class FencedLock(Lock):
    """RFencedLock: acquire returns a strictly monotonic fencing token."""

    _kind = "fenced_lock"

    def lock_and_get_token(self, lease_time: Optional[float] = None) -> int:
        self.lock(lease_time)
        return self.get_token()

    def try_lock_and_get_token(
        self, wait_time: float = 0.0, lease_time: Optional[float] = None
    ) -> Optional[int]:
        """Acquire + token in ONE atomic step: the token is read under the
        same record lock that performed the acquire, so a lapsed-lease steal
        between acquire and read cannot hand two holders the same token."""
        deadline = time.time() + wait_time
        while True:
            with self._engine.locked(self._name):
                if self._try_acquire(lease_time) is None:
                    tok = self._rec_or_create().host["token"]
                    self._start_watchdog(lease_time)
                    return int(tok)
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            self._wait().wait_for(min(remaining, 0.05))

    def get_token(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else rec.host["token"]


class SpinLock(Lock):
    """RSpinLock: no wakeup channel — exponential-backoff polling
    (RedissonSpinLock.java; initial 1ms, x2 up to 64ms)."""

    _kind = "spin_lock"

    def lock(self, lease_time: Optional[float] = None) -> None:
        delay = 0.001
        while self._try_acquire(lease_time) is not None:
            time.sleep(delay)
            delay = min(delay * 2, 0.064)
        self._start_watchdog(lease_time)

    def try_lock(self, wait_time: float = 0.0, lease_time: Optional[float] = None) -> bool:
        deadline = time.time() + wait_time
        delay = 0.001
        while True:
            if self._try_acquire(lease_time) is None:
                self._start_watchdog(lease_time)
                return True
            if time.time() >= deadline:
                return False
            time.sleep(min(delay, max(0.0, deadline - time.time())))
            delay = min(delay * 2, 0.064)


class FairLock(Lock):
    """RFairLock: FIFO ordering of waiters (RedissonFairLock Lua keeps a
    pending-threads list with per-waiter timeouts; here the queue lives in the
    record as (holder_id, refreshed_deadline) pairs).  A waiter refreshes its
    deadline on every acquisition attempt; entries whose deadline lapsed are
    pruned, so a waiter that died mid-wait cannot deadlock the head of the
    queue (the reference's Lua does the same timeout cleanup)."""

    _kind = "fair_lock"
    WAITER_TTL = 5.0  # must exceed the retry loop's longest park (1s)

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(
                kind=self._kind,
                host={"owner": None, "count": 0, "lease_until": None, "token": 0, "queue": []},
            ),
        )

    def _try_acquire(self, lease_time: Optional[float]) -> Optional[float]:
        me = _holder_id(self._engine)
        now = time.time()
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            h = rec.host
            h["queue"] = [(w, dl) for w, dl in h["queue"] if dl > now]  # prune dead
            q = h["queue"]
            if h["owner"] == me and h["count"] > 0 and not self._expired(h):
                h["count"] += 1
                h["lease_until"] = now + (lease_time or DEFAULT_LEASE)
                return None
            for i, (w, _dl) in enumerate(q):
                if w == me:
                    q[i] = (me, now + self.WAITER_TTL)  # refresh my deadline
                    break
            else:
                q.append((me, now + self.WAITER_TTL))
            if (h["owner"] is None or h["count"] == 0 or self._expired(h)) and q[0][0] == me:
                q.pop(0)
                h["owner"] = me
                h["count"] = 1
                h["token"] += 1
                h["lease_until"] = now + (lease_time or DEFAULT_LEASE)
                self._touch_version(rec)
                return None
            return max(0.0, (h["lease_until"] or now) - now) or 0.05

    def try_lock(self, wait_time: float = 0.0, lease_time: Optional[float] = None) -> bool:
        ok = super().try_lock(wait_time, lease_time)
        if not ok:  # leave the FIFO queue on timeout (Lua timeout cleanup)
            me = _holder_id(self._engine)
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                rec.host["queue"] = [(w, dl) for w, dl in rec.host["queue"] if w != me]
        return ok


class ReadWriteLock:
    """RReadWriteLock: returns reader/writer faces over shared state."""

    def __init__(self, engine, name, codec=None):
        self._engine = engine
        self._name = name

    def read_lock(self) -> "ReadLock":
        return ReadLock(self._engine, self._name)

    def write_lock(self) -> "WriteLock":
        return WriteLock(self._engine, self._name)


class _RWBase(RExpirable):
    _kind = "rw_lock"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(
                kind=self._kind,
                host={"mode": None, "writer": None, "write_count": 0, "readers": {}},
            ),
        )

    def _wait(self):
        return self._engine.wait_entry(f"__rwlock__:{self._name}")


class ReadLock(_RWBase):
    def try_lock(self, wait_time: float = 0.0) -> bool:
        me = _holder_id(self._engine)
        deadline = time.time() + wait_time
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                h = rec.host
                # readers admitted unless another thread holds write
                if h["write_count"] == 0 or h["writer"] == me:
                    h["readers"][me] = h["readers"].get(me, 0) + 1
                    h["mode"] = "read" if h["write_count"] == 0 else h["mode"]
                    self._touch_version(rec)
                    return True
            if time.time() >= deadline:
                return False
            self._wait().wait_for(min(1.0, deadline - time.time()))

    def lock(self) -> None:
        while not self.try_lock(1.0):
            pass

    def unlock(self) -> None:
        me = _holder_id(self._engine)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            h = rec.host
            n = h["readers"].get(me, 0)
            if n == 0:
                raise RuntimeError("read lock not held by current thread")
            if n == 1:
                del h["readers"][me]
            else:
                h["readers"][me] = n - 1
            if not h["readers"] and h["write_count"] == 0:
                h["mode"] = None
            self._touch_version(rec)
        self._wait().signal(all_=True)

    def is_locked(self) -> bool:
        rec = self._engine.store.get(self._name)
        return rec is not None and bool(rec.host["readers"])

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()
        return False


class WriteLock(_RWBase):
    def try_lock(self, wait_time: float = 0.0) -> bool:
        me = _holder_id(self._engine)
        deadline = time.time() + wait_time
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                h = rec.host
                others_reading = any(r != me for r in h["readers"])
                if (h["write_count"] == 0 or h["writer"] == me) and not others_reading:
                    # allowed: fresh write, write reentrancy, read->write upgrade
                    # only when sole reader (reference blocks upgrade; we allow
                    # sole-reader upgrade which is strictly less deadlock-prone)
                    h["writer"] = me
                    h["write_count"] += 1
                    h["mode"] = "write"
                    self._touch_version(rec)
                    return True
            if time.time() >= deadline:
                return False
            self._wait().wait_for(min(1.0, deadline - time.time()))

    def lock(self) -> None:
        while not self.try_lock(1.0):
            pass

    def unlock(self) -> None:
        me = _holder_id(self._engine)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            h = rec.host
            if h["writer"] != me or h["write_count"] == 0:
                raise RuntimeError("write lock not held by current thread")
            h["write_count"] -= 1
            if h["write_count"] == 0:
                h["writer"] = None
                h["mode"] = "read" if h["readers"] else None
            self._touch_version(rec)
        self._wait().signal(all_=True)

    def is_locked(self) -> bool:
        rec = self._engine.store.get(self._name)
        return rec is not None and rec.host["write_count"] > 0

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()
        return False


class MultiLock:
    """RMultiLock (RedissonMultiLock.java): all-or-nothing acquisition of a
    group of locks within a wait budget; base wait 1.5s per lock like the
    reference's baseWaitTime heuristic."""

    def __init__(self, *locks: Lock):
        if not locks:
            raise ValueError("MultiLock needs at least one lock")
        self._locks = list(locks)

    def try_lock(self, wait_time: float = 0.0, lease_time: Optional[float] = None) -> bool:
        deadline = time.time() + (wait_time or 1.5 * len(self._locks))
        acquired = []
        for lk in self._locks:
            remaining = max(0.0, deadline - time.time())
            if lk.try_lock(remaining, lease_time):
                acquired.append(lk)
            else:
                for a in reversed(acquired):
                    a.unlock()
                return False
        return True

    def lock(self, lease_time: Optional[float] = None) -> None:
        while not self.try_lock(0.0, lease_time):
            time.sleep(0.01)

    def unlock(self) -> None:
        errors = []
        for lk in reversed(self._locks):
            try:
                lk.unlock()
            except RuntimeError as e:
                errors.append(e)
        if errors:
            raise errors[0]

    def __enter__(self):
        self.lock()
        return self

    def __exit__(self, *exc):
        self.unlock()
        return False


class RedLock(MultiLock):
    """Deprecated in the reference (RedissonRedLock); kept for API parity —
    identical to MultiLock in a single-authority deployment."""
