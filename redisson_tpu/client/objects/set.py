"""Set family.

Parity targets:
  * RSet — ``org/redisson/RedissonSet.java`` (900 LoC): add/remove/contains,
    SSCAN iteration, union/intersection/diff (+ read/store variants),
    random/pop members, move.
  * RSetCache — ``RedissonSetCache.java`` (1,425 LoC): per-value TTL (the
    reference scores a ZSET by expiry; here expiry is stored per element).
  * RSortedSet / RLexSortedSet — ``RedissonSortedSet.java`` (510 LoC):
    comparator-ordered set.

Elements are codec-encoded (set membership = encoded equality, the reference
contract).
"""
from __future__ import annotations

import random
import time
from typing import Any, Iterable, Iterator, List, Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class Set(RExpirable):
    _kind = "set"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=set())
        )

    def _e(self, v) -> bytes:
        return self._codec.encode(v)

    def _d(self, raw: bytes):
        return self._codec.decode(raw)

    def add(self, value) -> bool:
        e = self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if e in rec.host:
                return False
            rec.host.add(e)
            self._touch_version(rec)
            return True

    # -- RSet round-4 surface: counted bulk ops, tryAdd, containsEach,
    # -- per-value synchronizers (RSet.java:39-75, 300-337)

    def add_all_counted(self, values: Iterable) -> int:
        """RSet.addAllCounted: number of elements actually ADDED."""
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for v in values:
                e = self._e(v)
                if e not in rec.host:
                    rec.host.add(e)
                    n += 1
            if n:
                self._touch_version(rec)
        return n

    def remove_all_counted(self, values: Iterable) -> int:
        """RSet.removeAllCounted: number of elements actually REMOVED."""
        n = 0
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            for v in values:
                e = self._e(v)
                if e in rec.host:
                    rec.host.discard(e)
                    n += 1
            if n:
                self._touch_version(rec)
        return n

    def try_add(self, *values) -> bool:
        """RSet.tryAdd: all-or-nothing — adds only when NONE are present."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            encoded = [self._e(v) for v in values]
            if any(e in rec.host for e in encoded):
                return False
            rec.host.update(encoded)
            self._touch_version(rec)
            return True

    def contains_each(self, values: Iterable) -> List:
        """RSet.containsEach: the subset of `values` present in the set."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [v for v in values if self._e(v) in rec.host]

    # per-value synchronizers: each value gets its own lock/semaphore/latch
    # namespace derived from the set name + the encoded value (the
    # reference suffixes the value's hash the same way)

    def _value_object_name(self, value, kind: str) -> str:
        import hashlib

        h = hashlib.sha1(self._e(value)).hexdigest()[:16]
        return f"{self._name}:{h}:{kind}"

    def get_lock(self, value):
        from redisson_tpu.client.objects.lock import Lock

        return Lock(self._engine, self._value_object_name(value, "lock"))

    def get_fair_lock(self, value):
        from redisson_tpu.client.objects.lock import FairLock

        return FairLock(self._engine, self._value_object_name(value, "fairlock"))

    def get_read_write_lock(self, value):
        from redisson_tpu.client.objects.lock import ReadWriteLock

        return ReadWriteLock(self._engine, self._value_object_name(value, "rwlock"))

    def get_semaphore(self, value):
        from redisson_tpu.client.objects.semaphore import Semaphore

        return Semaphore(self._engine, self._value_object_name(value, "semaphore"))

    def get_permit_expirable_semaphore(self, value):
        from redisson_tpu.client.objects.semaphore import PermitExpirableSemaphore

        return PermitExpirableSemaphore(
            self._engine, self._value_object_name(value, "psemaphore")
        )

    def get_count_down_latch(self, value):
        from redisson_tpu.client.objects.semaphore import CountDownLatch

        return CountDownLatch(self._engine, self._value_object_name(value, "latch"))

    def add_all(self, values: Iterable) -> bool:
        changed = False
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for v in values:
                e = self._e(v)
                if e not in rec.host:
                    rec.host.add(e)
                    changed = True
            if changed:
                self._touch_version(rec)
        return changed

    def remove(self, value) -> bool:
        e = self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if e not in rec.host:
                return False
            rec.host.discard(e)
            self._touch_version(rec)
            return True

    def remove_all(self, values: Iterable) -> bool:
        changed = False
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for v in values:
                if self._e(v) in rec.host:
                    rec.host.discard(self._e(v))
                    changed = True
            if changed:
                self._touch_version(rec)
        return changed

    def retain_all(self, values: Iterable) -> bool:
        keep = {self._e(v) for v in values}
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            before = len(rec.host)
            rec.host &= keep
            if len(rec.host) != before:
                self._touch_version(rec)
                return True
            return False

    def contains(self, value) -> bool:
        rec = self._engine.store.get(self._name)
        return rec is not None and self._e(value) in rec.host

    def contains_all(self, values: Iterable) -> bool:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return False
        return all(self._e(v) in rec.host for v in values)

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)

    def is_empty(self) -> bool:
        return self.size() == 0

    def read_all(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(e) for e in list(rec.host)]

    def __iter__(self) -> Iterator:
        return iter(self.read_all())

    def __len__(self):
        return self.size()

    def __contains__(self, value):
        return self.contains(value)

    def random_member(self):
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return None
        return self._d(random.choice(list(rec.host)))

    def random_members(self, count: int) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        pool = list(rec.host)
        return [self._d(e) for e in random.sample(pool, min(count, len(pool)))]

    def remove_random(self):
        """SPOP."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not rec.host:
                return None
            e = random.choice(list(rec.host))
            rec.host.discard(e)
            self._touch_version(rec)
            return self._d(e)

    def move(self, dest_name: str, value) -> bool:
        """SMOVE (RedissonSet.move)."""
        e = self._e(value)
        dest_h = Set(self._engine, dest_name, self._codec)  # maps dest_name
        with self._engine.locked_many((self._name, dest_h._name)):
            rec = self._rec_or_create()
            if e not in rec.host:
                return False
            dest = dest_h._rec_or_create()
            rec.host.discard(e)
            dest.host.add(e)
            self._touch_version(rec)
            self._touch_version(dest)
            return True

    # -- set algebra (SUNION/SINTER/SDIFF + STORE variants) ------------------

    def _others(self, names):
        """`names` are STORED keys (callers map logical operands once)."""
        out = []
        for nm in names:
            rec = self._engine.store.get(nm)
            out.append(set() if rec is None else set(rec.host))
        return out

    def read_union(self, *names: str) -> List:
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            acc = set(rec.host)
            for s in self._others(names):
                acc |= s
        return [self._d(e) for e in acc]

    def read_intersection(self, *names: str) -> List:
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            acc = set(rec.host)
            for s in self._others(names):
                acc &= s
        return [self._d(e) for e in acc]

    def read_diff(self, *names: str) -> List:
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            acc = set(rec.host)
            for s in self._others(names):
                acc -= s
        return [self._d(e) for e in acc]

    def union(self, *names: str) -> int:
        """SUNIONSTORE into this set; returns resulting size."""
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            acc = set()
            for s in self._others((self._name, *names)):
                acc |= s
            rec.host.clear()
            rec.host |= acc
            self._touch_version(rec)
            return len(rec.host)

    def intersection(self, *names: str) -> int:
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            sets = self._others((self._name, *names))
            acc = sets[0]
            for s in sets[1:]:
                acc &= s
            rec.host.clear()
            rec.host |= acc
            self._touch_version(rec)
            return len(rec.host)

    def diff(self, *names: str) -> int:
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            sets = self._others((self._name, *names))
            acc = sets[0]
            for s in sets[1:]:
                acc -= s
            rec.host.clear()
            rec.host |= acc
            self._touch_version(rec)
            return len(rec.host)


class SetCache(RExpirable):
    """RSetCache: add(value, ttl) with per-value expiry."""

    _kind = "set_cache"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={})
        )

    def _e(self, v) -> bytes:
        return self._codec.encode(v)

    def _d(self, raw: bytes):
        return self._codec.decode(raw)

    def _live(self, rec, e, now=None) -> bool:
        exp = rec.host.get(e, _MISSING)
        if exp is _MISSING:
            return False
        if exp is not None and (now or time.time()) >= exp:
            del rec.host[e]
            return False
        return True

    def add(self, value, ttl: Optional[float] = None) -> bool:
        e = self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            fresh = not self._live(rec, e)
            rec.host[e] = time.time() + ttl if ttl else None
            self._touch_version(rec)
            return fresh

    def contains(self, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return self._live(rec, self._e(value))

    def remove(self, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            e = self._e(value)
            live = self._live(rec, e)
            rec.host.pop(e, None)
            if live:
                self._touch_version(rec)
            return live

    def size(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            now = time.time()
            for e in list(rec.host.keys()):
                self._live(rec, e, now)
            return len(rec.host)

    def read_all(self) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            now = time.time()
            return [self._d(e) for e in list(rec.host.keys()) if self._live(rec, e, now)]

    def reap_expired(self) -> int:
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            before = len(rec.host)
            now = time.time()
            for e in list(rec.host.keys()):
                self._live(rec, e, now)
            return before - len(rec.host)


_MISSING = object()


class SortedSet(RExpirable):
    """RSortedSet: natural/comparator ordering over distinct values.

    The reference keeps a Redis LIST in sorted order guarded by a lock
    (RedissonSortedSet.java); here a sorted host list under the record lock.
    """

    _kind = "sorted_set"

    def __init__(self, engine, name, codec=None, key=None):
        super().__init__(engine, name, codec)
        self._key = key  # comparator analog: sort key over *decoded* values

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=[])
        )

    def _sortkey(self, v):
        return self._key(v) if self._key else v

    def add(self, value) -> bool:
        import bisect

        e = self._codec.encode(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            decoded = [self._codec.decode(x) for x in rec.host]
            if value in decoded:
                return False
            keys = [self._sortkey(d) for d in decoded]
            i = bisect.bisect_right(keys, self._sortkey(value))
            rec.host.insert(i, e)
            self._touch_version(rec)
            return True

    def add_all(self, values: Iterable) -> bool:
        changed = False
        for v in values:
            changed |= self.add(v)
        return changed

    def remove(self, value) -> bool:
        e = self._codec.encode(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            try:
                rec.host.remove(e)
            except ValueError:
                return False
            self._touch_version(rec)
            return True

    def contains(self, value) -> bool:
        rec = self._engine.store.get(self._name)
        return rec is not None and self._codec.encode(value) in rec.host

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)

    def read_all(self) -> List:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._codec.decode(e) for e in list(rec.host)]

    def first(self):
        vals = self.read_all()
        return vals[0] if vals else None

    def last(self):
        vals = self.read_all()
        return vals[-1] if vals else None

    def __iter__(self):
        return iter(self.read_all())


class LexSortedSet(SortedSet):
    """RLexSortedSet: string elements in lexicographic order with range ops."""

    _kind = "lex_sorted_set"

    def __init__(self, engine, name, codec=None):
        from redisson_tpu.client.codec import StringCodec

        super().__init__(engine, name, StringCodec())

    def range(self, from_value: str, from_inclusive: bool, to_value: str, to_inclusive: bool) -> List[str]:
        out = []
        for v in self.read_all():
            lo_ok = v > from_value or (from_inclusive and v == from_value)
            hi_ok = v < to_value or (to_inclusive and v == to_value)
            if lo_ok and hi_ok:
                out.append(v)
        return out

    def range_head(self, to_value: str, inclusive: bool) -> List[str]:
        return [v for v in self.read_all() if v < to_value or (inclusive and v == to_value)]

    def range_tail(self, from_value: str, inclusive: bool) -> List[str]:
        return [v for v in self.read_all() if v > from_value or (inclusive and v == from_value)]

    def count(self, from_value: str, from_inclusive: bool, to_value: str, to_inclusive: bool) -> int:
        return len(self.range(from_value, from_inclusive, to_value, to_inclusive))
