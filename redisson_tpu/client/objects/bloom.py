"""BloomFilter: the north-star object (BASELINE.md configs 1, 2, 5).

Parity target: ``org/redisson/RedissonBloomFilter.java`` —
  * geometry: optimalNumOfBits / optimalNumOfHashFunctions (:262-299, the
    Guava formulas), persisted config with optimistic concurrency (:203-213),
  * add/contains over k hashed bit positions (:90-196),
  * count() estimate from BITCOUNT.

TPU-first redesign: where the reference turns an N-key batch into k*N SETBIT/
GETBIT commands pipelined to Redis (SURVEY.md §3.4 — the hot loop), here the
whole batch is ONE kernel: hash on device, gather/scatter over the resident
bit plane, single boolean vector back.  Single-key calls ride the same path
with a 1-element batch (and are the slow path by design — batch or use
RBatch, exactly like the reference).
"""
from __future__ import annotations

import math
from typing import Iterable, Optional

import numpy as np

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import bittensor as bt
from redisson_tpu.utils import hashing as H


def optimal_num_of_bits(n: int, p: float) -> int:
    """RedissonBloomFilter.java:284-290 (Guava): m = -n ln p / (ln 2)^2."""
    if p == 0:
        p = 4.9e-324
    return int(-n * math.log(p) / (math.log(2) ** 2))


def optimal_num_of_hash_functions(n: int, m: int) -> int:
    """RedissonBloomFilter.java:292-298: k = max(1, round(m/n * ln 2))."""
    return max(1, round(m / max(1, n) * math.log(2)))


class BloomFilter(RExpirable):
    MAX_SIZE = 2**31 - 1024  # int32 index space minus plane padding

    # -- init / config ------------------------------------------------------

    def try_init(self, expected_insertions: int, false_probability: float) -> bool:
        """Create the filter config+plane; False if it already exists
        (RedissonBloomFilter.java:203-238 tryInit semantics)."""
        if not 0 < false_probability < 1:
            raise ValueError("false probability must be in (0, 1)")
        if expected_insertions <= 0:
            raise ValueError("expected insertions must be positive")
        m = optimal_num_of_bits(expected_insertions, false_probability)
        if m > self.MAX_SIZE:
            raise ValueError(f"bloom filter size {m} exceeds max {self.MAX_SIZE}")
        k = optimal_num_of_hash_functions(expected_insertions, m)
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False

            def factory():
                return StateRecord(
                    kind="bloom",
                    meta={
                        "n": expected_insertions,
                        "p": false_probability,
                        "m": m,
                        "k": k,
                        "hash": H.HASH_NAME,
                    },
                    arrays={"bits": bt.make(m)},
                )

            self._engine.store.get_or_create(self._name, "bloom", factory)
            return True

    def _rec(self) -> StateRecord:
        rec = self._engine.store.get(self._name)
        if rec is None:
            raise RuntimeError(f"Bloom filter '{self._name}' is not initialized")
        if rec.meta.get("hash") != H.HASH_NAME:
            raise RuntimeError(
                f"Bloom filter '{self._name}' was built with hash "
                f"{rec.meta.get('hash')!r}, runtime is {H.HASH_NAME!r}"
            )
        return rec

    # -- geometry accessors (reference getter parity) -----------------------

    def get_expected_insertions(self) -> int:
        return self._rec().meta["n"]

    def get_false_probability(self) -> float:
        return self._rec().meta["p"]

    def get_size(self) -> int:
        return self._rec().meta["m"]

    def get_hash_iterations(self) -> int:
        return self._rec().meta["k"]

    # -- data plane ---------------------------------------------------------

    def add(self, obj) -> bool:
        """True iff the element was (probably) newly added."""
        return bool(self.add_all([obj] if not isinstance(obj, np.ndarray) else obj))

    def add_all(self, objs) -> int:
        """Batch add; returns the number of (probably) new elements
        (RedissonBloomFilter.java:105-137 contract)."""
        return int(self.add_all_async(objs))

    def add_all_async(self, objs):
        """Pipelined add: newly-added count as a DEVICE scalar (4-byte result
        path, no host sync) — streaming writers dispatch flush after flush and
        only the final int() waits."""
        kind, arrays, n = self._engine.pack_keys(objs, self._codec)
        if n == 0:
            return np.int32(0)
        with self._engine.locked(self._name):
            rec = self._rec()
            m, k = rec.meta["m"], rec.meta["k"]
            bits = rec.arrays["bits"]
            if kind == "u64":
                bits, count = K.bloom_add_packed_count(bits, arrays, K.valid_n(n), k, m)
            else:
                words, nbytes = arrays
                bits, newly = K.bloom_add_bytes_masked(bits, words, nbytes, n, k, m)
                count = newly.astype(np.int32).sum()
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return count

    def add_each(self, objs) -> np.ndarray:
        """Batch add; returns a per-key "was newly added" bool array aligned
        with objs (the BF.MADD reply shape)."""
        newly, n = self.add_each_async(objs)
        return np.asarray(newly)[:n]

    def add_each_async(self, objs):
        """Pipelined batch add: (device newly-added array, n_valid) with NO
        host sync — the mutation is dispatched; callers force later (the
        frame-level lazy-reply path in server/registry.py, and streaming
        writers that keep flushes in flight)."""
        kind, arrays, n = self._engine.pack_keys(objs, self._codec)
        if n == 0:
            return np.zeros((0,), bool), 0
        with self._engine.locked(self._name):
            rec = self._rec()
            m, k = rec.meta["m"], rec.meta["k"]
            bits = rec.arrays["bits"]
            if kind == "u64":
                bits, newly = K.bloom_add_packed(bits, arrays, K.valid_n(n), k, m)
            else:
                words, nbytes = arrays
                bits, newly = K.bloom_add_bytes_masked(bits, words, nbytes, n, k, m)
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return newly, n

    def contains(self, obj) -> bool:
        if isinstance(obj, np.ndarray):
            raise TypeError("use contains_each / count_contains for batches")
        return bool(self.contains_each([obj])[0])

    def contains_each(self, objs) -> np.ndarray:
        """Vectorized membership: bool array aligned with objs."""
        found, n = self.contains_each_async(objs)
        arr = np.asarray(found)
        if arr.dtype == np.uint32:  # packed-bitmap fast path (u64 keys)
            return K.unpack_found(arr, n)
        return arr[:n]

    def contains_each_async(self, objs):
        """Pipelined membership with no host sync — the RBatch executeAsync
        analog (keep several flushes in flight, force later; see
        BloomFilterArray.contains_async).  For integer-key batches the result
        is a device uint32 bitmap (decode with kernels.unpack_found); for
        codec-encoded keys it is a device bool array."""
        kind, arrays, n = self._engine.pack_keys(objs, self._codec, cache_hot=True)
        if n == 0:
            return np.zeros((0,), np.uint32), 0
        # Dispatch under the record lock: a concurrent add() donates the bit
        # plane, which would invalidate the buffer between our read of
        # rec.arrays and the kernel call.  The device-side result fetch
        # happens outside the lock.
        with self._engine.locked(self._name):
            rec = self._rec()
            m, k = rec.meta["m"], rec.meta["k"]
            bits = rec.arrays["bits"]
            if kind == "u64":
                found = K.bloom_contains_packed_bits(bits, arrays, K.valid_n(n), k, m)
            else:
                words, nbytes = arrays
                found = K.bloom_contains_bytes_masked(bits, words, nbytes, n, k, m)
        return found, n

    def count_contains(self, objs) -> int:
        """Number of objs (probably) present — reference contains(Collection)."""
        return int(self.contains_each(objs).sum())

    def count(self) -> int:
        """Approximate cardinality from the fill ratio
        (RedissonBloomFilter.java count(): X = BITCOUNT; -m/k * ln(1 - X/m))."""
        with self._engine.locked(self._name):
            rec = self._rec()
            m, k = rec.meta["m"], rec.meta["k"]
            x = int(K.bitset_popcount(rec.arrays["bits"], m))
        if x == 0:
            return 0
        if x >= m:
            return rec.meta["n"]
        return int(round(-m / k * math.log1p(-x / m)))
