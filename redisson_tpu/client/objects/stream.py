"""Stream: append-only log with consumer groups.

Parity target: RStream — ``org/redisson/RedissonStream.java`` (1,441 LoC):
XADD (auto/explicit ids), XLEN, XRANGE/XREVRANGE, XREAD, XREADGROUP with
consumer PELs, XACK, XCLAIM/XAUTOCLAIM, XPENDING, XTRIM, XDEL,
createGroup/removeGroup/createConsumer.

Entry ids follow Redis '<ms>-<seq>' ordering and auto-generation rules.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord

StreamId = Tuple[int, int]


def parse_id(s) -> StreamId:
    if isinstance(s, tuple):
        return s
    if s in ("-",):
        return (0, 0)
    if s in ("+",):
        return (1 << 62, 1 << 62)
    if "-" in str(s):
        ms, seq = str(s).split("-")
        return (int(ms), int(seq))
    return (int(s), 0)


def fmt_id(i: StreamId) -> str:
    return f"{i[0]}-{i[1]}"


class Stream(RExpirable):
    _kind = "stream"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(
                kind=self._kind,
                host={"entries": [], "last_id": (0, 0), "groups": {}},
            ),
        )

    def _wait(self):
        return self._engine.wait_entry(f"__stream__:{self._name}")

    # -- producing ----------------------------------------------------------

    def add(self, fields: Dict[Any, Any], id: Optional[str] = None) -> str:
        """XADD; returns the entry id."""
        enc = {self._codec.encode_map_key(k): self._codec.encode_map_value(v) for k, v in fields.items()}
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if id is None or id == "*":
                ms = int(time.time() * 1000)
                last = rec.host["last_id"]
                eid = (ms, last[1] + 1) if ms <= last[0] else (ms, 0)
                if eid <= last:
                    eid = (last[0], last[1] + 1)
            else:
                eid = parse_id(id)
                if eid <= rec.host["last_id"]:
                    raise ValueError(
                        "The ID specified in XADD is equal or smaller than the "
                        "target stream top item"
                    )
            rec.host["entries"].append((eid, enc))
            rec.host["last_id"] = eid
            self._touch_version(rec)
        self._wait().signal(all_=True)
        return fmt_id(eid)

    def trim(self, max_len: int) -> int:
        """XTRIM MAXLEN."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            drop = max(0, len(rec.host["entries"]) - max_len)
            rec.host["entries"] = rec.host["entries"][drop:]
            if drop:
                self._touch_version(rec)
            return drop

    def trim_by_min_id(self, min_id: str) -> int:
        """XTRIM MINID: drop every entry with an id BELOW min_id (the second
        trim strategy, RedissonStream StreamTrimArgs.minId)."""
        lo = parse_id(min_id)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            before = len(rec.host["entries"])
            rec.host["entries"] = [(i, f) for i, f in rec.host["entries"] if i >= lo]
            drop = before - len(rec.host["entries"])
            if drop:
                self._touch_version(rec)
            return drop

    def last_id(self) -> Optional[str]:
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host["entries"]:
            return None
        return fmt_id(rec.host["entries"][-1][0])

    def remove(self, *ids: str) -> int:
        """XDEL."""
        targets = {parse_id(i) for i in ids}
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            before = len(rec.host["entries"])
            rec.host["entries"] = [(i, f) for i, f in rec.host["entries"] if i not in targets]
            n = before - len(rec.host["entries"])
            if n:
                self._touch_version(rec)
            return n

    # -- reading ------------------------------------------------------------

    def _decode(self, enc: Dict[bytes, bytes]) -> Dict:
        return {
            self._codec.decode_map_key(k): self._codec.decode_map_value(v)
            for k, v in enc.items()
        }

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host["entries"])

    def range(self, from_id: str = "-", to_id: str = "+", count: Optional[int] = None) -> Dict[str, Dict]:
        lo, hi = parse_id(from_id), parse_id(to_id)
        rec = self._engine.store.get(self._name)
        if rec is None:
            return {}
        out = {}
        for eid, enc in rec.host["entries"]:
            if lo <= eid <= hi:
                out[fmt_id(eid)] = self._decode(enc)
                if count is not None and len(out) >= count:
                    break
        return out

    def rev_range(self, from_id: str = "+", to_id: str = "-", count: Optional[int] = None) -> Dict[str, Dict]:
        hi, lo = parse_id(from_id), parse_id(to_id)
        rec = self._engine.store.get(self._name)
        if rec is None:
            return {}
        out = {}
        for eid, enc in reversed(rec.host["entries"]):
            if lo <= eid <= hi:
                out[fmt_id(eid)] = self._decode(enc)
                if count is not None and len(out) >= count:
                    break
        return out

    def read(self, from_id: str = "0", count: Optional[int] = None, timeout: float = 0.0) -> Dict[str, Dict]:
        """XREAD: entries strictly after from_id; optionally blocking."""
        after = parse_id(from_id)
        deadline = time.time() + timeout
        while True:
            rec = self._engine.store.get(self._name)
            out = {}
            if rec is not None:
                for eid, enc in rec.host["entries"]:
                    if eid > after:
                        out[fmt_id(eid)] = self._decode(enc)
                        if count is not None and len(out) >= count:
                            break
            if out or time.time() >= deadline:
                return out
            self._wait().wait_for(max(0.0, deadline - time.time()))

    # -- consumer groups ------------------------------------------------------

    def create_group(self, group: str, from_id: str = "$") -> None:
        """XGROUP CREATE ($ = only new entries)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if group in rec.host["groups"]:
                raise ValueError(f"BUSYGROUP consumer group '{group}' already exists")
            start = rec.host["last_id"] if from_id == "$" else parse_id(from_id)
            rec.host["groups"][group] = {"last_delivered": start, "pel": {}, "consumers": {}}
            self._touch_version(rec)

    def remove_group(self, group: str) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["groups"].pop(group, None)
            self._touch_version(rec)

    def _group(self, rec, group: str) -> dict:
        g = rec.host["groups"].get(group)
        if g is None:
            raise KeyError(f"NOGROUP no such consumer group '{group}'")
        return g

    def read_group(
        self,
        group: str,
        consumer: str,
        count: Optional[int] = None,
        timeout: float = 0.0,
        from_id: str = ">",
    ) -> Dict[str, Dict]:
        """XREADGROUP: '>' delivers new entries into the consumer's PEL;
        an explicit id re-reads that consumer's pending entries."""
        deadline = time.time() + timeout
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                g = self._group(rec, group)
                g["consumers"].setdefault(consumer, time.time())
                out = {}
                if from_id == ">":
                    for eid, enc in rec.host["entries"]:
                        if eid > g["last_delivered"]:
                            g["pel"][eid] = [consumer, time.time(), 1]
                            g["last_delivered"] = eid
                            out[fmt_id(eid)] = self._decode(enc)
                            if count is not None and len(out) >= count:
                                break
                else:
                    after = parse_id(from_id)
                    entries = {i: f for i, f in rec.host["entries"]}
                    for eid, (owner, _, _) in sorted(g["pel"].items()):
                        if owner == consumer and eid > after and eid in entries:
                            out[fmt_id(eid)] = self._decode(entries[eid])
                            if count is not None and len(out) >= count:
                                break
                if out:
                    self._touch_version(rec)
                    return out
            if time.time() >= deadline:
                return {}
            self._wait().wait_for(max(0.0, deadline - time.time()))

    def ack(self, group: str, *ids: str) -> int:
        """XACK."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            g = self._group(rec, group)
            n = 0
            for i in ids:
                if g["pel"].pop(parse_id(i), None) is not None:
                    n += 1
            if n:
                self._touch_version(rec)
            return n

    def pending_range(
        self, group: str, from_id: str = "-", to_id: str = "+", count: Optional[int] = None,
        consumer: Optional[str] = None,
    ) -> List[dict]:
        """XPENDING (extended form)."""
        lo, hi = parse_id(from_id), parse_id(to_id)
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        g = self._group(rec, group)
        out = []
        for eid, (owner, delivered_at, n_deliv) in sorted(g["pel"].items()):
            if lo <= eid <= hi and (consumer is None or owner == consumer):
                out.append(
                    {
                        "id": fmt_id(eid),
                        "consumer": owner,
                        "idle": time.time() - delivered_at,
                        "delivered": n_deliv,
                    }
                )
                if count is not None and len(out) >= count:
                    break
        return out

    def claim(
        self, group: str, consumer: str, min_idle: float, *ids: str, force: bool = False
    ) -> Dict[str, Dict]:
        """XCLAIM: transfer ownership of idle pending entries.  `force`
        creates a PEL entry for an existing stream entry that nobody has
        delivered yet (XCLAIM FORCE semantics)."""
        targets = [parse_id(i) for i in ids]
        now = time.time()
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            g = self._group(rec, group)
            g["consumers"].setdefault(consumer, now)  # XCLAIM auto-creates
            entries = {i: f for i, f in rec.host["entries"]}
            out = {}
            for eid in targets:
                cell = g["pel"].get(eid)
                if cell is None:
                    if not (force and eid in entries):
                        continue
                    cell = [consumer, 0.0, 0]  # fresh forced claim
                elif now - cell[1] < min_idle:
                    continue
                g["pel"][eid] = [consumer, now, cell[2] + 1]
                if eid in entries:
                    out[fmt_id(eid)] = self._decode(entries[eid])
            if out:
                self._touch_version(rec)
            return out

    def auto_claim(
        self, group: str, consumer: str, min_idle: float, start_id: str = "0", count: int = 100
    ) -> Tuple[str, Dict[str, Dict]]:
        """XAUTOCLAIM: scan the PEL from start_id, claiming idle entries."""
        after = parse_id(start_id)
        now = time.time()
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            g = self._group(rec, group)
            g["consumers"].setdefault(consumer, now)  # XAUTOCLAIM auto-creates
            entries = {i: f for i, f in rec.host["entries"]}
            out = {}
            cursor = (0, 0)
            for eid, cell in sorted(g["pel"].items()):
                if eid < after:
                    continue
                if len(out) >= count:
                    cursor = eid
                    break
                if now - cell[1] >= min_idle:
                    g["pel"][eid] = [consumer, now, cell[2] + 1]
                    if eid in entries:
                        out[fmt_id(eid)] = self._decode(entries[eid])
            if out:
                self._touch_version(rec)
            return fmt_id(cursor), out

    def pending_summary(self, group: str) -> dict:
        """XPENDING (summary form): total, smallest/largest pending id, and
        per-consumer pending counts."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return {"total": 0, "min_id": None, "max_id": None, "consumers": {}}
        g = self._group(rec, group)
        per: Dict[str, int] = {}
        ids = sorted(g["pel"])
        for _eid, (owner, _t, _n) in g["pel"].items():
            per[owner] = per.get(owner, 0) + 1
        return {
            "total": len(ids),
            "min_id": fmt_id(ids[0]) if ids else None,
            "max_id": fmt_id(ids[-1]) if ids else None,
            "consumers": per,
        }

    def create_consumer(self, group: str, consumer: str) -> bool:
        """XGROUP CREATECONSUMER; True if the consumer is new."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            g = self._group(rec, group)
            fresh = consumer not in g["consumers"]
            g["consumers"].setdefault(consumer, time.time())
            if fresh:
                self._touch_version(rec)
            return fresh

    def remove_consumer(self, group: str, consumer: str) -> int:
        """XGROUP DELCONSUMER: drop a consumer, DISCARDING its pending
        entries (Redis semantics); returns #pending discarded."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            g = self._group(rec, group)
            mine = [eid for eid, cell in g["pel"].items() if cell[0] == consumer]
            for eid in mine:
                del g["pel"][eid]
            g["consumers"].pop(consumer, None)
            if mine:
                self._touch_version(rec)
            return len(mine)

    def set_group_id(self, group: str, from_id: str) -> None:
        """XGROUP SETID: move the group's last-delivered cursor."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            g = self._group(rec, group)
            g["last_delivered"] = parse_id(from_id) if from_id != "$" else (
                rec.host["entries"][-1][0] if rec.host["entries"] else (0, 0)
            )
            self._touch_version(rec)

    def list_groups(self) -> List[str]:
        rec = self._engine.store.get(self._name)
        return [] if rec is None else list(rec.host["groups"])

    def list_consumers(self, group: str) -> List[str]:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return list(self._group(rec, group)["consumers"])
