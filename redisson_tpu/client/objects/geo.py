"""Geo: geospatial member index.

Parity target: RGeo — ``org/redisson/RedissonGeo.java`` (984 LoC): GEOADD,
GEODIST (m/km/mi/ft), GEOPOS, GEOHASH, GEOSEARCH by radius/box around a
member or a point, with count/order options, and ...StoreTo variants.

TPU-first: distance evaluation is a *vectorized haversine over all members*
(numpy today, trivially jit-able) — the data-parallel re-expression of the
server-side geo index walk.
"""
from __future__ import annotations

import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord

EARTH_RADIUS_M = 6372797.560856  # Redis' constant (geohash_helper.c)

_UNITS = {"m": 1.0, "km": 1000.0, "mi": 1609.34, "ft": 0.3048}

_BASE32 = "0123456789bcdefghjkmnpqrstuvwxyz"


def _haversine_m(lon1, lat1, lon2, lat2):
    """Vectorized great-circle distance in meters."""
    lon1, lat1, lon2, lat2 = (np.radians(np.asarray(x, np.float64)) for x in (lon1, lat1, lon2, lat2))
    u = np.sin((lat2 - lat1) / 2)
    v = np.sin((lon2 - lon1) / 2)
    return 2 * EARTH_RADIUS_M * np.arcsin(np.sqrt(u * u + np.cos(lat1) * np.cos(lat2) * v * v))


def geohash(lon: float, lat: float, precision: int = 11) -> str:
    """Standard geohash (GEOHASH reply format)."""
    lat_r, lon_r = [-90.0, 90.0], [-180.0, 180.0]
    bits, out, ch, even = 0, [], 0, True
    while len(out) < precision:
        if even:
            mid = (lon_r[0] + lon_r[1]) / 2
            if lon >= mid:
                ch = ch * 2 + 1
                lon_r[0] = mid
            else:
                ch *= 2
                lon_r[1] = mid
        else:
            mid = (lat_r[0] + lat_r[1]) / 2
            if lat >= mid:
                ch = ch * 2 + 1
                lat_r[0] = mid
            else:
                ch *= 2
                lat_r[1] = mid
        even = not even
        bits += 1
        if bits == 5:
            out.append(_BASE32[ch])
            bits, ch = 0, 0
    return "".join(out)


class GeoSearchArgs:
    """Builder mirroring ``api/geo/GeoSearchArgs`` (the reference's modern
    search surface): origin = point or member; shape = radius or box; plus
    count/order.  Construct via ``from_coords``/``from_member`` and chain."""

    def __init__(self):
        self._point: Optional[Tuple[float, float]] = None
        self._member = None
        self._radius: Optional[Tuple[float, str]] = None
        self._box: Optional[Tuple[float, float, str]] = None
        self.count: Optional[int] = None
        self.order: Optional[str] = None

    @classmethod
    def from_coords(cls, lon: float, lat: float) -> "GeoSearchArgs":
        a = cls()
        a._point = (float(lon), float(lat))
        return a

    @classmethod
    def from_member(cls, member) -> "GeoSearchArgs":
        a = cls()
        a._member = member
        return a

    def radius(self, r: float, unit: str = "m") -> "GeoSearchArgs":
        self._radius = (float(r), unit)
        return self

    def box(self, width: float, height: float, unit: str = "m") -> "GeoSearchArgs":
        self._box = (float(width), float(height), unit)
        return self

    def with_count(self, n: int) -> "GeoSearchArgs":
        self.count = int(n)
        return self

    def with_order(self, order: str) -> "GeoSearchArgs":
        order = order.upper()
        if order not in ("ASC", "DESC"):
            raise ValueError("order must be ASC or DESC")
        self.order = order
        return self


class Geo(RExpirable):
    _kind = "geo"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={})
        )

    def _e(self, v) -> bytes:
        return self._codec.encode(v)

    def _d(self, raw):
        return self._codec.decode(raw)

    def add(self, lon: float, lat: float, member) -> int:
        """GEOADD one member; returns 1 if new."""
        if not (-180 <= lon <= 180 and -85.05112878 <= lat <= 85.05112878):
            raise ValueError(f"invalid longitude/latitude ({lon}, {lat})")
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            e = self._e(member)
            fresh = e not in rec.host
            rec.host[e] = (float(lon), float(lat))
            self._touch_version(rec)
            return int(fresh)

    def add_all(self, entries: Dict[Any, Tuple[float, float]]) -> int:
        return sum(self.add(lon, lat, m) for m, (lon, lat) in entries.items())

    def add_if_exists(self, lon: float, lat: float, member) -> bool:
        """GEOADD XX (RGeo.addIfExists): update an existing member's
        position only; returns True when the position CHANGED."""
        if not (-180 <= lon <= 180 and -85.05112878 <= lat <= 85.05112878):
            raise ValueError(f"invalid longitude/latitude ({lon}, {lat})")
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            e = self._e(member)
            old = rec.host.get(e)
            if old is None:
                return False
            new = (float(lon), float(lat))
            if old == new:
                return False
            rec.host[e] = new
            self._touch_version(rec)
            return True

    def try_add(self, lon: float, lat: float, member) -> bool:
        """GEOADD NX (RGeo.tryAdd): add only when ABSENT."""
        if not (-180 <= lon <= 180 and -85.05112878 <= lat <= 85.05112878):
            raise ValueError(f"invalid longitude/latitude ({lon}, {lat})")
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            e = self._e(member)
            if e in rec.host:
                return False
            rec.host[e] = (float(lon), float(lat))
            self._touch_version(rec)
            return True

    def search_with_position(
        self, *a, **kw
    ) -> Dict[Any, Tuple[float, float]]:
        """GEOSEARCH ... WITHCOORD (RGeo.searchWithPosition): member ->
        (lon, lat), nearest-first.  Accepts a GeoSearchArgs (the modern
        surface) or legacy (lon, lat, radius[, unit, count, order])."""
        if len(a) == 1 and isinstance(a[0], GeoSearchArgs):
            return self.search_with_position_args(a[0])
        if len(a) >= 3:
            lon, lat, radius = a[:3]
        else:
            # pre-r5 named-parameter signature: lon/lat/radius may arrive as
            # keywords — fall back to kw when the positionals run short
            # instead of raising an opaque unpack ValueError
            try:
                lon = a[0] if len(a) > 0 else kw["lon"]
                lat = a[1] if len(a) > 1 else kw["lat"]
                radius = kw["radius"]
            except KeyError as e:
                raise TypeError(
                    f"search_with_position() missing required argument: {e.args[0]!r}"
                ) from None
        unit = a[3] if len(a) > 3 else kw.get("unit", "m")
        count = a[4] if len(a) > 4 else kw.get("count")
        order = a[5] if len(a) > 5 else kw.get("order", "ASC")
        members = self.search_radius(lon, lat, radius, unit=unit, count=count, order=order)
        positions = self.pos(*members)
        return {m: positions[m] for m in members if positions.get(m) is not None}

    def remove(self, member) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.host.pop(self._e(member), None) is None:
                return False
            self._touch_version(rec)
            return True

    def pos(self, *members) -> Dict[Any, Tuple[float, float]]:
        """GEOPOS."""
        rec = self._engine.store.get(self._name)
        out = {}
        if rec is None:
            return out
        for m in members:
            p = rec.host.get(self._e(m))
            if p is not None:
                out[m] = p
        return out

    def dist(self, member1, member2, unit: str = "m") -> Optional[float]:
        """GEODIST."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return None
        p1 = rec.host.get(self._e(member1))
        p2 = rec.host.get(self._e(member2))
        if p1 is None or p2 is None:
            return None
        d = float(_haversine_m(p1[0], p1[1], p2[0], p2[1]))
        return d / _UNITS[unit]

    def hash(self, *members) -> Dict[Any, str]:
        """GEOHASH."""
        out = {}
        for m, (lon, lat) in self.pos(*members).items():
            out[m] = geohash(lon, lat)
        return out

    def _search_point(
        self, lon: float, lat: float, radius_m: float, count: Optional[int], order: Optional[str]
    ) -> List[Tuple[Any, float]]:
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return []
        members = list(rec.host.keys())
        pts = np.asarray([rec.host[m] for m in members], np.float64)
        d = _haversine_m(lon, lat, pts[:, 0], pts[:, 1])
        sel = np.nonzero(d <= radius_m)[0]
        pairs = [(members[i], float(d[i])) for i in sel]
        if order == "DESC":
            pairs.sort(key=lambda p: -p[1])
        else:
            pairs.sort(key=lambda p: p[1])
        if count is not None:
            pairs = pairs[:count]
        return pairs

    def search_radius(
        self,
        lon: float,
        lat: float,
        radius: float,
        unit: str = "m",
        count: Optional[int] = None,
        order: Optional[str] = "ASC",
    ) -> List:
        """GEOSEARCH FROMLONLAT BYRADIUS."""
        pairs = self._search_point(lon, lat, radius * _UNITS[unit], count, order)
        return [self._d(m) for m, _ in pairs]

    def search_radius_with_distance(
        self, lon, lat, radius, unit: str = "m", count=None, order="ASC"
    ) -> Dict[Any, float]:
        pairs = self._search_point(lon, lat, radius * _UNITS[unit], count, order)
        u = _UNITS[unit]
        return {self._d(m): d / u for m, d in pairs}

    def search_member_radius(self, member, radius: float, unit: str = "m", count=None, order="ASC") -> List:
        """GEOSEARCH FROMMEMBER."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        p = rec.host.get(self._e(member))
        if p is None:
            raise KeyError(f"could not decode requested zset member {member!r}")
        return self.search_radius(p[0], p[1], radius, unit, count, order)

    def search_box(self, lon: float, lat: float, width: float, height: float, unit: str = "m") -> List:
        """GEOSEARCH BYBOX (width/height centered on the point)."""
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host:
            return []
        w_m, h_m = width * _UNITS[unit] / 2, height * _UNITS[unit] / 2
        members = list(rec.host.keys())
        pts = np.asarray([rec.host[m] for m in members], np.float64)
        dx = _haversine_m(lon, pts[:, 1], pts[:, 0], pts[:, 1])  # along-lat distance
        dy = _haversine_m(lon, lat, lon, pts[:, 1])
        sel = np.nonzero((dx <= w_m) & (dy <= h_m))[0]
        return [self._d(members[i]) for i in sel]

    def store_search_radius_to(self, dest_name: str, lon, lat, radius, unit: str = "m") -> int:
        """GEOSEARCHSTORE: store hits (as a geo set) into dest."""
        return self.store_search_to(
            dest_name, GeoSearchArgs.from_coords(lon, lat).radius(radius, unit)
        )

    # -- GeoSearchArgs surface (api/geo/GeoSearchArgs parity) ----------------

    def _eval_args(self, args: GeoSearchArgs) -> List[Tuple[bytes, float]]:
        """(encoded member, distance_m) pairs for any origin/shape combo,
        ordered per args (nearest-first by default)."""
        rec = self._engine.store.get(self._name)
        if args._member is not None:
            # a missing FROMMEMBER origin errors even on an empty key
            # (Redis: "could not decode requested zset member")
            p = rec.host.get(self._e(args._member)) if rec is not None else None
            if p is None:
                raise KeyError(
                    f"could not decode requested zset member {args._member!r}"
                )
            lon, lat = p
        else:
            lon, lat = args._point
        if rec is None or not rec.host:
            return []
        members = list(rec.host.keys())
        pts = np.asarray([rec.host[m] for m in members], np.float64)
        d = _haversine_m(lon, lat, pts[:, 0], pts[:, 1])
        if args._radius is not None:
            r, unit = args._radius
            sel = np.nonzero(d <= r * _UNITS[unit])[0]
        elif args._box is not None:
            w, h, unit = args._box
            w_m, h_m = w * _UNITS[unit] / 2, h * _UNITS[unit] / 2
            dx = _haversine_m(lon, pts[:, 1], pts[:, 0], pts[:, 1])
            dy = _haversine_m(lon, lat, lon, pts[:, 1])
            sel = np.nonzero((dx <= w_m) & (dy <= h_m))[0]
        else:
            raise ValueError("GeoSearchArgs needs .radius() or .box()")
        pairs = [(members[i], float(d[i])) for i in sel]
        pairs.sort(key=lambda p: -p[1] if args.order == "DESC" else p[1])
        if args.count is not None:
            pairs = pairs[: args.count]
        return pairs

    def _result_unit(self, args: GeoSearchArgs) -> float:
        shape = args._radius or args._box
        return _UNITS[shape[-1] if shape else "m"]

    def search(self, args: GeoSearchArgs) -> List:
        """RGeo.search(GeoSearchArgs) (RedissonGeo.java search surface)."""
        return [self._d(m) for m, _ in self._eval_args(args)]

    def search_with_distance(self, args: GeoSearchArgs) -> Dict[Any, float]:
        u = self._result_unit(args)
        return {self._d(m): d / u for m, d in self._eval_args(args)}

    def search_with_position_args(self, args: GeoSearchArgs) -> Dict[Any, Tuple[float, float]]:
        members = self.search(args)
        positions = self.pos(*members)
        return {m: positions[m] for m in members if positions.get(m) is not None}

    def store_search_to(self, dest_name: str, args: GeoSearchArgs) -> int:
        """GEOSEARCHSTORE (RGeo.storeSearchTo): hits land in dest, replacing
        it — Redis GEOSEARCHSTORE overwrites the destination key."""
        pairs = self._eval_args(args)
        dest = Geo(self._engine, dest_name, self._codec)  # maps dest_name
        with self._engine.locked_many((self._name, dest._name)):
            # re-fetch the source UNDER the lock: members matched by the
            # pre-lock evaluation may have been concurrently removed — skip
            # them instead of raising KeyError after dest was already cleared
            rec = self._engine.store.get(self._name)
            src = rec.host if rec is not None else {}
            drec = dest._rec_or_create()
            drec.host.clear()
            stored = 0
            for m, _ in pairs:
                p = src.get(m)
                if p is None:
                    continue  # vanished between evaluation and the lock
                drec.host[m] = p
                stored += 1
            self._touch_version(drec)
        return stored

    def store_sorted_search_to(self, dest_name: str, args: GeoSearchArgs) -> int:
        """GEOSEARCHSTORE STOREDIST analog: dest iterates nearest-first
        (RGeo.storeSortedSearchTo; read_all order is insertion order here,
        which _eval_args makes distance-ascending unless args order says
        otherwise)."""
        return self.store_search_to(dest_name, args)

    def read_all(self) -> List:
        """Every member, in stored (insertion / store-order) sequence."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(m) for m in rec.host.keys()]

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)
