"""Sharded sketch objects: single logical objects spread across the mesh.

The capability jump over the reference (SURVEY.md §5.7): Redis pins any one
key's value to ONE shard (``cluster/ClusterConnectionManager.java`` slot
model); here a single BloomFilterArray's bit plane is column-sharded across
every chip on the mesh's `shard` axis and probed with one psum over ICI, and
a ShardedHllArray's tenant axis is range-sharded (the expert-parallel
analog).  These are real object handles on the engine path — same record
store, same locks, same checkpoint/replication surface as every other object
(VERDICT round-1 next-step #1), not kernel demos.

Geometry notes:
  * bloom: m is rounded up so it divides evenly by the shard-axis size
    (each shard owns a contiguous column range of every tenant's plane);
  * hll: tenants are rounded up to a shard-axis multiple (each shard owns a
    tenant range; adds route with zero collectives, estimates gather).
"""
from __future__ import annotations

from typing import Optional

import numpy as np

import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.client.objects.bloom import (
    optimal_num_of_bits,
    optimal_num_of_hash_functions,
)
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import hll as hll_ops
from redisson_tpu.parallel.manager import MeshManager
from redisson_tpu.parallel.mesh import SHARD_AXIS
from redisson_tpu.utils import hashing as H

BLOOM_SPEC = P(None, SHARD_AXIS)   # (T, m): columns sharded
HLL_SPEC = P(SHARD_AXIS, None)     # (T, regs): tenants sharded


class _ShardedBase(RExpirable):
    @property
    def _mgr(self) -> MeshManager:
        return MeshManager.of(self._engine)

    def _bloom_width(self, m: int, geom) -> int:
        """Stored plane width for the dispatch geometry: the hash domain m
        padded to a lane-aligned shard multiple (pad columns are never
        probed, so a reshard to a non-dividing shard count just re-pads —
        live resharding, SURVEY §7.3-4)."""
        return self._mgr.round_up(m, 128 * geom.n_shard)

    def _hll_rows(self, tenants: int, geom) -> int:
        """Stored row count for the dispatch geometry (logical tenants
        padded to a shard multiple; pad rows are never addressed)."""
        return self._mgr.round_up(tenants, geom.n_shard)

    def _rec(self) -> StateRecord:
        rec = self._engine.store.get(self._name)
        if rec is None:
            raise RuntimeError(f"{type(self).__name__} '{self._name}' is not initialized")
        return rec

    def _pack(self, tenant_ids, keys, geom):
        t = np.ascontiguousarray(tenant_ids, np.int32)
        if not self._engine.is_int_batch(keys):
            raise TypeError(
                f"{type(self).__name__} is the vectorized fast path: keys must "
                "be an integer numpy array"
            )
        arr = np.ascontiguousarray(keys, np.int64)
        if t.shape != arr.shape:
            raise ValueError("tenant_ids and keys must be aligned 1-D arrays")
        lo, hi = H.int_keys_to_u32_pair(arr)
        return self._mgr.pad_batch(t, lo, hi, geom=geom)


class ShardedBloomFilterArray(_ShardedBase):
    """Multi-tenant bloom bank whose bit plane is sharded across the mesh —
    capacity scales with chips, probes cost one psum over ICI."""

    _kind = "sharded_bloom_array"

    def try_init(
        self,
        tenants: int,
        expected_insertions: int,
        false_probability: float,
        m: Optional[int] = None,
    ) -> bool:
        if tenants <= 0:
            raise ValueError("tenants must be positive")
        mgr = self._mgr
        if m is None:
            m = optimal_num_of_bits(expected_insertions, false_probability)
        # columns must split evenly over the shard axis; keep shard-local
        # widths lane-aligned (128) so the per-shard gather tiles cleanly
        m = mgr.round_up(m, 128 * mgr.n_shard)
        k = optimal_num_of_hash_functions(expected_insertions, m)
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            bits = jnp.zeros((tenants, m), jnp.uint8)
            rec = StateRecord(
                kind=self._kind,
                meta={
                    "tenants": tenants,
                    "n": expected_insertions,
                    "p": false_probability,
                    "m": m,
                    "k": k,
                    "hash": H.HASH_NAME,
                    "sharded": True,
                },
                arrays={"bits": bits},
            )
            mgr.ensure_state(rec, "bits", BLOOM_SPEC)
            self._engine.store.put(self._name, rec)
            return True

    def tenants(self) -> int:
        return self._rec().meta["tenants"]

    def get_size(self) -> int:
        return self._rec().meta["m"]

    def get_hash_iterations(self) -> int:
        return self._rec().meta["k"]

    def shards(self) -> int:
        return self._mgr.n_shard

    def add_each(self, tenant_ids, keys) -> np.ndarray:
        """Batch add across tenants; bool array: element was (probably) new."""
        geom = self._mgr.geometry()
        tenant, lo, hi, n = self._pack(tenant_ids, keys, geom)
        if n == 0:
            return np.zeros((0,), bool)
        with self._engine.locked(self._name):
            rec = self._rec()
            meta = rec.meta
            w = self._bloom_width(meta["m"], geom)
            add, _ = self._mgr.bloom_kernels(
                meta["k"], meta["m"], meta["tenants"], width=w, geom=geom
            )
            bits = self._mgr.adapt_plane(
                rec, "bits", BLOOM_SPEC, axis=1, length=w, geom=geom
            )
            bits, newly = add(bits, tenant, lo, hi, n)
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return np.asarray(newly)[:n]

    def add(self, tenant_ids, keys) -> int:
        return int(np.sum(self.add_each(tenant_ids, keys)))

    def contains_each(self, tenant_ids, keys) -> np.ndarray:
        """Vectorized membership across tenants: bool array aligned to keys."""
        found, n = self.contains_async(tenant_ids, keys)
        return np.asarray(found)[:n]

    def contains_async(self, tenant_ids, keys):
        """Pipelined probe: (device bool array, n_valid) without forcing the
        device->host sync — callers keep flushes in flight and force later."""
        geom = self._mgr.geometry()
        tenant, lo, hi, n = self._pack(tenant_ids, keys, geom)
        if n == 0:
            return np.zeros((0,), bool), 0
        with self._engine.locked(self._name):
            rec = self._rec()
            meta = rec.meta
            w = self._bloom_width(meta["m"], geom)
            _, contains = self._mgr.bloom_kernels(
                meta["k"], meta["m"], meta["tenants"], width=w, geom=geom
            )
            bits = self._mgr.adapt_plane(
                rec, "bits", BLOOM_SPEC, axis=1, length=w, geom=geom
            )
            found = contains(bits, tenant, lo, hi, n)
        return found, n

    def clear_tenant(self, tenant_id: int) -> None:
        with self._engine.locked(self._name):
            rec = self._rec()
            if not 0 <= tenant_id < rec.meta["tenants"]:
                # .at[].set would silently CLAMP an out-of-range row and wipe
                # the last tenant's bits — fail loudly instead
                raise IndexError(
                    f"tenant {tenant_id} out of range [0, {rec.meta['tenants']})"
                )
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            bits = self._mgr.adapt_plane(
                rec, "bits", BLOOM_SPEC, axis=1, length=w, geom=geom
            )
            rec.arrays["bits"] = bits.at[tenant_id].set(jnp.uint8(0))
            self._touch_version(rec)

    def tenant_bit_counts(self) -> np.ndarray:
        """Per-tenant set-bit counts (the fill monitor); computed shard-local
        then summed by XLA across the column shards."""
        with self._engine.locked(self._name):
            rec = self._rec()
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            bits = self._mgr.adapt_plane(
                rec, "bits", BLOOM_SPEC, axis=1, length=w, geom=geom
            )
            return np.asarray(jnp.sum(bits.astype(jnp.int32), axis=1))


class ShardedHllArray(_ShardedBase):
    """Multi-tenant HLL bank with the tenant axis sharded across the mesh:
    adds are shard-local (zero collectives), estimates gather once."""

    _kind = "sharded_hll_array"

    def try_init(self, tenants: int, p: int = hll_ops.DEFAULT_P) -> bool:
        if tenants <= 0:
            raise ValueError("tenants must be positive")
        mgr = self._mgr
        geom = mgr.geometry()
        padded_tenants = self._hll_rows(tenants, geom)
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            regs = jnp.zeros((padded_tenants, hll_ops.m_of(p)), jnp.uint8)
            rec = StateRecord(
                kind=self._kind,
                meta={
                    "tenants": tenants,
                    "p": p,
                    "hash": H.HASH_NAME,
                    "sharded": True,
                },
                arrays={"regs": regs},
            )
            mgr.ensure_state(rec, "regs", HLL_SPEC)
            self._engine.store.put(self._name, rec)
            return True

    def tenants(self) -> int:
        return self._rec().meta["tenants"]

    def shards(self) -> int:
        return self._mgr.n_shard

    def add_each(self, tenant_ids, keys) -> None:
        geom = self._mgr.geometry()
        tenant, lo, hi, n = self._pack(tenant_ids, keys, geom)
        if n == 0:
            return
        with self._engine.locked(self._name):
            rec = self._rec()
            meta = rec.meta
            rows = self._hll_rows(meta["tenants"], geom)
            add, _ = self._mgr.hll_kernels(meta["p"], rows, geom=geom)
            regs = self._mgr.adapt_plane(
                rec, "regs", HLL_SPEC, axis=0, length=rows, geom=geom
            )
            rec.arrays["regs"] = add(regs, tenant, lo, hi, n)
            self._touch_version(rec)

    def estimate_all(self) -> np.ndarray:
        """Per-tenant cardinality estimates (gathered once over ICI)."""
        with self._engine.locked(self._name):
            rec = self._rec()
            meta = rec.meta
            geom = self._mgr.geometry()
            rows = self._hll_rows(meta["tenants"], geom)
            _, estimate = self._mgr.hll_kernels(meta["p"], rows, geom=geom)
            regs = self._mgr.adapt_plane(
                rec, "regs", HLL_SPEC, axis=0, length=rows, geom=geom
            )
            ests = estimate(regs)
        return np.asarray(ests)[: meta["tenants"]]

    def estimate(self, tenant_id: int) -> int:
        return int(round(float(self.estimate_all()[tenant_id])))

    def clear_tenant(self, tenant_id: int) -> None:
        with self._engine.locked(self._name):
            rec = self._rec()
            if not 0 <= tenant_id < rec.meta["tenants"]:
                raise IndexError(
                    f"tenant {tenant_id} out of range [0, {rec.meta['tenants']})"
                )
            geom = self._mgr.geometry()
            rows = self._hll_rows(rec.meta["tenants"], geom)
            regs = self._mgr.adapt_plane(
                rec, "regs", HLL_SPEC, axis=0, length=rows, geom=geom
            )
            rec.arrays["regs"] = regs.at[tenant_id].set(jnp.uint8(0))
            self._touch_version(rec)


BITSET_SPEC = P(SHARD_AXIS)        # (m,): columns sharded


class ShardedBitSet(_ShardedBase):
    """ONE logical RBitSet column-sharded across the mesh — wider than any
    single chip's HBM, probed/updated with one psum over ICI (SURVEY.md
    §5.7: the reference's one-key-one-shard ceiling removed for bulk bits).

    The LOGICAL size is fixed at try_init; the STORED width is mesh-
    dependent (padded to a lane- and shard-aligned multiple for the current
    geometry and re-padded on reshard by adapt_plane).  Indexes are
    validated against the logical size, so padding never leaks into
    results — never compare raw plane shapes across records."""

    _kind = "sharded_bitset"

    def try_init(self, size: int) -> bool:
        if size <= 0:
            raise ValueError("size must be positive")
        if size > (1 << 31):
            # indexes travel as int32 through the kernels; a larger plane
            # would silently WRAP high indexes onto low bits
            raise ValueError("sharded bitset size is capped at 2^31 bits")
        mgr = self._mgr
        m = mgr.round_up(size, 128 * mgr.n_shard)
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            rec = StateRecord(
                kind=self._kind,
                meta={"size": size, "m": m, "sharded": True},
                arrays={"bits": jnp.zeros((m,), jnp.uint8)},
            )
            mgr.ensure_state(rec, "bits", BITSET_SPEC)
            self._engine.store.put(self._name, rec)
            return True

    def size(self) -> int:
        return self._rec().meta["size"]

    def plane_width(self) -> int:
        return self._rec().meta["m"]

    def shards(self) -> int:
        return self._mgr.n_shard

    def _pack_indexes(self, indexes, size: int):
        import jax

        from redisson_tpu.core import kernels as K
        from redisson_tpu.parallel import mesh as M

        idx = np.ascontiguousarray(indexes, np.int64)
        if idx.ndim != 1:
            raise ValueError("indexes must be a 1-D integer array")
        if idx.size and ((idx < 0) | (idx >= size)).any():
            raise IndexError(f"bit index out of range [0, {size})")
        mgr = self._mgr
        n = idx.shape[0]
        # 1/8-octave buckets like pad_batch: pow2 would waste up to 2x of
        # host->device bandwidth on padding (the dominant flush cost)
        b = mgr.round_up(K.bucket_size(max(1, n)), mgr.dp)
        idx32 = np.pad(idx.astype(np.int32), (0, b - n)) if b > n else idx.astype(np.int32)
        return jax.device_put(idx32, M.batch_sharding(mgr.mesh)), n

    def set_each(self, indexes, value: bool = True) -> np.ndarray:
        """Batch SETBIT; returns each bit's PREVIOUS value."""
        with self._engine.locked(self._name):
            rec = self._rec()
            idx, n = self._pack_indexes(indexes, rec.meta["size"])
            if n == 0:
                return np.zeros((0,), bool)
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            (set_t, set_f), _, _ = self._mgr.bitset_kernels(
                rec.meta["m"], width=w, geom=geom
            )
            bits = self._mgr.adapt_plane(
                rec, "bits", BITSET_SPEC, axis=0, length=w, geom=geom
            )
            bits, old = (set_t if value else set_f)(bits, idx, n)
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return np.asarray(old)[:n]

    def get_each(self, indexes) -> np.ndarray:
        with self._engine.locked(self._name):
            rec = self._rec()
            idx, n = self._pack_indexes(indexes, rec.meta["size"])
            if n == 0:
                return np.zeros((0,), bool)
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            _, get, _ = self._mgr.bitset_kernels(rec.meta["m"], width=w, geom=geom)
            bits = self._mgr.adapt_plane(
                rec, "bits", BITSET_SPEC, axis=0, length=w, geom=geom
            )
            got = get(bits, idx, n)
        return np.asarray(got)[:n]

    def set(self, index: int, value: bool = True) -> bool:
        return bool(self.set_each(np.asarray([index]), value)[0])

    def get(self, index: int) -> bool:
        return bool(self.get_each(np.asarray([index]))[0])

    def cardinality(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec()
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            _, _, card = self._mgr.bitset_kernels(rec.meta["m"], width=w, geom=geom)
            bits = self._mgr.adapt_plane(
                rec, "bits", BITSET_SPEC, axis=0, length=w, geom=geom
            )
            return int(card(bits))

    def clear(self) -> None:
        with self._engine.locked(self._name):
            rec = self._rec()
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            rec.arrays["bits"] = jnp.zeros((w,), jnp.uint8)
            self._mgr.ensure_state(rec, "bits", BITSET_SPEC, geom=geom)
            self._touch_version(rec)

    def _binary_op(self, op, other_names):
        """BITOP against other sharded bitsets: identically-sharded planes,
        elementwise combine — XLA emits zero collectives."""
        other_names = [self._map_name(n) for n in other_names]
        names = [self._name, *other_names]
        with self._engine.locked_many(names):
            rec = self._rec()
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            bits = self._mgr.adapt_plane(
                rec, "bits", BITSET_SPEC, axis=0, length=w, geom=geom
            )
            for other in other_names:
                orec = self._engine.store.get(other)
                if orec is None or orec.kind != self._kind:
                    raise ValueError(f"'{other}' is not an initialized {type(self).__name__}")
                if orec.meta["m"] != rec.meta["m"] or orec.meta["size"] != rec.meta["size"]:
                    # logical size matters too: a wider-size operand would
                    # plant ghost bits past this plane's size, corrupting
                    # cardinality() and not_()'s padding invariant
                    raise ValueError("sharded BITOP operands must share geometry (size and plane width)")
                obits = self._mgr.adapt_plane(
                    orec, "bits", BITSET_SPEC, axis=0, length=w, geom=geom
                )
                bits = op(bits, obits)
            rec.arrays["bits"] = bits
            self._touch_version(rec)

    def or_(self, *other_names: str) -> None:
        self._binary_op(jnp.bitwise_or, other_names)

    def and_(self, *other_names: str) -> None:
        self._binary_op(jnp.bitwise_and, other_names)

    def xor(self, *other_names: str) -> None:
        self._binary_op(jnp.bitwise_xor, other_names)

    def not_(self) -> None:
        """Flip every LOGICAL bit (padding stays zero so cardinality and
        cross-plane ops never see ghost bits)."""
        with self._engine.locked(self._name):
            rec = self._rec()
            geom = self._mgr.geometry()
            w = self._bloom_width(rec.meta["m"], geom)
            bits = self._mgr.adapt_plane(
                rec, "bits", BITSET_SPEC, axis=0, length=w, geom=geom
            )
            mask = (jnp.arange(w, dtype=jnp.int32) < rec.meta["size"])
            rec.arrays["bits"] = jnp.where(mask, 1 - bits, bits).astype(jnp.uint8)
            self._touch_version(rec)
