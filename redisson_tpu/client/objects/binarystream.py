"""BinaryStream + JsonBucket.

Parity targets:
  * RBinaryStream — ``org/redisson/RedissonBinaryStream.java``: stream-style
    read/write over a byte value (GETRANGE/SETRANGE), channel positions.
  * RJsonBucket — ``org/redisson/RedissonJsonBucket.java`` (932 LoC): JSON
    document with path get/set (JSON.GET/JSON.SET of RedisJSON), array ops,
    numeric increment.  Paths use a dotted subset ("a.b[0].c", "$" = root).
"""
from __future__ import annotations

import json
import re
from typing import Any, List, Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class BinaryStream(RExpirable):
    _kind = "binary_stream"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=bytearray())
        )

    def get(self) -> bytes:
        rec = self._engine.store.get(self._name)
        return b"" if rec is None else bytes(rec.host)

    def set(self, data: bytes) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host[:] = data
            self._touch_version(rec)

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)

    def read(self, position: int, length: int) -> bytes:
        """GETRANGE-style read."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return b""
        return bytes(rec.host[position : position + length])

    def write(self, position: int, data: bytes) -> int:
        """SETRANGE-style write (zero-fills a gap); returns new size."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if position > len(rec.host):
                rec.host.extend(b"\x00" * (position - len(rec.host)))
            end = position + len(data)
            if end > len(rec.host):
                rec.host.extend(b"\x00" * (end - len(rec.host)))
            rec.host[position:end] = data
            self._touch_version(rec)
            return len(rec.host)

    def append(self, data: bytes) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.extend(data)
            self._touch_version(rec)
            return len(rec.host)


_PATH_TOKEN = re.compile(r"([^.\[\]]+)|\[(\d+)\]")


def _parse_path(path: str) -> List:
    if path in ("$", "", "."):
        return []
    out: List = []
    for name, idx in _PATH_TOKEN.findall(path.lstrip("$.")):
        out.append(int(idx) if idx else name)
    return out


class JsonBucket(RExpirable):
    """RJsonBucket: JSON document store with path operations."""

    _kind = "json_bucket"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={"doc": None})
        )

    @staticmethod
    def _walk(doc, tokens, create=False):
        """Returns (parent_container, final_token) for a path."""
        cur = doc
        for i, t in enumerate(tokens[:-1]):
            nxt = None
            if isinstance(cur, dict):
                nxt = cur.get(t)
                if nxt is None and create:
                    nxt = cur[t] = {}
            elif isinstance(cur, list) and isinstance(t, int) and t < len(cur):
                nxt = cur[t]
            if nxt is None:
                raise KeyError(".".join(map(str, tokens[: i + 1])))
            cur = nxt
        return cur, tokens[-1] if tokens else None

    def set(self, path: str, value: Any) -> None:
        """JSON.SET."""
        value = json.loads(json.dumps(value))  # enforce JSON-able
        tokens = _parse_path(path)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if not tokens:
                rec.host["doc"] = value
            else:
                if rec.host["doc"] is None:
                    rec.host["doc"] = {}
                parent, last = self._walk(rec.host["doc"], tokens, create=True)
                if isinstance(parent, list):
                    parent[last] = value
                else:
                    parent[last] = value
            self._touch_version(rec)

    def get(self, path: str = "$") -> Any:
        """JSON.GET."""
        rec = self._engine.store.get(self._name)
        if rec is None or rec.host["doc"] is None:
            return None
        tokens = _parse_path(path)
        if not tokens:
            return rec.host["doc"]
        try:
            parent, last = self._walk(rec.host["doc"], tokens)
            return parent[last] if last is not None else parent
        except (KeyError, IndexError, TypeError):
            return None

    def delete(self, path: str = "$") -> bool:
        """JSON.DEL; root delete removes the object."""
        tokens = _parse_path(path)
        with self._engine.locked(self._name):
            if not tokens:
                return self._engine.store.delete(self._name)
            rec = self._rec_or_create()
            if rec.host["doc"] is None:
                return False
            try:
                parent, last = self._walk(rec.host["doc"], tokens)
                if isinstance(parent, dict):
                    del parent[last]
                else:
                    parent.pop(last)
                self._touch_version(rec)
                return True
            except (KeyError, IndexError, TypeError):
                return False

    def increment_and_get(self, path: str, delta) -> Any:
        """JSON.NUMINCRBY."""
        with self._engine.locked(self._name):
            cur = self.get(path)
            if not isinstance(cur, (int, float)):
                raise TypeError(f"value at {path!r} is not a number")
            new = cur + delta
            self.set(path, new)
            return new

    def array_append(self, path: str, *values) -> int:
        """JSON.ARRAPPEND; returns new array length."""
        with self._engine.locked(self._name):
            arr = self.get(path)
            if not isinstance(arr, list):
                raise TypeError(f"value at {path!r} is not an array")
            arr.extend(json.loads(json.dumps(v)) for v in values)
            rec = self._rec_or_create()
            self._touch_version(rec)
            return len(arr)

    def array_size(self, path: str) -> Optional[int]:
        arr = self.get(path)
        return len(arr) if isinstance(arr, list) else None

    def string_size(self, path: str) -> Optional[int]:
        s = self.get(path)
        return len(s) if isinstance(s, str) else None

    def type(self, path: str = "$") -> Optional[str]:
        v = self.get(path)
        if v is None:
            return None
        return {dict: "object", list: "array", str: "string", bool: "boolean", int: "integer", float: "number"}[type(v)]

    def clear(self, path: str = "$") -> int:
        """JSON.CLEAR: empty containers, zero numbers; returns #cleared."""
        with self._engine.locked(self._name):
            v = self.get(path)
            if isinstance(v, dict) or isinstance(v, list):
                self.set(path, {} if isinstance(v, dict) else [])
                return 1
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                self.set(path, 0)
                return 1
            return 0

    def toggle(self, path: str) -> Optional[bool]:
        """JSON.TOGGLE a boolean; returns the new value."""
        with self._engine.locked(self._name):
            v = self.get(path)
            if not isinstance(v, bool):
                return None
            self.set(path, not v)
            return not v

    def string_append(self, path: str, s: str) -> int:
        """JSON.STRAPPEND; returns the new string length."""
        with self._engine.locked(self._name):
            cur = self.get(path)
            if not isinstance(cur, str):
                raise TypeError(f"value at {path!r} is not a string")
            new = cur + s
            self.set(path, new)
            return len(new)

    def array_insert(self, path: str, index: int, *values) -> int:
        """JSON.ARRINSERT; negative index counts from the end; returns the
        new array length.  All values insert CONTIGUOUSLY at the normalized
        position (inserting relative to the growing list would scatter
        them)."""
        with self._engine.locked(self._name):
            arr = self.get(path)
            if not isinstance(arr, list):
                raise TypeError(f"value at {path!r} is not an array")
            idx = index + len(arr) if index < 0 else index
            idx = max(0, min(idx, len(arr)))
            arr[idx:idx] = [json.loads(json.dumps(v)) for v in values]
            self._touch_version(self._rec_or_create())
            return len(arr)

    def array_pop(self, path: str, index: int = -1) -> Any:
        """JSON.ARRPOP; returns the popped element (None on empty/missing).
        Out-of-range indexes clamp to the nearest end (Redis semantics)."""
        with self._engine.locked(self._name):
            arr = self.get(path)
            if not isinstance(arr, list) or not arr:
                return None
            idx = index + len(arr) if index < 0 else index
            idx = max(0, min(idx, len(arr) - 1))
            v = arr.pop(idx)
            self._touch_version(self._rec_or_create())
            return v

    def array_trim(self, path: str, start: int, stop: int) -> int:
        """JSON.ARRTRIM to [start, stop] inclusive; negative indexes count
        from the end Redis-style (stop=-1 keeps through the last element);
        returns the new length."""
        with self._engine.locked(self._name):
            arr = self.get(path)
            if not isinstance(arr, list):
                raise TypeError(f"value at {path!r} is not an array")
            n = len(arr)
            lo = max(0, start + n if start < 0 else start)
            hi = stop + n if stop < 0 else stop
            arr[:] = arr[lo : hi + 1] if hi >= lo else []
            self._touch_version(self._rec_or_create())
            return len(arr)

    def array_index_of(self, path: str, value, start: int = 0, stop: int = 0) -> int:
        """JSON.ARRINDEX; -1 when absent.  stop=0 means 'to the end';
        negative indexes count from the end (Redis semantics).  The result
        is always an ABSOLUTE position."""
        arr = self.get(path)
        if not isinstance(arr, list):
            return -1
        n = len(arr)
        lo = max(0, start + n if start < 0 else start)
        hi = n if stop == 0 else (stop + n if stop < 0 else min(stop, n))
        hi = max(0, hi)  # a stop below -len must mean "empty range", not a
        # second negative re-interpretation inside list.index
        try:
            return arr.index(value, lo, hi)
        except ValueError:
            return -1

    def object_keys(self, path: str = "$") -> Optional[List[str]]:
        """JSON.OBJKEYS."""
        v = self.get(path)
        return list(v.keys()) if isinstance(v, dict) else None

    def object_size(self, path: str = "$") -> Optional[int]:
        """JSON.OBJLEN."""
        v = self.get(path)
        return len(v) if isinstance(v, dict) else None

    def merge(self, path: str, value: Any) -> None:
        """JSON.MERGE (RFC 7386 merge-patch): dicts merge recursively,
        None values delete keys, everything else replaces."""

        def patch(target, p):
            if not isinstance(p, dict):
                return json.loads(json.dumps(p))
            if not isinstance(target, dict):
                target = {}
            for k, v in p.items():
                if v is None:
                    target.pop(k, None)
                else:
                    target[k] = patch(target.get(k), v)
            return target

        with self._engine.locked(self._name):
            cur = self.get(path)
            self.set(path, patch(cur, value))
