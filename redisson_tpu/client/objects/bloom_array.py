"""BloomFilterArray: multi-tenant bloom bank (BASELINE.md config 2 / §7.3-7).

The reference models "1000 tenant filters" as 1000 independent RBloomFilter
objects whose batched ops still execute per-key on the server.  The TPU-first
design packs all tenants of one family into a single (T, m) bit plane so a
mixed 100k-op flush spanning hundreds of tenants is STILL one kernel — the
tenant id is just another index column (SURVEY.md §7.3 item 7).

Per-tenant semantics preserved: clear_tenant drops one row, per-tenant counts
via row popcounts.  Geometry (m, k) is shared across tenants by construction
— the trade the reference cannot express.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.client.objects.bloom import optimal_num_of_bits, optimal_num_of_hash_functions
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import bittensor as bt
from redisson_tpu.utils import hashing as H

import jax.numpy as jnp


class BloomFilterArray(RExpirable):
    def try_init(self, tenants: int, expected_insertions: int, false_probability: float) -> bool:
        """Create a (tenants, m) bank; m/k sized per tenant."""
        if tenants <= 0:
            raise ValueError("tenants must be positive")
        m = optimal_num_of_bits(expected_insertions, false_probability)
        m = bt.padded_size(m)  # row-align so the 2-D plane tiles cleanly
        k = optimal_num_of_hash_functions(expected_insertions, m)
        if tenants * m > K.BANK_MAX_CELLS:
            raise ValueError(
                f"bank of {tenants} x {m} bits = {tenants * m} cells exceeds the "
                f"single-chip flat-index limit ({K.BANK_MAX_CELLS}); use fewer/"
                "smaller tenants or the sharded mesh kernels (parallel.sharded)"
            )
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            self._engine.store.put(
                self._name,
                StateRecord(
                    kind="bloom_array",
                    meta={
                        "tenants": tenants,
                        "n": expected_insertions,
                        "p": false_probability,
                        "m": m,
                        "k": k,
                        "hash": H.HASH_NAME,
                    },
                    arrays={"bits": jnp.zeros((tenants, m), jnp.uint8)},
                ),
            )
            return True

    def _rec(self) -> StateRecord:
        rec = self._engine.store.get(self._name)
        if rec is None:
            raise RuntimeError(f"BloomFilterArray '{self._name}' is not initialized")
        return rec

    def tenants(self) -> int:
        return self._rec().meta["tenants"]

    def get_size(self) -> int:
        return self._rec().meta["m"]

    def get_hash_iterations(self) -> int:
        return self._rec().meta["k"]

    def _pack(self, tenant_ids, keys):
        """One flush -> ONE contiguous (3, B) uint32 transfer buffer
        (rows: tenant, key-lo, key-hi).  The host->device copy dominates a
        flush's cost on a tunneled chip, and one large transfer runs ~3x the
        bandwidth of three small ones (core/kernels.py pack_rows note)."""
        t = np.ascontiguousarray(tenant_ids, np.int32)
        if not self._engine.is_int_batch(keys):
            raise TypeError(
                "BloomFilterArray is the vectorized fast path: keys must be an "
                "integer numpy array (use BloomFilter for codec-encoded objects)"
            )
        arr = np.ascontiguousarray(keys, np.int64)
        if t.shape != arr.shape:
            raise ValueError("tenant_ids and keys must be aligned 1-D arrays")
        n = arr.shape[0]
        b = K.bucket_size(max(1, n))
        lo, hi = H.int_keys_to_u32_pair(arr)
        return K.pack_rows(t, lo, hi, size=b), n

    def add_each(self, tenant_ids, keys) -> np.ndarray:
        """Batch add across tenants; bool array: element was (probably) new."""
        newly, n = self.add_each_async(tenant_ids, keys)
        return np.asarray(newly)[:n]

    def add_each_async(self, tenant_ids, keys):
        """Pipelined add: (device newly-added array, n_valid), no host sync —
        callers (the server's lazy-reply frames, streaming writers) force
        once per batch of flushes."""
        tlh, n = self._pack(tenant_ids, keys)
        if n == 0:
            return np.zeros((0,), bool), 0
        with self._engine.locked(self._name):
            rec = self._rec()
            bits, newly = K.bloom_bank_add_packed(
                rec.arrays["bits"], tlh, K.valid_n(n), rec.meta["k"], rec.meta["m"]
            )
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return newly, n

    def add(self, tenant_ids, keys) -> int:
        """Batch add across tenants; returns # of (probably) new elements."""
        return int(self.add_async(tenant_ids, keys))

    def add_async(self, tenant_ids, keys):
        """Pipelined add: returns the newly-added count as a DEVICE scalar
        without forcing a host sync — streaming writers dispatch flush after
        flush and only the final int() conversion waits."""
        tlh, n = self._pack(tenant_ids, keys)
        if n == 0:
            return np.int32(0)
        with self._engine.locked(self._name):
            rec = self._rec()
            bits, count = K.bloom_bank_add_packed_count(
                rec.arrays["bits"], tlh, K.valid_n(n), rec.meta["k"], rec.meta["m"]
            )
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return count

    def contains(self, tenant_ids, keys) -> np.ndarray:
        """Vectorized membership across tenants: bool array aligned with keys."""
        packed, n = self.contains_async(tenant_ids, keys)
        return K.unpack_found(np.asarray(packed), n)

    def contains_async(self, tenant_ids, keys):
        """Pipelined variant: returns (device uint32 result bitmap, n_valid)
        WITHOUT forcing the device->host transfer — callers keep several
        flushes in flight, force later (jax.device_get / np.asarray), and
        decode with kernels.unpack_found(bitmap, n).  Results travel as
        bitmaps because B bool bytes per flush dominate the d2h path (the
        executeAsync analog of RBatch; dispatches overlap so tunnel/dispatch
        latency amortizes away)."""
        tlh, n = self._pack(tenant_ids, keys)
        if n == 0:
            return np.zeros((0,), np.uint32), 0
        with self._engine.locked(self._name):
            rec = self._rec()
            found = K.bloom_bank_contains_packed_bits(
                rec.arrays["bits"], tlh, K.valid_n(n), rec.meta["k"], rec.meta["m"]
            )
        return found, n

    def clear_tenant(self, tenant_id: int) -> None:
        with self._engine.locked(self._name):
            rec = self._rec()
            rec.arrays["bits"] = rec.arrays["bits"].at[tenant_id].set(jnp.uint8(0))
            self._touch_version(rec)

    def tenant_bit_counts(self) -> np.ndarray:
        """Per-tenant set-bit counts (fill monitoring / growth policy input)."""
        with self._engine.locked(self._name):
            rec = self._rec()
            return np.asarray(jnp.sum(rec.arrays["bits"].astype(jnp.int32), axis=1))
