"""BloomFilterArray: multi-tenant bloom bank (BASELINE.md config 2 / §7.3-7).

The reference models "1000 tenant filters" as 1000 independent RBloomFilter
objects whose batched ops still execute per-key on the server.  The TPU-first
design packs all tenants of one family into a single (T, m) bit plane so a
mixed 100k-op flush spanning hundreds of tenants is STILL one kernel — the
tenant id is just another index column (SURVEY.md §7.3 item 7).

Per-tenant semantics preserved: clear_tenant drops one row, per-tenant counts
via row popcounts.  Geometry (m, k) is shared across tenants by construction
— the trade the reference cannot express.
"""
from __future__ import annotations

import math
from typing import Optional

import numpy as np

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.client.objects.bloom import optimal_num_of_bits, optimal_num_of_hash_functions
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import bittensor as bt
from redisson_tpu.utils import hashing as H

import jax.numpy as jnp


class BloomFilterArray(RExpirable):
    def try_init(self, tenants: int, expected_insertions: int, false_probability: float) -> bool:
        """Create a (tenants, m) bank; m/k sized per tenant."""
        if tenants <= 0:
            raise ValueError("tenants must be positive")
        m = optimal_num_of_bits(expected_insertions, false_probability)
        m = bt.padded_size(m)  # row-align so the 2-D plane tiles cleanly
        k = optimal_num_of_hash_functions(expected_insertions, m)
        if tenants * m > K.BANK_MAX_CELLS:
            raise ValueError(
                f"bank of {tenants} x {m} bits = {tenants * m} cells exceeds the "
                f"single-chip flat-index limit ({K.BANK_MAX_CELLS}); use fewer/"
                "smaller tenants or the sharded mesh kernels (parallel.sharded)"
            )
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            self._engine.store.put(
                self._name,
                StateRecord(
                    kind="bloom_array",
                    meta={
                        "tenants": tenants,
                        "n": expected_insertions,
                        "p": false_probability,
                        "m": m,
                        "k": k,
                        "hash": H.HASH_NAME,
                    },
                    arrays={"bits": jnp.zeros((tenants, m), jnp.uint8)},
                ),
            )
            return True

    def _rec(self) -> StateRecord:
        rec = self._engine.store.get(self._name)
        if rec is None:
            raise RuntimeError(f"BloomFilterArray '{self._name}' is not initialized")
        return rec

    def tenants(self) -> int:
        return self._rec().meta["tenants"]

    def get_size(self) -> int:
        return self._rec().meta["m"]

    def get_hash_iterations(self) -> int:
        return self._rec().meta["k"]

    def _validate_flush(self, tenant_ids, keys, allow_empty: bool = True):
        """Shared flush validation/conversion for the single-flush and
        window packers — ONE place for dtype/shape rules so the two transfer
        layouts can never drift."""
        t = np.ascontiguousarray(tenant_ids, np.int32)
        if not self._engine.is_int_batch(keys):
            raise TypeError(
                "BloomFilterArray is the vectorized fast path: keys must be an "
                "integer numpy array (use BloomFilter for codec-encoded objects)"
            )
        arr = np.ascontiguousarray(keys, np.int64)
        if t.shape != arr.shape or t.ndim != 1:
            raise ValueError("tenant_ids and keys must be aligned 1-D arrays")
        if not allow_empty and arr.shape[0] == 0:
            raise ValueError("window flushes must be non-empty")
        return t, arr

    def _pack(self, tenant_ids, keys, cache_hot: bool = False):
        """One flush -> ONE contiguous (3, B) uint32 transfer buffer
        (rows: tenant, key-lo, key-hi).  The host->device copy dominates a
        flush's cost on a tunneled chip, and one large transfer runs ~3x the
        bandwidth of three small ones (core/kernels.py pack_rows note).

        Hot-set reuse (`cache_hot`, read paths only): the staged buffer is
        content-addressed (kernels query cache), so a serving loop
        re-probing the same working set skips the pack AND the upload — a
        sync flush then costs one computed-result fetch, i.e. the transport
        floor.  Write flushes never cache: one-shot operands would evict
        the hot set for zero hits."""
        t, arr = self._validate_flush(tenant_ids, keys)
        n = arr.shape[0]
        b = K.bucket_size(max(1, n))

        def build():
            lo, hi = H.int_keys_to_u32_pair(arr)
            return K.pack_rows(t, lo, hi, size=b, pool=self._engine.staging_pool())

        if cache_hot and n >= 4096:
            return K.cached_staged(build, t, arr, extra=b"bfa%d" % b), n
        return build(), n

    def add_each(self, tenant_ids, keys) -> np.ndarray:
        """Batch add across tenants; bool array: element was (probably) new."""
        newly, n = self.add_each_async(tenant_ids, keys)
        return np.asarray(newly)[:n]

    def add_each_async(self, tenant_ids, keys):
        """Pipelined add: (device newly-added array, n_valid), no host sync —
        callers (the server's lazy-reply frames, streaming writers) force
        once per batch of flushes."""
        tlh, n = self._pack(tenant_ids, keys)
        if n == 0:
            return np.zeros((0,), bool), 0
        with self._engine.locked(self._name):
            rec = self._rec()
            bits, newly = K.bloom_bank_add_packed(
                rec.arrays["bits"], tlh, K.valid_n(n), rec.meta["k"], rec.meta["m"]
            )
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return newly, n

    def add(self, tenant_ids, keys) -> int:
        """Batch add across tenants; returns # of (probably) new elements."""
        return int(self.add_async(tenant_ids, keys))

    def add_async(self, tenant_ids, keys):
        """Pipelined add: returns the newly-added count as a DEVICE scalar
        without forcing a host sync — streaming writers dispatch flush after
        flush and only the final int() conversion waits."""
        tlh, n = self._pack(tenant_ids, keys)
        if n == 0:
            return np.int32(0)
        with self._engine.locked(self._name):
            rec = self._rec()
            bits, count = K.bloom_bank_add_packed_count(
                rec.arrays["bits"], tlh, K.valid_n(n), rec.meta["k"], rec.meta["m"]
            )
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return count

    def contains(self, tenant_ids, keys) -> np.ndarray:
        """Vectorized membership across tenants: bool array aligned with keys."""
        packed, n = self.contains_async(tenant_ids, keys)
        return K.unpack_found(np.asarray(packed), n)

    def contains_async(self, tenant_ids, keys):
        """Pipelined variant: returns (device uint32 result bitmap, n_valid)
        WITHOUT forcing the device->host transfer — callers keep several
        flushes in flight, force later (jax.device_get / np.asarray), and
        decode with kernels.unpack_found(bitmap, n).  Results travel as
        bitmaps because B bool bytes per flush dominate the d2h path (the
        executeAsync analog of RBatch; dispatches overlap so tunnel/dispatch
        latency amortizes away)."""
        tlh, n = self._pack(tenant_ids, keys, cache_hot=True)
        if n == 0:
            return np.zeros((0,), np.uint32), 0
        with self._engine.locked(self._name):
            rec = self._rec()
            found = K.bloom_bank_contains_packed_bits(
                rec.arrays["bits"], tlh, K.valid_n(n), rec.meta["k"], rec.meta["m"]
            )
        return found, n

    # -- window submission (multi-flush, single transfer) --------------------

    def _pack_flush_window(self, flushes):
        """Pack R flushes into ONE contiguous (3, R*Bb) uint32 buffer staged
        to the device in a single async copy.

        The RBatch discipline taken one level further: the reference batches
        k*N SETBIT/GETBITs of one logical op into one CommandsData frame
        (command/CommandBatchService.java:87-151); a window submission
        batches R whole flushes into one frame.  One large copy sustains
        tunnel bandwidth that R small pipelined copies measurably do not
        (the tunnel's async-copy path degrades with copy COUNT, not bytes).

        Each flush gets a uniform Bb = bucket_size(max_len) slot; the slack
        is filled by REPEATING the flush's last entry, so the same packed
        buffer is valid for add (scatter-OR is idempotent; repeats set the
        same bits again) and for contains (repeat results are discarded at
        unpack).  Returns (device buffer, Bb, lengths)."""
        if not flushes:
            raise ValueError("empty window")
        # identity dedupe: window position -> unique-flush slot.  Keyed on the
        # CALLER's array objects (all alive in `flushes`, so ids are unique
        # among them) — exact, and costs nothing for all-distinct windows.
        slot_of: dict = {}
        first_pos: list = []
        idx = np.empty(len(flushes), np.int32)
        for i, (t, k) in enumerate(flushes):
            key = (id(t), id(k))
            s = slot_of.get(key)
            if s is None:
                s = slot_of[key] = len(first_pos)
                first_pos.append(i)
            idx[i] = s
        rows = [
            self._validate_flush(*flushes[i], allow_empty=False) for i in first_pos
        ]
        lengths = [rows[idx[i]][1].shape[0] for i in range(len(flushes))]
        bb = K.bucket_size(max(lengths))

        def fill(dst, t, arr):
            n = arr.shape[0]
            lo, hi = H.int_keys_to_u32_pair(arr)
            dst[0, :n] = t.view(np.uint32)
            dst[1, :n] = lo
            dst[2, :n] = hi
            if n < bb:  # repeat-pad: idempotent for add, ignored for contains
                dst[:, n:bb] = dst[:, n - 1 : n]

        if len(rows) == len(flushes):
            # all distinct: one flat buffer, no device-side composition.
            # The buffer comes from the engine's double-buffered staging
            # pool (overlap plane): packing window W+1 overlaps window W's
            # still-in-flight upload instead of waiting allocator + DMA.
            pool = self._engine.staging_pool()
            shape = (3, len(rows) * bb)
            if pool is None:
                buf, slot = np.zeros(shape, np.uint32), None
            else:
                buf, slot = pool.acquire(shape, np.uint32)
            try:
                for i, (t, arr) in enumerate(rows):
                    fill(buf[:, i * bb : (i + 1) * bb], t, arr)
                staged = K.stage(buf)
            except BaseException:
                if pool is not None:
                    pool.release(slot)  # never leak a busy slot on error
                raise
            if pool is not None:
                pool.commit(slot, staged)
            return staged, bb, lengths
        # repeated flushes: upload UNIQUE buffers once, compose the window
        # in HBM (kernels.window_from_unique) — R-x less tunnel traffic for
        # hot-set workloads that re-submit the same query buffers
        uniq = np.zeros((len(rows), 3, bb), np.uint32)
        for s, (t, arr) in enumerate(rows):
            fill(uniq[s], t, arr)
        tlh = K.window_from_unique(K.stage(uniq), K.stage(idx))
        return tlh, bb, lengths

    def contains_flushes_async(self, flushes):
        """Submit R contains flushes as ONE upload + ONE kernel dispatch.

        Returns (device uint32 bitmap over R*Bb entries, Bb, lengths); decode
        flush i with kernels.unpack_found on the [i*Bb, i*Bb+lengths[i])
        slice (contains_flushes does this).  This is the throughput path for
        pipelined multi-flush workloads (BASELINE config 2)."""
        tlh, bb, lengths = self._pack_flush_window(flushes)
        total = tlh.shape[1]
        with self._engine.locked(self._name):
            rec = self._rec()
            packed = K.bloom_bank_contains_packed_bits(
                rec.arrays["bits"], tlh, K.valid_n(total), rec.meta["k"], rec.meta["m"]
            )
        return packed, bb, lengths

    def contains_flushes(self, flushes) -> list:
        """Sync window submission: list of bool arrays, one per flush."""
        packed, bb, lengths = self.contains_flushes_async(flushes)
        full = K.unpack_found(np.asarray(packed), len(lengths) * bb)
        return [full[i * bb : i * bb + n] for i, n in enumerate(lengths)]

    def add_flushes_async(self, flushes):
        """Submit R add flushes as ONE upload + ONE kernel dispatch; returns
        (device newly-added uint32 bitmap, Bb, lengths) without a host sync
        — the bulk-populate path (one transfer for a whole ingest window)."""
        tlh, bb, lengths = self._pack_flush_window(flushes)
        total = tlh.shape[1]
        with self._engine.locked(self._name):
            rec = self._rec()
            bits, newly = K.bloom_bank_add_packed_bits(
                rec.arrays["bits"], tlh, K.valid_n(total), rec.meta["k"], rec.meta["m"]
            )
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return newly, bb, lengths

    def add_flushes(self, flushes) -> list:
        """Sync window submission: newly-added count per flush.

        Positions past lengths[i] (the repeat-padding) are sliced off before
        counting, so padding never inflates counts.  "Newly" is evaluated
        against the bank state at WINDOW start (one batch-parallel dispatch):
        a key appearing in two flushes of the same window counts as new in
        both — identical to the existing semantics for duplicate keys inside
        a single flush."""
        newly, bb, lengths = self.add_flushes_async(flushes)
        full = K.unpack_found(np.asarray(newly), len(lengths) * bb)
        return [int(full[i * bb : i * bb + n].sum()) for i, n in enumerate(lengths)]

    def clear_tenant(self, tenant_id: int) -> None:
        with self._engine.locked(self._name):
            rec = self._rec()
            rec.arrays["bits"] = rec.arrays["bits"].at[tenant_id].set(jnp.uint8(0))
            self._touch_version(rec)

    def tenant_bit_counts(self) -> np.ndarray:
        """Per-tenant set-bit counts (fill monitoring / growth policy input)."""
        with self._engine.locked(self._name):
            rec = self._rec()
            return np.asarray(jnp.sum(rec.arrays["bits"].astype(jnp.int32), axis=1))
