"""TimeSeries: timestamp-ordered values with TTL and range queries.

Parity target: RTimeSeries — ``org/redisson/RedissonTimeSeries.java`` (989
LoC): add(timestamp, value[, label]) with optional per-entry TTL, get,
range/rangeReversed (+limit), pollFirst/pollLast, first/last/firstTimestamp/
lastTimestamp, removeRange, size.  The reference stores a ZSET by timestamp +
value map; here a sorted host list with vectorized range scans as the device
upgrade path.
"""
from __future__ import annotations

import bisect
import time
from typing import Any, Iterable, List, Optional, Tuple

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class TimeSeries(RExpirable):
    _kind = "timeseries"

    def _rec_or_create(self) -> StateRecord:
        # host: sorted list of [ts, value_enc, label_enc|None, expire_at|None]
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=[])
        )

    def _reap(self, rec) -> None:
        now = time.time()
        rec.host[:] = [c for c in rec.host if c[3] is None or c[3] > now]

    def add(self, timestamp: float, value, label=None, ttl: Optional[float] = None) -> None:
        cell = [
            float(timestamp),
            self._codec.encode(value),
            self._codec.encode(label) if label is not None else None,
            time.time() + ttl if ttl else None,
        ]
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            # replace same-timestamp entry (ZADD semantics)
            i = bisect.bisect_left([c[0] for c in rec.host], cell[0])
            if i < len(rec.host) and rec.host[i][0] == cell[0]:
                rec.host[i] = cell
            else:
                rec.host.insert(i, cell)
            self._touch_version(rec)

    def add_all(self, entries: dict, ttl: Optional[float] = None) -> None:
        for ts, v in entries.items():
            self.add(ts, v, ttl=ttl)

    def get(self, timestamp: float):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            for c in rec.host:
                if c[0] == timestamp:
                    return self._codec.decode(c[1])
            return None

    def remove(self, timestamp: float) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            before = len(rec.host)
            rec.host[:] = [c for c in rec.host if c[0] != timestamp]
            changed = len(rec.host) != before
            if changed:
                self._touch_version(rec)
            return changed

    def size(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            return len(rec.host)

    def range(self, from_ts: float, to_ts: float, limit: Optional[int] = None) -> List[Tuple[float, Any]]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            out = [
                (c[0], self._codec.decode(c[1]))
                for c in rec.host
                if from_ts <= c[0] <= to_ts
            ]
        return out[:limit] if limit is not None else out

    def range_reversed(self, from_ts: float, to_ts: float, limit: Optional[int] = None):
        out = list(reversed(self.range(from_ts, to_ts)))
        return out[:limit] if limit is not None else out

    def remove_range(self, from_ts: float, to_ts: float) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            before = len(rec.host)
            rec.host[:] = [c for c in rec.host if not (from_ts <= c[0] <= to_ts)]
            n = before - len(rec.host)
            if n:
                self._touch_version(rec)
            return n

    def first(self, count: int = 1) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            return [self._codec.decode(c[1]) for c in rec.host[:count]]

    def last(self, count: int = 1) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            return [self._codec.decode(c[1]) for c in rec.host[-count:]][::-1]

    def first_timestamp(self) -> Optional[float]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            return rec.host[0][0] if rec.host else None

    def last_timestamp(self) -> Optional[float]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            return rec.host[-1][0] if rec.host else None

    def poll_first(self, count: int = 1) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            out, rec.host[:count] = [self._codec.decode(c[1]) for c in rec.host[:count]], []
            if out:
                self._touch_version(rec)
            return out

    def poll_last(self, count: int = 1) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            if not rec.host:
                return []
            taken = rec.host[-count:]
            del rec.host[-count:]
            self._touch_version(rec)
            return [self._codec.decode(c[1]) for c in reversed(taken)]
