"""HyperLogLog handle (BASELINE.md config 3).

Parity target: ``org/redisson/RedissonHyperLogLog.java:71-102`` — add/addAll
(PFADD), count (PFCOUNT), countWith (PFCOUNT key1 key2...), mergeWith
(PFMERGE).  The reference delegates all sketch math to the Redis server;
here it runs as HllTensor kernels (ops/hll.py) over device registers, so a
streaming add is one scatter-max and a merge is one elementwise max.
"""
from __future__ import annotations

from typing import Iterable

import numpy as np

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import hll as hll_ops
from redisson_tpu.utils import hashing as H


class HyperLogLog(RExpirable):
    def _rec_or_create(self) -> StateRecord:
        def factory():
            return StateRecord(
                kind="hll",
                meta={"p": hll_ops.DEFAULT_P, "hash": H.HASH_NAME},
                arrays={"regs": hll_ops.make(hll_ops.DEFAULT_P)},
            )

        return self._engine.store.get_or_create(self._name, "hll", factory)

    def create_if_absent(self) -> None:
        """Create the (empty) register bank if absent (PFADD with no args).
        Named to avoid colliding with RObject.touch's last-access contract."""
        self._rec_or_create()

    def add(self, obj) -> bool:
        """PFADD semantics: True if any register changed."""
        return self.add_all([obj] if not isinstance(obj, np.ndarray) else obj)

    def add_all(self, objs) -> bool:
        kind, arrays, n = self._engine.pack_keys(objs, self._codec)
        if n == 0:
            return False
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            p = rec.meta["p"]
            regs = rec.arrays["regs"]
            if kind == "u64":
                new_regs = K.hll_add_packed(regs, arrays, K.valid_n(n), p)
            else:
                words, nbytes = arrays
                new_regs = K.hll_add_bytes(regs, words, nbytes, n, p)
            rec.arrays["regs"] = new_regs
            self._touch_version(rec)
        # PFADD returns whether the estimate may have changed; tracking exact
        # register deltas costs an extra gather — report True on any add.
        return True

    def count(self) -> int:
        # Locked dispatch: concurrent add_all donates the register buffer.
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            est = K.hll_estimate(rec.arrays["regs"])
        return int(round(float(est)))

    @staticmethod
    def _spans_devices(regs_list) -> bool:
        """True when the registers live on MORE than one committed device
        (device-sharded placement put the slots on different chips)."""
        from redisson_tpu.core.ioplane import device_of

        seen = {d for d in map(device_of, regs_list) if d is not None}
        return len(seen) > 1

    def count_with(self, *other_names: str) -> int:
        """PFCOUNT over the union of this and other counters, non-destructive.

        Registers spanning devices (device-sharded slots) merge ON-DEVICE
        through the mesh collectives / d2d transfers
        (parallel.manager.merge_across_devices) — never a host gather."""
        names = (self._name, *(self._map_name(n) for n in other_names))
        with self._engine.locked_many(names):
            all_regs = []
            for nm in names:
                rec = self._engine.store.get(nm)
                if rec is not None:
                    all_regs.append(rec.arrays["regs"])
            if not all_regs:
                return 0
            if self._spans_devices(all_regs):
                from redisson_tpu.parallel.manager import merge_across_devices

                regs = merge_across_devices(all_regs)
            else:
                regs = None
                for r in all_regs:
                    # merge produces a fresh array, so the estimate below
                    # never aliases a live (donatable) record buffer
                    regs = hll_ops.merge(r, r) if regs is None else hll_ops.merge(regs, r)
            est = K.hll_estimate(regs)
        return int(round(float(est)))

    def merge_with(self, *other_names: str) -> None:
        """PFMERGE other counters into this one (RedissonHyperLogLog.java:96-102).
        Cross-device sources merge on-device (see count_with) and the result
        lands committed back on THIS record's device."""
        other_names = [self._map_name(n) for n in other_names]
        with self._engine.locked_many((self._name, *other_names)):
            rec = self._rec_or_create()
            regs = rec.arrays["regs"]
            sources = []
            for nm in other_names:
                if nm == self._name:  # self-merge is a no-op (and would alias
                    continue          # the donated buffer as a second arg)
                other = self._engine.store.get(nm)
                if other is None:
                    continue
                if other.kind != "hll":
                    raise TypeError(f"'{nm}' is not a HyperLogLog")
                sources.append(other.arrays["regs"])
            if sources and self._spans_devices([regs, *sources]):
                from redisson_tpu.core.ioplane import device_of
                from redisson_tpu.parallel.manager import merge_across_devices

                regs = merge_across_devices(
                    [regs, *sources], dest_device=device_of(regs)
                )
            else:
                for src in sources:
                    regs = K.hll_merge(regs, src)
            rec.arrays["regs"] = regs
            self._touch_version(rec)
