"""Semaphores, CountDownLatch, RateLimiter.

Parity targets:
  * RSemaphore — ``org/redisson/RedissonSemaphore.java`` (526 LoC): counter +
    release channel wakeups; trySetPermits/acquire/release/drain/addPermits.
  * RPermitExpirableSemaphore — ``RedissonPermitExpirableSemaphore.java``
    (909 LoC): permits are leased by id with a timeout ZSET; expired leases
    return to the pool; release by permit id.
  * RCountDownLatch — ``RedissonCountDownLatch.java`` + CountDownLatchPubSub:
    trySetCount/countDown/await.
  * RRateLimiter — ``RedissonRateLimiter.java`` (367 LoC): token bucket over
    a sliding interval, OVERALL or PER_CLIENT scope.

Same synchronizer template as lock.py: atomic compare-and-mutate under the
record lock + wait-entry wakeups (the Lua + pubsub pattern, SURVEY.md §3.3).
"""
from __future__ import annotations

import time
import uuid
from typing import List, Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class Semaphore(RExpirable):
    _kind = "semaphore"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={"permits": 0})
        )

    def _wait(self):
        return self._engine.wait_entry(f"__sem__:{self._name}")

    def try_set_permits(self, permits: int) -> bool:
        """Initialize the pool only if unset (RedissonSemaphore.trySetPermits)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.meta.get("initialized"):
                return False
            rec.meta["initialized"] = True
            rec.host["permits"] = permits
            self._touch_version(rec)
            return True

    def available_permits(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else rec.host["permits"]

    def try_acquire(self, permits: int = 1, wait_time: float = 0.0) -> bool:
        deadline = time.time() + wait_time
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                if rec.host["permits"] >= permits:
                    rec.host["permits"] -= permits
                    self._touch_version(rec)
                    return True
            remaining = deadline - time.time()
            if remaining <= 0:
                return False
            self._wait().wait_for(min(remaining, 1.0))

    def acquire(self, permits: int = 1) -> None:
        while not self.try_acquire(permits, wait_time=1.0):
            pass

    def release(self, permits: int = 1) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["permits"] += permits
            self._touch_version(rec)
        self._wait().signal(all_=True)

    def add_permits(self, permits: int) -> None:
        self.release(permits) if permits > 0 else self._reduce(-permits)

    def _reduce(self, permits: int) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["permits"] -= permits
            self._touch_version(rec)

    def drain_permits(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            n = rec.host["permits"]
            rec.host["permits"] = 0
            if n:
                self._touch_version(rec)
            return n


class PermitExpirableSemaphore(RExpirable):
    """RPermitExpirableSemaphore: leased permits identified by id."""

    _kind = "permit_semaphore"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(kind=self._kind, host={"permits": 0, "leases": {}}),
        )

    def _wait(self):
        return self._engine.wait_entry(f"__psem__:{self._name}")

    def _reap(self, rec) -> None:
        now = time.time()
        expired = [pid for pid, exp in rec.host["leases"].items() if exp is not None and now >= exp]
        for pid in expired:
            del rec.host["leases"][pid]
            rec.host["permits"] += 1

    def try_set_permits(self, permits: int) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.meta.get("initialized"):
                return False
            rec.meta["initialized"] = True
            rec.host["permits"] = permits
            self._touch_version(rec)
            return True

    def available_permits(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            return rec.host["permits"]

    def try_acquire(self, wait_time: float = 0.0, lease_time: Optional[float] = None) -> Optional[str]:
        """Returns a permit id, or None on timeout (reference returns the id
        or throws; Optional is the pythonic equivalent)."""
        deadline = time.time() + wait_time
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                self._reap(rec)
                if rec.host["permits"] > 0:
                    rec.host["permits"] -= 1
                    pid = uuid.uuid4().hex
                    rec.host["leases"][pid] = (
                        time.time() + lease_time if lease_time is not None else None
                    )
                    self._touch_version(rec)
                    return pid
            remaining = deadline - time.time()
            if remaining <= 0:
                return None
            self._wait().wait_for(min(remaining, 1.0))

    def acquire(self, lease_time: Optional[float] = None) -> str:
        while True:
            pid = self.try_acquire(wait_time=1.0, lease_time=lease_time)
            if pid is not None:
                return pid

    def release(self, permit_id: str) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            if permit_id not in rec.host["leases"]:
                return False
            del rec.host["leases"][permit_id]
            rec.host["permits"] += 1
            self._touch_version(rec)
        self._wait().signal(all_=True)
        return True

    def update_lease_time(self, permit_id: str, lease_time: float) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            self._reap(rec)
            if permit_id not in rec.host["leases"]:
                return False
            rec.host["leases"][permit_id] = time.time() + lease_time
            self._touch_version(rec)
            return True


class CountDownLatch(RExpirable):
    _kind = "count_down_latch"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={"count": 0})
        )

    def _wait(self):
        return self._engine.wait_entry(f"__latch__:{self._name}")

    def try_set_count(self, count: int) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.host["count"] > 0:
                return False
            rec.host["count"] = count
            self._touch_version(rec)
            return True

    def get_count(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else rec.host["count"]

    def count_down(self) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.host["count"] > 0:
                rec.host["count"] -= 1
                self._touch_version(rec)
            released = rec.host["count"] == 0
        if released:
            self._wait().signal(all_=True)

    def await_(self, timeout: Optional[float] = None) -> bool:
        deadline = None if timeout is None else time.time() + timeout
        while self.get_count() > 0:
            remaining = None if deadline is None else deadline - time.time()
            if remaining is not None and remaining <= 0:
                return False
            self._wait().wait_for(min(remaining, 1.0) if remaining is not None else 1.0)
        return True


class RateLimiter(RExpirable):
    """RRateLimiter: token bucket over a sliding interval.

    rate/rate_interval mirror trySetRate(mode, rate, rateInterval, unit);
    modes OVERALL (one shared bucket) and PER_CLIENT (bucket per client
    instance) as in ``api/RateType``.
    """

    _kind = "rate_limiter"
    OVERALL = "OVERALL"
    PER_CLIENT = "PER_CLIENT"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(kind=self._kind, host={"buckets": {}}),
        )

    def _wait(self):
        return self._engine.wait_entry(f"__rate__:{self._name}")

    def _client_key(self) -> str:
        rec = self._engine.store.get(self._name)
        if rec is not None and rec.meta.get("mode") == self.PER_CLIENT:
            cid = getattr(self._engine, "_client_uuid", None) or "local"
            return cid
        return "__overall__"

    def try_set_rate(self, mode: str, rate: int, rate_interval: float) -> bool:
        if mode not in (self.OVERALL, self.PER_CLIENT):
            raise ValueError(f"unknown rate mode {mode!r}")
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if "rate" in rec.meta:
                return False
            rec.meta.update(mode=mode, rate=rate, interval=rate_interval)
            self._touch_version(rec)
            return True

    def set_rate(self, mode: str, rate: int, rate_interval: float) -> None:
        """Overwrite the rate config and reset buckets (RRateLimiter.setRate)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.meta.update(mode=mode, rate=rate, interval=rate_interval)
            rec.host["buckets"].clear()
            self._touch_version(rec)

    def _try_take(self, permits: int) -> Optional[float]:
        """None = granted; else seconds until enough tokens refill."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if "rate" not in rec.meta:
                raise RuntimeError(f"RateLimiter '{self._name}' is not initialized")
            rate, interval = rec.meta["rate"], rec.meta["interval"]
            if permits > rate:
                raise ValueError(f"requested {permits} permits > rate {rate}")
            now = time.time()
            key = self._client_key()
            used: List[float] = rec.host["buckets"].setdefault(key, [])
            # sliding window: drop grants older than the interval
            cutoff = now - interval
            while used and used[0] <= cutoff:
                used.pop(0)
            if len(used) + permits <= rate:
                used.extend([now] * permits)
                self._touch_version(rec)
                return None
            need = len(used) + permits - rate
            return max(0.0, used[need - 1] + interval - now)

    def try_acquire(self, permits: int = 1, timeout: float = 0.0) -> bool:
        deadline = time.time() + timeout
        while True:
            delay = self._try_take(permits)
            if delay is None:
                return True
            remaining = deadline - time.time()
            if remaining <= 0:
                return False
            time.sleep(min(delay + 1e-4, remaining))

    def acquire(self, permits: int = 1) -> None:
        while not self.try_acquire(permits, timeout=10.0):
            pass

    def available_permits(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if "rate" not in rec.meta:
                return 0
            now = time.time()
            key = self._client_key()
            used = rec.host["buckets"].get(key, [])
            cutoff = now - rec.meta["interval"]
            live = sum(1 for t in used if t > cutoff)
            return rec.meta["rate"] - live

    def get_config(self) -> dict:
        rec = self._engine.store.get(self._name)
        if rec is None or "rate" not in rec.meta:
            return {}
        return {k: rec.meta[k] for k in ("mode", "rate", "interval")}
