"""BitSet handle.

Parity target: ``org/redisson/RedissonBitSet.java`` (511 LoC) — SETBIT/GETBIT
(:109-150), BITCOUNT cardinality (:278), BITOP AND/OR/XOR against other bit
sets (:387-446), NOT (:304), BITPOS (:483), length, toByteArray.

TPU-first: a bit set is a resident expanded bit plane (ops/bittensor.py);
single-bit calls are 1-element batches, the real surface is the vectorized
set_each/get_each used by batch flushes and BITOP which runs as one
elementwise kernel per operand instead of a server-side BITOP command.
"""
from __future__ import annotations

from typing import Iterable, Optional, Sequence

import numpy as np

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import bittensor as bt

_DEFAULT_BITS = 1 << 20


class BitSet(RExpirable):
    def _rec_or_create(self, min_bits: int = 0) -> StateRecord:
        def factory():
            return StateRecord(
                kind="bitset",
                meta={"nbits": max(_DEFAULT_BITS, bt.padded_size(min_bits))},
                arrays={"bits": bt.make(max(_DEFAULT_BITS, min_bits))},
            )

        rec = self._engine.store.get_or_create(self._name, "bitset", factory)
        if min_bits > rec.meta["nbits"]:
            self._grow(rec, min_bits)
        return rec

    def _grow(self, rec: StateRecord, min_bits: int) -> None:
        """Grow the plane (Redis strings auto-grow on SETBIT past the end)."""
        new_size = bt.padded_size(max(min_bits, rec.meta["nbits"] * 2))
        old = rec.arrays["bits"]
        new = bt.make(new_size)
        rec.arrays["bits"] = new.at[: old.shape[0]].set(old)
        rec.meta["nbits"] = new_size

    # -- single-bit surface (reference RBitSet.get/set) ---------------------

    def set(self, index: int, value: bool = True) -> bool:
        """Set one bit, returning its previous value (SETBIT reply)."""
        return bool(self.set_each(np.asarray([index], np.int64), value)[0])

    def get(self, index: int) -> bool:
        return bool(self.get_each(np.asarray([index], np.int64))[0])

    def clear_bit(self, index: int) -> bool:
        return self.set(index, False)

    # -- vectorized surface (the batch-coalesced fast path) -----------------

    MAX_BIT = 2**31 - 1024  # int32 index space minus plane padding

    def _check_range(self, idx: np.ndarray) -> None:
        if idx.size and (int(idx.min()) < 0 or int(idx.max()) > self.MAX_BIT):
            raise ValueError(
                f"bit index out of range [0, {self.MAX_BIT}] — int32 kernel "
                "index space (Redis allows up to 2^32; shard larger planes)"
            )

    def set_each(self, indexes: np.ndarray, value: bool = True) -> np.ndarray:
        """Batch SETBIT; returns previous bit values aligned with indexes."""
        old, n = self.set_each_async(indexes, value)
        return np.asarray(old)[:n]

    def set_each_async(self, indexes: np.ndarray, value: bool = True):
        """Pipelined batch SETBIT: (device previous-values array, n_valid),
        no host sync (the server's lazy-reply frames force per frame)."""
        self._check_range(np.asarray(indexes, np.int64))
        idx = np.ascontiguousarray(indexes, np.int32)
        n = idx.shape[0]
        if n == 0:
            return np.zeros((0,), np.uint8), 0
        b = K.pow2_bucket(n)
        vals = K.stage(np.full((b,), 1 if value else 0, np.uint8))
        with self._engine.locked(self._name):
            rec = self._rec_or_create(int(idx.max()) + 1 if n else 0)
            bits, old = K.bitset_set(
                rec.arrays["bits"], K.stage(K.pad_to(idx, b)), K.valid_n(n), vals
            )
            rec.arrays["bits"] = bits
            self._touch_version(rec)
        return old, n

    def get_each(self, indexes: np.ndarray) -> np.ndarray:
        got, n = self.get_each_async(indexes)
        return np.asarray(got)[:n]

    def get_each_async(self, indexes: np.ndarray):
        self._check_range(np.asarray(indexes, np.int64))
        idx = np.ascontiguousarray(indexes, np.int32)
        n = idx.shape[0]
        if n == 0:
            return np.zeros((0,), np.uint8), 0
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return np.zeros(idx.shape, np.uint8), n
            got = K.bitset_get(
                rec.arrays["bits"], K.stage(K.pad_to(idx, K.pow2_bucket(n)))
            )
        return got, n

    def set_range(self, from_index: int, to_index: int, value: bool = True) -> None:
        """RBitSet.set(from, to) — contiguous range."""
        self.set_each(np.arange(from_index, to_index, dtype=np.int64), value)

    # -- aggregates ---------------------------------------------------------

    def cardinality(self) -> int:
        """BITCOUNT (RedissonBitSet.java:278)."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            return int(K.bitset_popcount(rec.arrays["bits"], rec.meta["nbits"]))

    def length(self) -> int:
        """Highest set bit + 1 (RedissonBitSet length())."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            return int(K.bitset_length(rec.arrays["bits"]))

    def size(self) -> int:
        """Allocated plane size in bits (RBitSet.size = string length * 8)."""
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else rec.meta["nbits"]

    def bitpos(self, value: bool) -> int:
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0 if not value else -1
            return int(K.bitset_bitpos(rec.arrays["bits"], 1 if value else 0, rec.meta["nbits"]))

    # -- BITOP against other bit sets (RedissonBitSet.java:387-446) ---------

    def _binary_op(self, op, other_names: Sequence[str]) -> None:
        from redisson_tpu.core import ioplane

        other_names = [self._map_name(n) for n in other_names]
        names = (self._name, *other_names)
        with self._engine.locked_many(names):
            rec = self._rec_or_create()
            acc = rec.arrays["bits"]
            acc_dev = ioplane.device_of(acc)
            for nm in other_names:
                if nm == self._name:
                    continue
                other = self._engine.store.get(nm)
                if other is None:
                    o_bits = bt.make(rec.meta["nbits"])
                elif other.kind != "bitset":
                    raise TypeError(f"'{nm}' is not a BitSet")
                else:
                    # device-sharded slots: a source plane on another device
                    # hops over d2d (never through the host) before the
                    # donated combine — ioplane.colocate, counted
                    o_bits = ioplane.colocate(other.arrays["bits"], acc_dev)
                if o_bits.shape[0] > acc.shape[0]:
                    grown = bt.make(o_bits.shape[0])
                    acc = grown.at[: acc.shape[0]].set(acc)
                    rec.meta["nbits"] = o_bits.shape[0]
                elif o_bits.shape[0] < acc.shape[0]:
                    grown = bt.make(acc.shape[0])
                    o_bits = grown.at[: o_bits.shape[0]].set(o_bits)
                acc = op(acc, o_bits)
            rec.arrays["bits"] = acc
            self._touch_version(rec)

    def and_(self, *other_names: str) -> None:
        self._binary_op(K.bitset_and, other_names)

    def or_(self, *other_names: str) -> None:
        self._binary_op(K.bitset_or, other_names)

    def xor(self, *other_names: str) -> None:
        self._binary_op(K.bitset_xor, other_names)

    def not_(self) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.arrays["bits"] = K.bitset_not(rec.arrays["bits"], rec.meta["nbits"])
            self._touch_version(rec)

    # -- serialization ------------------------------------------------------

    def to_byte_array(self) -> bytes:
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return b""
            nbits = rec.meta["nbits"]
            host = np.asarray(rec.arrays["bits"])
        return bt.to_packed(host, nbits)

    def from_byte_array(self, data: bytes) -> None:
        nbits = len(data) * 8
        with self._engine.locked(self._name):
            rec = self._rec_or_create(nbits)
            import jax.numpy as jnp

            host = bt.from_packed(data, nbits)
            plane = rec.arrays["bits"]
            rec.arrays["bits"] = plane.at[: host.shape[0]].set(jnp.asarray(host))
            self._touch_version(rec)
