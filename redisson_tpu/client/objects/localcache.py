"""LocalCachedMap: Map with a per-handle near cache + invalidation topic.

Parity target: RLocalCachedMap (``RedissonLocalCachedMap.java``,
``cache/LocalCacheListener.java:49-290``).  Each handle keeps a bounded local
cache of decoded entries; mutations publish to an invalidation channel
(`redisson_local_cache:{name}` here, mirroring the reference's
`{name}:topic`) so every *other* handle either drops (INVALIDATE) or applies
(UPDATE) the entry.  Messages carry the publishing handle's cache-id, and a
handle ignores its own messages — exactly the reference's excludedId scheme.

Strategies (same names and meanings as the reference enums):
  * SyncStrategy NONE / INVALIDATE / UPDATE
  * ReconnectionStrategy NONE / CLEAR / LOAD  (applied by `on_reconnect()`,
    which the remote client invokes from its watchdog after a re-connect)
  * EvictionPolicy NONE / LRU / LFU — bounded by `cache_size`
  * per-entry `time_to_live` / `max_idle` on the local copies

The local cache is a host-side structure only — reads that hit it never touch
the device path at all, which is the entire point (the reference's Caffeine
near cache saves a network hop; this one saves a dispatch).
"""
from __future__ import annotations

import time
import uuid
from collections import OrderedDict
from typing import Any, Dict, Optional, Tuple

from redisson_tpu.client.objects.map import Map, MapOptions


class EvictionPolicy:
    NONE = "NONE"
    LRU = "LRU"
    LFU = "LFU"


class SyncStrategy:
    NONE = "NONE"
    INVALIDATE = "INVALIDATE"
    UPDATE = "UPDATE"
    # Server-assisted mode (ISSUE 7): coherence rides the CLIENT TRACKING
    # invalidation plane (tracking/) instead of the hand-rolled topic —
    # writers need not publish anything; the server remembers which
    # connections read the map and pushes RESP3 invalidations on write.
    # Wire handles (client/remote.py RemoteLocalCachedMap) require the
    # facade's tracking plane (client.enable_tracking()); the EMBEDDED
    # handle below has no wire, so it degrades to INVALIDATE topic
    # semantics — in-process peers are coherent either way.
    TRACKING = "TRACKING"


class ReconnectionStrategy:
    NONE = "NONE"
    CLEAR = "CLEAR"
    LOAD = "LOAD"


class LocalCachedMapOptions(MapOptions):
    """Mirror of api/LocalCachedMapOptions defaults (cacheSize=0 unbounded,
    LRU not enforced unless sized, syncStrategy=INVALIDATE)."""

    def __init__(
        self,
        *,
        cache_size: int = 0,
        eviction_policy: str = EvictionPolicy.NONE,
        time_to_live: Optional[float] = None,
        max_idle: Optional[float] = None,
        sync_strategy: str = SyncStrategy.INVALIDATE,
        reconnection_strategy: str = ReconnectionStrategy.NONE,
        **kw,
    ):
        super().__init__(**kw)
        self.cache_size = cache_size
        self.eviction_policy = eviction_policy
        self.time_to_live = time_to_live
        self.max_idle = max_idle
        self.sync_strategy = sync_strategy
        self.reconnection_strategy = reconnection_strategy

    @classmethod
    def defaults(cls) -> "LocalCachedMapOptions":
        return cls()


class _LocalCache:
    """Bounded decoded-entry cache: value + timestamps + LFU hit counter."""

    __slots__ = ("opts", "data")

    def __init__(self, opts: LocalCachedMapOptions):
        self.opts = opts
        # ek -> [value, created_at, last_access, hits]
        self.data: "OrderedDict[bytes, list]" = OrderedDict()

    def get(self, ek: bytes) -> Tuple[bool, Any]:
        cell = self.data.get(ek)
        if cell is None:
            return False, None
        now = time.time()
        o = self.opts
        if (o.time_to_live is not None and now - cell[1] >= o.time_to_live) or (
            o.max_idle is not None and now - cell[2] >= o.max_idle
        ):
            del self.data[ek]
            return False, None
        cell[2] = now
        cell[3] += 1
        if o.eviction_policy == EvictionPolicy.LRU:
            self.data.move_to_end(ek)
        return True, cell[0]

    def put(self, ek: bytes, value: Any) -> None:
        now = time.time()
        prev = self.data.pop(ek, None)
        self.data[ek] = [value, now, now, prev[3] if prev else 0]
        self._evict()

    def _evict(self) -> None:
        o = self.opts
        if o.cache_size <= 0:
            return
        while len(self.data) > o.cache_size:
            if o.eviction_policy == EvictionPolicy.LFU:
                victim = min(self.data, key=lambda k: self.data[k][3])
                del self.data[victim]
            else:  # LRU order (and insertion order for NONE) — head is oldest
                self.data.popitem(last=False)

    def invalidate(self, ek: bytes) -> None:
        self.data.pop(ek, None)

    def clear(self) -> None:
        self.data.clear()

    def __len__(self) -> int:
        return len(self.data)


class LocalCachedMap(Map):
    """Map + near cache.  Sync messages: ("inv", cache_id, [ek...]) |
    ("upd", cache_id, [(ek, ev)...]) | ("clear", cache_id)."""

    _kind = "map"

    def __init__(self, engine, name, codec=None, options: Optional[LocalCachedMapOptions] = None):
        opts = options or LocalCachedMapOptions.defaults()
        super().__init__(engine, name, codec, opts)
        self._lc_opts = opts
        self._cache = _LocalCache(opts)
        self._cache_id = uuid.uuid4().hex
        self._disabled: set = set()  # active tx-commit disable requests
        self._channel = f"redisson_local_cache:{name}"
        self._listener_id = engine.pubsub.subscribe(self._channel, self._on_sync)
        self.hits = 0
        self.misses = 0

    # -- invalidation plumbing ----------------------------------------------

    def _on_sync(self, channel: str, msg) -> None:
        if isinstance(msg, (bytes, bytearray)):
            # wire clients PUBLISH pickled tuples (client/remote.py
            # RemoteLocalCachedMap._broadcast) — same shape after decode
            from redisson_tpu.net.safe_pickle import safe_loads

            try:
                msg = safe_loads(bytes(msg))
            except Exception:  # noqa: BLE001 — foreign frame on our channel
                return
        kind, sender = msg[0], msg[1]
        if sender == self._cache_id:
            return
        if kind == "inv":
            for ek in msg[2]:
                self._cache.invalidate(ek)
        elif kind == "upd":
            for ek, ev in msg[2]:
                self._cache.put(ek, self._dv(ev))
        elif kind == "clear":
            self._cache.clear()
        elif kind == "disable":
            # transaction commit handshake (LocalCachedMapDisable analog):
            # bypass the near cache until the matching enable — with a
            # failsafe timer in case the committer dies mid-commit
            self._disabled.add(sender)
            self._cache.clear()
            self._engine.schedule_timeout(
                lambda: self._disabled.discard(sender), 30.0
            )
        elif kind == "enable":
            self._disabled.discard(sender)
            self._cache.clear()

    def _broadcast(self, kind: str, payload=None) -> None:
        s = self._lc_opts.sync_strategy
        if s == SyncStrategy.NONE:
            return
        if kind == "upd" and s != SyncStrategy.UPDATE:
            # TRACKING degrades to INVALIDATE on the embedded handle (no
            # wire between in-process peers; see SyncStrategy.TRACKING)
            kind, payload = "inv", [ek for ek, _ in payload]
        self._engine.pubsub.publish(self._channel, (kind, self._cache_id, payload))

    # -- read path -----------------------------------------------------------

    def get(self, key):
        if self._disabled:
            # tx-commit window: read through, never serve or populate the
            # near cache (the reference's disabledKeys discipline)
            return super().get(key)
        ek = self._ek(key)
        hit, value = self._cache.get(ek)
        if hit:
            self.hits += 1
            return value
        self.misses += 1
        # read + cache-populate under the record lock: a writer cannot slip a
        # mutation (whose invalidation we'd miss) between our read and the
        # near-cache insert — the reference serializes the same window through
        # its cache-update listener ordering (LocalCacheListener.java)
        with self._engine.locked(self._name):
            value = super().get(key)
            if value is not None:
                self._cache.put(ek, value)
        return value

    def get_all(self, keys) -> Dict:
        if self._disabled:
            return super().get_all(keys)
        out, missing = {}, []
        for k in keys:
            hit, v = self._cache.get(self._ek(k))
            if hit:
                self.hits += 1
                out[k] = v
            else:
                self.misses += 1
                missing.append(k)
        if missing:
            with self._engine.locked(self._name):
                fetched = super().get_all(missing)
                for k, v in fetched.items():
                    self._cache.put(self._ek(k), v)
            out.update(fetched)
        return out

    # -- transaction commit handshake ----------------------------------------

    def tx_disable(self, req_id: str) -> None:
        """Broadcast + locally apply the near-cache disable for a
        transaction commit (disableLocalCacheAsync analog).  Published with
        the REQUEST id as sender so no subscriber — including this handle —
        is excluded by the own-write filter."""
        self._disabled.add(req_id)
        self._cache.clear()
        self._engine.pubsub.publish(self._channel, ("disable", req_id, None))

    def tx_enable(self, req_id: str) -> None:
        self._disabled.discard(req_id)
        self._cache.clear()
        self._engine.pubsub.publish(self._channel, ("enable", req_id, None))

    # -- write path (mutate shared map, update own cache, notify peers) ------

    def put(self, key, value):
        old = super().put(key, value)
        ek = self._ek(key)
        self._cache.put(ek, value)
        self._broadcast("upd", [(ek, self._ev(value))])
        return old

    def fast_put(self, key, value) -> bool:
        created = super().fast_put(key, value)
        ek = self._ek(key)
        self._cache.put(ek, value)
        self._broadcast("upd", [(ek, self._ev(value))])
        return created

    def put_all(self, entries: Dict) -> None:
        super().put_all(entries)
        payload = []
        for k, v in entries.items():
            ek = self._ek(k)
            self._cache.put(ek, v)
            payload.append((ek, self._ev(v)))
        self._broadcast("upd", payload)

    def put_if_absent(self, key, value):
        prev = super().put_if_absent(key, value)
        if prev is None:  # insert happened
            ek = self._ek(key)
            self._cache.put(ek, value)
            self._broadcast("upd", [(ek, self._ev(value))])
        return prev

    # fast_put_if_absent needs no override: Map.fast_put_if_absent delegates
    # to self.put_if_absent, which dispatches to the override above — a second
    # override here would cache and broadcast every insert twice

    def replace(self, key, value):
        old = super().replace(key, value)
        if old is not None:
            ek = self._ek(key)
            self._cache.put(ek, value)
            self._broadcast("upd", [(ek, self._ev(value))])
        return old

    def replace_if_equals(self, key, expected, update) -> bool:
        ok = super().replace_if_equals(key, expected, update)
        if ok:
            ek = self._ek(key)
            self._cache.put(ek, update)
            self._broadcast("upd", [(ek, self._ev(update))])
        return ok

    def remove_if_equals(self, key, expected) -> bool:
        ok = super().remove_if_equals(key, expected)
        if ok:
            ek = self._ek(key)
            self._cache.invalidate(ek)
            self._broadcast("inv", [ek])
        return ok

    def add_and_get(self, key, delta):
        new = super().add_and_get(key, delta)
        ek = self._ek(key)
        self._cache.put(ek, new)
        self._broadcast("upd", [(ek, self._ev(new))])
        return new

    def remove(self, key):
        old = super().remove(key)
        ek = self._ek(key)
        self._cache.invalidate(ek)
        self._broadcast("inv", [ek])
        return old

    def fast_remove(self, *keys) -> int:
        n = super().fast_remove(*keys)
        eks = [self._ek(k) for k in keys]
        for ek in eks:
            self._cache.invalidate(ek)
        self._broadcast("inv", eks)
        return n

    def clear(self) -> None:
        super().clear()
        self._cache.clear()
        self._engine.pubsub.publish(self._channel, ("clear", self._cache_id))

    # -- local-cache view (LocalCacheView analog) ----------------------------

    def cached_size(self) -> int:
        return len(self._cache)

    def cached_keys(self):
        return [self._dk(ek) for ek in list(self._cache.data.keys())]

    def clear_local_cache(self) -> None:
        self._cache.clear()

    def pre_load_cache(self) -> None:
        """Populate the near cache from the shared map (reference's
        ReconnectionStrategy.LOAD warm-up, LocalCacheListener.java:169-186)."""
        for k, v in super().read_all_entry_set():
            self._cache.put(self._ek(k), v)

    def on_reconnect(self) -> None:
        """Apply the configured ReconnectionStrategy after a connection drop —
        a stale near cache must not serve values missed while disconnected."""
        r = self._lc_opts.reconnection_strategy
        if r == ReconnectionStrategy.CLEAR:
            self._cache.clear()
        elif r == ReconnectionStrategy.LOAD:
            self._cache.clear()
            self.pre_load_cache()

    def destroy(self) -> None:
        """Detach from the invalidation channel (RObject.destroy parity)."""
        self._engine.pubsub.unsubscribe(self._channel, self._listener_id)
        self._cache.clear()
