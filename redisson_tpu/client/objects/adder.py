"""LongAdder / DoubleAdder: write-optimized distributed counters.

Parity target: ``RedissonBaseAdder.java`` (+ RedissonLongAdder /
RedissonDoubleAdder).  The reference trades read cost for write cost: each
`increment()` only touches a handle-local counter; `sum()` publishes to the
adder's topic, every live handle flushes its local value into the shared
atomic, and the caller then reads the aggregate.  `reset()` follows the same
broadcast pattern.

Here the topic is the engine pub/sub hub, whose delivery is synchronous
in-process — so sum() is: publish "flush" (all handles fold in and zero their
locals), then read the shared counter.  Remote handles attach through the
wire-level pubsub the same way.
"""
from __future__ import annotations

import threading
from typing import Optional

from redisson_tpu.client.objects.bucket import AtomicDouble, AtomicLong


class _BaseAdder:
    _atomic_cls = AtomicLong
    _zero = 0

    def __init__(self, engine, name: str):
        self._engine = engine
        self._name = name
        self._atomic = self._atomic_cls(engine, name)
        self._local = self._zero
        self._local_lock = threading.Lock()
        self._channel = f"redisson_adder:{name}"
        self._listener_id = engine.pubsub.subscribe(self._channel, self._on_msg)

    @property
    def name(self) -> str:
        return self._name

    def _on_msg(self, channel: str, msg) -> None:
        kind = msg[0] if isinstance(msg, (tuple, list)) else msg
        if kind == "flush":
            with self._local_lock:
                pending, self._local = self._local, self._zero
            if pending:
                self._atomic.add_and_get(pending)
        elif kind == "reset":
            with self._local_lock:
                self._local = self._zero
        else:
            return
        if isinstance(msg, (tuple, list)) and len(msg) > 1:
            # ack so the aggregating handle knows this handle folded in
            self._engine.pubsub.publish(msg[1], "ack")

    # -- write path: local only (the whole point of an adder) ---------------

    def add(self, delta) -> None:
        with self._local_lock:
            self._local += delta

    def increment(self) -> None:
        self.add(1)

    def decrement(self) -> None:
        self.add(-1)

    # -- read path: aggregate ------------------------------------------------

    def _broadcast_and_wait(self, kind: str, timeout: float) -> None:
        """Publish `kind` and wait for one ack per receiver — the reference's
        semaphore-counted acknowledge (RedissonBaseAdder.sum waits for every
        live handle before reading).  In-process delivery is synchronous so
        acks usually arrive before publish() returns; wire-attached handles
        ack asynchronously and are bounded by `timeout`."""
        import threading
        import uuid as _uuid

        ack_channel = f"{self._channel}:ack:{_uuid.uuid4().hex}"
        acks = threading.Semaphore(0)
        lid = self._engine.pubsub.subscribe(
            ack_channel, lambda _c, _m: acks.release()
        )
        try:
            receivers = self._engine.pubsub.publish(self._channel, (kind, ack_channel))
            import time as _time

            deadline = None if timeout is None else _time.time() + timeout
            for _ in range(receivers):
                remaining = None if deadline is None else max(0.0, deadline - _time.time())
                if not acks.acquire(timeout=remaining):
                    break
        finally:
            self._engine.pubsub.unsubscribe(ack_channel, lid)

    def sum(self, timeout: float = 1.0):
        self._broadcast_and_wait("flush", timeout)
        return self._atomic.get()

    def reset(self, timeout: float = 1.0) -> None:
        self._broadcast_and_wait("reset", timeout)
        self._atomic.set(self._zero)

    def destroy(self) -> None:
        """Flush and detach (RedissonBaseAdder.destroy parity)."""
        with self._local_lock:
            pending, self._local = self._local, self._zero
        if pending:
            self._atomic.add_and_get(pending)
        self._engine.pubsub.unsubscribe(self._channel, self._listener_id)


class LongAdder(_BaseAdder):
    _atomic_cls = AtomicLong
    _zero = 0


class DoubleAdder(_BaseAdder):
    _atomic_cls = AtomicDouble
    _zero = 0.0
