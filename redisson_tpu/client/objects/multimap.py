"""Multimaps: key -> many values.

Parity targets (SURVEY.md §2.5 "Multimaps"):
  * RListMultimap / RSetMultimap — ``RedissonListMultimap*.java`` /
    ``RedissonSetMultimap*.java`` (~4k LoC): per-key value collections,
    get/getAll/put/remove/removeAll/fastRemove, keySet/entries, faceted
    per-key views.
  * Cache variants — per-key TTL (RedissonListMultimapCache / SetMultimapCache).
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional, Tuple

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class _BaseMultimap(RExpirable):
    _kind = "multimap"
    _container = list  # overridden

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(kind=self._kind, host={"data": {}, "ttl": {}}),
        )

    def _ek(self, k) -> bytes:
        return self._codec.encode_map_key(k)

    def _ev(self, v) -> bytes:
        return self._codec.encode_map_value(v)

    def _dk(self, raw):
        return self._codec.decode_map_key(raw)

    def _dv(self, raw):
        return self._codec.decode_map_value(raw)

    def _live(self, rec, ek) -> bool:
        exp = rec.host["ttl"].get(ek)
        if exp is not None and time.time() >= exp:
            rec.host["data"].pop(ek, None)
            rec.host["ttl"].pop(ek, None)
            return False
        return ek in rec.host["data"]

    def put(self, key, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            ek = self._ek(key)
            self._live(rec, ek)
            bucket = rec.host["data"].setdefault(ek, self._container())
            return self._add(rec, bucket, self._ev(value))

    def put_all(self, key, values: Iterable) -> bool:
        changed = False
        for v in values:
            changed |= self.put(key, v)
        return changed

    def put_all_entries(self, mapping) -> int:
        """Bulk merge {key: [values...]} under ONE lock/one wire frame — the
        batch-first citizen MapReduce mappers use to flush a whole partition
        buffer per call instead of one put per emitted key (the reference's
        Collector.emit writes per emit, mapreduce/Collector.java:56-73)."""
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for key, values in mapping.items():
                ek = self._ek(key)
                self._live(rec, ek)
                bucket = rec.host["data"].setdefault(ek, self._container())
                for v in values:
                    if self._add(rec, bucket, self._ev(v)):
                        n += 1
        return n

    def get_all(self, key) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            ek = self._ek(key)
            if not self._live(rec, ek):
                return []
            return [self._dv(v) for v in list(rec.host["data"][ek])]

    def remove(self, key, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            ek = self._ek(key)
            if not self._live(rec, ek):
                return False
            bucket = rec.host["data"][ek]
            ev = self._ev(value)
            if ev not in bucket:
                return False
            bucket.remove(ev)
            if not bucket:
                del rec.host["data"][ek]
                rec.host["ttl"].pop(ek, None)
            self._touch_version(rec)
            return True

    def replace_values(self, key, values) -> List:
        """RListMultimap.replaceValues: swap the key's whole value
        collection atomically; returns the PREVIOUS values (empty values
        clears the key, matching the reference)."""
        with self._engine.locked(self._name):
            old = self.remove_all(key)
            for v in values:
                self.put(key, v)
            return old

    def remove_all(self, key) -> List:
        """Drops the key; returns its values (RMultimap.removeAll)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            ek = self._ek(key)
            if not self._live(rec, ek):
                return []
            vals = [self._dv(v) for v in rec.host["data"].pop(ek)]
            rec.host["ttl"].pop(ek, None)
            self._touch_version(rec)
            return vals

    def fast_remove(self, *keys) -> int:
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for k in keys:
                ek = self._ek(k)
                if self._live(rec, ek):
                    del rec.host["data"][ek]
                    rec.host["ttl"].pop(ek, None)
                    n += 1
            if n:
                self._touch_version(rec)
        return n

    def contains_key(self, key) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return self._live(rec, self._ek(key))

    def contains_entry(self, key, value) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            ek = self._ek(key)
            return self._live(rec, ek) and self._ev(value) in rec.host["data"][ek]

    def key_size(self) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for ek in list(rec.host["data"]):
                self._live(rec, ek)
            return len(rec.host["data"])

    def size(self) -> int:
        """Total number of (key, value) pairs."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            total = 0
            for ek in list(rec.host["data"]):
                if self._live(rec, ek):
                    total += len(rec.host["data"][ek])
            return total

    def read_all_key_set(self) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return [self._dk(ek) for ek in list(rec.host["data"]) if self._live(rec, ek)]

    def entries(self) -> List[Tuple]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            out = []
            for ek in list(rec.host["data"]):
                if self._live(rec, ek):
                    for ev in rec.host["data"][ek]:
                        out.append((self._dk(ek), self._dv(ev)))
            return out

class ListMultimap(_BaseMultimap):
    """RListMultimap: values per key form a list (duplicates kept, order kept)."""

    _kind = "list_multimap"
    _container = list

    def _add(self, rec, bucket: list, ev: bytes) -> bool:
        bucket.append(ev)
        self._touch_version(rec)
        return True


class SetMultimap(_BaseMultimap):
    """RSetMultimap: values per key form a set (encoded uniqueness)."""

    _kind = "set_multimap"
    _container = list  # list-of-unique keeps insertion order deterministic

    def _add(self, rec, bucket: list, ev: bytes) -> bool:
        if ev in bucket:
            return False
        bucket.append(ev)
        self._touch_version(rec)
        return True


class _MultimapCacheMixin:
    """Per-key TTL surface of the cache variants
    (`RedissonListMultimapCache.java` / `RedissonSetMultimapCache.java`):
    the only API the reference adds over the plain multimap is
    `expireKey(key, ttl)`; expiry itself is enforced lazily by `_live` and
    swept by the EvictionScheduler (`eviction/BaseEvictionTask` analog —
    the facade registers `reap_expired` on creation)."""

    def expire_key(self, key, ttl: float) -> bool:
        """RMultimapCache.expireKey — per-key TTL in seconds; False if the
        key is absent (matches the reference's boolean reply)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            ek = self._ek(key)
            if not self._live(rec, ek):
                return False
            rec.host["ttl"][ek] = time.time() + ttl
            self._touch_version(rec)
            return True

    def reap_expired(self) -> int:
        """EvictionScheduler sweep entry point; returns keys removed."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            if rec is None:
                return 0
            before = len(rec.host["data"])
            for ek in list(rec.host["data"]):
                self._live(rec, ek)
            return before - len(rec.host["data"])


class ListMultimapCache(_MultimapCacheMixin, ListMultimap):
    """RListMultimapCache: list multimap + per-key TTL."""

    _kind = "list_multimap_cache"


class SetMultimapCache(_MultimapCacheMixin, SetMultimap):
    """RSetMultimapCache: set multimap + per-key TTL."""

    _kind = "set_multimap_cache"
