"""Object handle base classes.

Parity: every reference object extends ``RedissonObject`` (name + codec +
encode/decode helpers, ``org/redisson/RedissonObject.java``) then
``RedissonExpirable`` (expire/ttl surface, ``RedissonExpirable.java``); all
state lives server-side and handles are cheap & stateless (SURVEY.md §1 L5).
Here the "server" is the engine's DeviceStore.
"""
from __future__ import annotations

import time
from typing import Optional

from redisson_tpu.client.codec import Codec
from redisson_tpu.core.engine import Engine


class RObject:
    def __init__(self, engine: Engine, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.codec import ReferenceCodec

        self._engine = engine
        # NameMapper SPI (config/Config.java NameMapper): logical name ->
        # stored key, applied at handle construction exactly like the
        # reference's RedissonObject ctor maps via config.getNameMapper()
        mapper = getattr(engine.config, "name_mapper", None)
        self._name = mapper.map(name) if mapper is not None else name
        # every handle's codec is reference-aware: storing another handle
        # persists a typed RedissonReference, not a serialized copy
        # (client/codec.py ReferenceCodec; RedissonObjectBuilder analog)
        base = codec or engine.default_codec
        if isinstance(base, ReferenceCodec):
            # rebind to THIS engine: a shipped/shared wrapper may carry no
            # engine (pickled to a worker) or a different one
            self._codec = (
                base if base._engine is engine else ReferenceCodec(base.inner, engine)
            )
        else:
            self._codec = ReferenceCodec(base, engine)

    def __reduce__(self):
        # handles bind an engine (thread locks, device state) and can never
        # cross a process boundary live; they pickle as inert ObjectRef
        # descriptors — the remote result path resolves them back into
        # handles bound to the receiving client (client/remote.py)
        from redisson_tpu.client.codec import ObjectRef, ReferenceCodec, _codec_spec

        codec = self._codec.inner if isinstance(self._codec, ReferenceCodec) else self._codec
        return (
            ObjectRef,
            # references carry the LOGICAL name: resolution re-enters a
            # factory whose ctor maps again (a stored key here would
            # double-map under a NameMapper)
            (type(self).__module__, type(self).__name__,
             self._unmap_name(self._name), _codec_spec(codec)),
        )

    @property
    def name(self) -> str:
        return self._name

    @property
    def codec(self) -> Codec:
        return self._codec

    def is_exists(self) -> bool:
        return self._engine.store.exists(self._name)

    def delete(self) -> bool:
        with self._engine.locked(self._name):
            return self._engine.store.delete(self._name)

    def _map_name(self, name: str) -> str:
        """Logical -> stored key for OTHER-object name parameters (dest
        names, combination operands): cross-key ops must address the same
        namespace this handle's own name was mapped into."""
        mapper = getattr(self._engine.config, "name_mapper", None)
        return mapper.map(name) if mapper is not None else name

    def _unmap_name(self, key: str) -> str:
        mapper = getattr(self._engine.config, "name_mapper", None)
        return mapper.unmap(key) if mapper is not None else key

    def rename(self, new_name: str) -> None:
        mapped = self._map_name(new_name)  # stay inside the namespace
        with self._engine.locked(self._name):
            if not self._engine.store.rename(self._name, mapped):
                raise KeyError(f"object '{self._name}' does not exist")
            self._name = mapped

    # -- lifecycle surface (RObject.java dump/restore/copy/touch/unlink) ----

    def touch(self) -> bool:
        """RObject.touch: True if the object exists (access-clock poke)."""
        return self._engine.store.exists(self._name)

    def unlink(self) -> bool:
        """RObject.unlink — in-process reclamation is immediate, so this is
        delete (the reference's UNLINK/DEL distinction is Redis-internal)."""
        return self.delete()

    def dump(self) -> bytes:
        """Portable serialized state (RObject.dump / the DUMP verb): the
        shared single-record codec — same fields as checkpoint records plus
        a hash_version stamp (core/checkpoint.dump_record)."""
        from redisson_tpu.core import checkpoint

        return checkpoint.dump_record(self._engine, self._name)

    def _restore(self, state: bytes, ttl: Optional[float], replace: bool) -> None:
        from redisson_tpu.core import checkpoint

        checkpoint.restore_record(self._engine, self._name, state, ttl, replace)

    def restore(self, state: bytes, ttl: Optional[float] = None) -> None:
        """RObject.restore: install a dump under this name; BUSYKEY error if
        the name exists (Redis RESTORE semantics)."""
        self._restore(state, ttl, replace=False)

    def restore_and_replace(self, state: bytes, ttl: Optional[float] = None) -> None:
        self._restore(state, ttl, replace=True)

    def copy_to(self, dest_name: str, replace: bool = False) -> bool:
        """RObject.copy: clone this record under `dest_name` (the COPY verb
        and this method share core/checkpoint.clone_record)."""
        from redisson_tpu.core import checkpoint

        return checkpoint.clone_record(
            self._engine, self._name, self._map_name(dest_name), replace
        )

    def migrate(
        self,
        address: str,
        timeout: float = 10.0,
        delete_local: bool = True,
        replace: bool = False,
        password: Optional[str] = None,
        username: Optional[str] = None,
        ssl_context=None,
    ) -> None:
        """RObject.migrate: DUMP here, RESTORE on the node at `address`
        (tpu://host:port), then delete locally — the Redis MIGRATE recipe.
        Mirrors MIGRATE's contracts: the remaining TTL is measured here and
        travels as RESTORE's explicit ttl operand (Redis MIGRATE does the
        same; wire RESTORE treats ttl 0 as persistent), a destination
        collision is BUSYKEY unless `replace` (Redis's REPLACE opt-in), and
        secured destinations take credentials/TLS (the AUTH/AUTH2 knobs)."""
        from redisson_tpu.net.client import NodeClient

        ttl = self._engine.store.ttl(self._name)  # before dump: no expiry race
        blob = self.dump()
        ttl_ms = max(1, int(ttl * 1000)) if ttl is not None else 0
        node = NodeClient(
            address, ping_interval=0, password=password, username=username,
            ssl_context=ssl_context,
        )
        try:
            args = ("RESTORE", self._name, ttl_ms, blob) + (("REPLACE",) if replace else ())
            node.execute(*args, timeout=timeout)  # error replies RAISE RespError
        finally:
            node.close()
        if delete_local:
            self.delete()

    def _record(self):
        return self._engine.store.get(self._name)

    def _touch_version(self, rec) -> None:
        rec.version += 1


class RExpirable(RObject):
    def expire(self, seconds: float) -> bool:
        return self._engine.store.expire(self._name, time.time() + seconds)

    def expire_at(self, epoch_seconds: float) -> bool:
        return self._engine.store.expire(self._name, epoch_seconds)

    def clear_expire(self) -> bool:
        return self._engine.store.expire(self._name, None)

    def remain_time_to_live(self) -> Optional[float]:
        """Seconds until expiry; None if persistent or absent (pttl analog)."""
        return self._engine.store.ttl(self._name)

    # Redis-7 conditional expiry (RExpirable.expireIfSet/NotSet/Greater/Less
    # — the EXPIRE NX|XX|GT|LT options)

    def _expire_if(self, seconds: float, pred) -> bool:
        with self._engine.locked(self._name):
            if not self._engine.store.exists(self._name):
                return False
            current = self._engine.store.ttl(self._name)
            if not pred(current):
                return False
            return self._engine.store.expire(self._name, time.time() + seconds)

    def expire_if_set(self, seconds: float) -> bool:
        """EXPIRE XX: only when a TTL already exists."""
        return self._expire_if(seconds, lambda cur: cur is not None)

    def expire_if_not_set(self, seconds: float) -> bool:
        """EXPIRE NX: only when the object is persistent."""
        return self._expire_if(seconds, lambda cur: cur is None)

    def expire_if_greater(self, seconds: float) -> bool:
        """EXPIRE GT: only extend (persistent counts as infinite, like Redis)."""
        return self._expire_if(
            seconds, lambda cur: cur is not None and seconds > cur
        )

    def expire_if_less(self, seconds: float) -> bool:
        """EXPIRE LT: only shorten (always applies when persistent)."""
        return self._expire_if(seconds, lambda cur: cur is None or seconds < cur)
