"""Topics: pub/sub messaging objects.

Parity targets:
  * RTopic — ``org/redisson/RedissonTopic.java``: addListener/removeListener/
    publish/countSubscribers over PublishSubscribeService.
  * RPatternTopic — PSUBSCRIBE glob patterns.
  * RShardedTopic — ``RedissonShardedTopic.java``: SSUBSCRIBE; in-process the
    shard channel is the same hub keyed by slot (kept for API parity and for
    mesh-mode routing).
  * RReliableTopic — ``RedissonReliableTopic.java:48+``: stream-backed topic
    where each subscriber tracks its own offset and a watchdog expires dead
    subscribers; messages survive subscriber downtime.
"""
from __future__ import annotations

import time
import uuid
from typing import Any, Callable, Dict, List, Optional

from redisson_tpu.client.objects.base import RObject
from redisson_tpu.core.store import StateRecord


class Topic(RObject):
    def publish(self, message: Any) -> int:
        """Returns number of receivers (PUBLISH reply).  The message takes a
        full codec round-trip so listeners observe exactly what a remote
        subscriber would decode."""
        data = self._codec.encode(message)
        return self._engine.pubsub.publish(self._name, self._codec.decode(data))

    def add_listener(self, listener: Callable[[str, Any], None]) -> int:
        return self._engine.pubsub.subscribe(self._name, listener)

    def remove_listener(self, listener_id: int) -> None:
        self._engine.pubsub.unsubscribe(self._name, listener_id)

    def count_subscribers(self) -> int:
        return self._engine.pubsub.subscriber_count(self._name)


class PatternTopic:
    """RPatternTopic: glob-pattern subscription."""

    def __init__(self, engine, pattern: str, codec=None):
        self._engine = engine
        self._pattern = pattern

    def add_listener(self, listener: Callable[[str, Any], None]) -> int:
        return self._engine.pubsub.psubscribe(self._pattern, listener)

    def remove_listener(self, listener_id: int) -> None:
        self._engine.pubsub.punsubscribe(self._pattern, listener_id)


class ShardedTopic(Topic):
    """RShardedTopic: identical delivery semantics in-process; the name maps
    to a keyspace slot so mesh-mode routing can pin it to a shard."""

    def slot(self) -> int:
        from redisson_tpu.utils.crc16 import calc_slot

        return calc_slot(self._name)


class ReliableTopic(RObject):
    """RReliableTopic: durable stream + per-subscriber offsets.

    Subscribers poll from their own offset; messages are retained until every
    live subscriber has consumed them (the reference trims via XTRIM after
    watchdog-checked offsets).  Subscriber liveness uses a watchdog timeout
    (reliableTopicWatchdogTimeout, config/Config.java:77 — default 600s).
    """

    _kind = "reliable_topic"
    WATCHDOG_TIMEOUT = 600.0

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(
                kind=self._kind,
                host={"messages": [], "base": 0, "subscribers": {}},  # id -> [offset, last_seen]
            ),
        )

    def publish(self, message: Any) -> int:
        data = self._codec.encode(message)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["messages"].append(data)
            self._touch_version(rec)
            n = len(rec.host["subscribers"])
        self._engine.wait_entry(f"__rtopic__:{self._name}").signal(all_=True)
        return n

    def add_subscriber(self) -> str:
        sid = uuid.uuid4().hex[:12]
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["subscribers"][sid] = [
                rec.host["base"] + len(rec.host["messages"]),
                time.time(),
            ]
            self._touch_version(rec)
        return sid

    def remove_subscriber(self, subscriber_id: str) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["subscribers"].pop(subscriber_id, None)
            self._trim(rec)
            self._touch_version(rec)

    def poll(self, subscriber_id: str, timeout: float = 0.0, max_messages: int = 100) -> List:
        """Fetch messages after this subscriber's offset; advances the offset."""
        deadline = time.time() + timeout
        while True:
            with self._engine.locked(self._name):
                rec = self._rec_or_create()
                sub = rec.host["subscribers"].get(subscriber_id)
                if sub is None:
                    raise KeyError(f"unknown subscriber {subscriber_id}")
                sub[1] = time.time()  # watchdog heartbeat
                base = rec.host["base"]
                start = sub[0] - base
                msgs = rec.host["messages"][start : start + max_messages]
                if msgs:
                    sub[0] += len(msgs)
                    self._reap_dead(rec)
                    self._trim(rec)
                    self._touch_version(rec)
                    return [self._codec.decode(m) for m in msgs]
            if time.time() >= deadline:
                return []
            self._engine.wait_entry(f"__rtopic__:{self._name}").wait_for(
                max(0.0, deadline - time.time())
            )

    def _reap_dead(self, rec) -> None:
        now = time.time()
        dead = [
            sid
            for sid, (_, seen) in rec.host["subscribers"].items()
            if now - seen > self.WATCHDOG_TIMEOUT
        ]
        for sid in dead:
            del rec.host["subscribers"][sid]

    def _trim(self, rec) -> None:
        """Drop messages consumed by every subscriber (XTRIM analog)."""
        subs = rec.host["subscribers"]
        if not subs:
            rec.host["base"] += len(rec.host["messages"])
            rec.host["messages"].clear()
            return
        min_off = min(off for off, _ in subs.values())
        drop = min_off - rec.host["base"]
        if drop > 0:
            rec.host["messages"] = rec.host["messages"][drop:]
            rec.host["base"] = min_off

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host["messages"])

    def count_subscribers(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host["subscribers"])
