"""HyperLogLogArray: a bank of HLL counters as one (T, m) register tensor.

Capability analog of running many RHyperLogLog objects (BASELINE.md config 3:
"10k counters, streaming add + pairwise mergeWith"): the reference issues
PFADD/PFMERGE per counter; here a mixed-tenant add batch is one scatter-max
kernel and a whole wave of pairwise merges is one row-gather + scatter-max —
per-counter semantics with bank-wide dispatch (SURVEY.md §7.3 item 7).
"""
from __future__ import annotations

import numpy as np

import jax.numpy as jnp

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core import kernels as K
from redisson_tpu.core.store import StateRecord
from redisson_tpu.ops import hll as hll_ops
from redisson_tpu.utils import hashing as H


class HyperLogLogArray(RExpirable):
    def try_init(self, tenants: int, p: int = hll_ops.DEFAULT_P) -> bool:
        if tenants <= 0:
            raise ValueError("tenants must be positive")
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            self._engine.store.put(
                self._name,
                StateRecord(
                    kind="hll_array",
                    meta={"tenants": tenants, "p": p, "hash": H.HASH_NAME},
                    arrays={"regs": hll_ops.make_bank(tenants, p)},
                ),
            )
            return True

    def _rec(self) -> StateRecord:
        rec = self._engine.store.get(self._name)
        if rec is None:
            raise RuntimeError(f"HyperLogLogArray '{self._name}' is not initialized")
        return rec

    def tenants(self) -> int:
        return self._rec().meta["tenants"]

    def add(self, tenant_ids, keys) -> None:
        """Mixed-tenant streaming add: one scatter-max kernel."""
        t = np.ascontiguousarray(tenant_ids, np.int32)
        if not self._engine.is_int_batch(keys):
            raise TypeError("HyperLogLogArray fast path requires integer numpy keys")
        arr = np.ascontiguousarray(keys, np.int64)
        if t.shape != arr.shape:
            raise ValueError("tenant_ids and keys must be aligned 1-D arrays")
        n = arr.shape[0]
        if n == 0:
            return
        b = K.bucket_size(n)
        lo, hi = H.int_keys_to_u32_pair(arr)
        tlh = K.pack_rows(t, lo, hi, size=b)  # one contiguous transfer buffer
        with self._engine.locked(self._name):
            rec = self._rec()
            rec.arrays["regs"] = K.hll_bank_add_packed(rec.arrays["regs"], tlh, K.valid_n(n), rec.meta["p"])
            self._touch_version(rec)

    def merge_rows(self, dst_ids, src_ids) -> None:
        """Batched pairwise PFMERGE: counter[dst] |= counter[src] per pair.

        Each round ships ONE (P,) source map and dispatches ONE dense
        gather+max over the bank (kernels.hll_bank_merge_map) — the
        scatter-free shape that lifted config3 off the serialized
        row-scatter path.  Pairs sharing a dst split into successive
        unique-dst rounds; rounds past the first gather from a PRE-CALL
        snapshot of the bank (hll_bank_merge_map_from), so every source
        folds in with read-all-sources-from-old scatter-max semantics —
        a dst updated in round 1 cannot leak its new registers through a
        later round."""
        import jax.numpy as jnp

        dst = np.ascontiguousarray(dst_ids, np.int32)
        src = np.ascontiguousarray(src_ids, np.int32)
        if dst.shape != src.shape:
            raise ValueError("dst_ids and src_ids must be aligned")
        if dst.shape[0] == 0:
            return
        with self._engine.locked(self._name):
            rec = self._rec()
            P = rec.arrays["regs"].shape[0]
            if dst.size and (int(dst.min()) < 0 or int(dst.max()) >= P
                             or int(src.min()) < 0 or int(src.max()) >= P):
                raise ValueError(f"counter id out of range [0, {P})")
            multi_round = len(np.unique(dst)) != dst.shape[0]
            # duplicate dsts: later rounds must read sources from the
            # pre-call bank, which the first round's donation destroys
            orig = jnp.copy(rec.arrays["regs"]) if multi_round else None
            first_round = True
            pairs_d, pairs_s = dst, src
            while pairs_d.size:
                _vals, first = np.unique(pairs_d, return_index=True)
                take = np.zeros(pairs_d.shape[0], bool)
                take[first] = True
                src_map = np.arange(P, dtype=np.int32)
                src_map[pairs_d[take]] = pairs_s[take]
                if first_round:
                    rec.arrays["regs"] = K.hll_bank_merge_map(
                        rec.arrays["regs"], K.stage(src_map)
                    )
                    first_round = False
                else:
                    rec.arrays["regs"] = K.hll_bank_merge_map_from(
                        rec.arrays["regs"], orig, K.stage(src_map)
                    )
                pairs_d, pairs_s = pairs_d[~take], pairs_s[~take]
            self._touch_version(rec)

    def estimate_all(self) -> np.ndarray:
        """Per-tenant cardinality estimates (one fused reduce over the bank)."""
        return np.asarray(self.estimate_all_async())

    def estimate_all_async(self):
        """Pipelined estimate: the (T,) float64 result stays on DEVICE — the
        server's reply path rides it as a readback future (overlap plane),
        so an estimate sweep never blocks the frame that asked for it."""
        with self._engine.locked(self._name):
            rec = self._rec()
            return K.hll_estimate(rec.arrays["regs"])

    def estimate_union_pairs(self, a_ids, b_ids) -> np.ndarray:
        """PFCOUNT of union per (a, b) pair without mutating either row."""
        return np.asarray(self.estimate_union_pairs_async(a_ids, b_ids))

    def estimate_union_pairs_async(self, a_ids, b_ids):
        """Pipelined pairwise union estimate (device result, no host sync)."""
        a = np.ascontiguousarray(a_ids, np.int32)
        b = np.ascontiguousarray(b_ids, np.int32)
        with self._engine.locked(self._name):
            rec = self._rec()
            return K.hll_bank_estimate_union_pairs(rec.arrays["regs"], a, b)
