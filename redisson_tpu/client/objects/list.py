"""List: ordered collection with index access.

Parity target: RList — ``org/redisson/BaseRedissonList.java`` (897 LoC) +
``RedissonList.java``: LPUSH/RPUSH/LRANGE/LINDEX/LSET/LINSERT/LREM semantics,
subList, indexOf, trim, fastSet, range reads.
"""
from __future__ import annotations

from typing import Any, Iterable, Iterator, List as PyList, Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord


class RList(RExpirable):
    _kind = "list"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host=[])
        )

    def _e(self, v) -> bytes:
        return self._codec.encode(v)

    def _d(self, raw: bytes):
        return self._codec.decode(raw)

    def add(self, value) -> bool:
        """RPUSH one element."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.append(self._e(value))
            self._touch_version(rec)
            return True

    def add_all(self, values: Iterable) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            added = False
            for v in values:
                rec.host.append(self._e(v))
                added = True
            if added:
                self._touch_version(rec)
            return added

    def add_first(self, value) -> None:
        """LPUSH."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.insert(0, self._e(value))
            self._touch_version(rec)

    def add_at(self, index: int, value) -> None:
        """LINSERT-by-index (reference add(index, element))."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if index < 0 or index > len(rec.host):
                raise IndexError(index)
            rec.host.insert(index, self._e(value))
            self._touch_version(rec)

    def _add_relative(self, pivot, value, after: bool) -> int:
        """LINSERT BEFORE|AFTER pivot; new length, or -1 if pivot absent."""
        ep, ev = self._e(pivot), self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            try:
                i = rec.host.index(ep)
            except ValueError:
                return -1
            rec.host.insert(i + 1 if after else i, ev)
            self._touch_version(rec)
            return len(rec.host)

    def add_after(self, pivot, value) -> int:
        """RList.addAfter (LINSERT AFTER)."""
        return self._add_relative(pivot, value, after=True)

    def add_before(self, pivot, value) -> int:
        """RList.addBefore (LINSERT BEFORE)."""
        return self._add_relative(pivot, value, after=False)

    def sub_list(self, from_index: int, to_index: int) -> PyList:
        """RList.subList materialized (reference returns a live view; a
        snapshot honors the same read semantics without proxy plumbing)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if from_index < 0 or to_index > len(rec.host) or from_index > to_index:
                raise IndexError(f"subList({from_index}, {to_index}) out of bounds")
            return [self._d(e) for e in rec.host[from_index:to_index]]

    def get(self, index: int):
        """LINDEX; raises IndexError out of range (reference throws)."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            raise IndexError(index)
        return self._d(rec.host[index])

    def set(self, index: int, value):
        """LSET; returns previous element."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = rec.host[index]
            rec.host[index] = self._e(value)
            self._touch_version(rec)
            return self._d(old)

    def fast_set(self, index: int, value) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host[index] = self._e(value)
            self._touch_version(rec)

    def remove(self, value) -> bool:
        """LREM count=1."""
        e = self._e(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            try:
                rec.host.remove(e)
            except ValueError:
                return False
            self._touch_version(rec)
            return True

    def remove_at(self, index: int):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = rec.host.pop(index)
            self._touch_version(rec)
            return self._d(old)

    def remove_count(self, value, count: int) -> bool:
        """LREM with count (sign ignored — removes first |count| occurrences)."""
        e = self._e(value)
        removed = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            while removed < abs(count):
                try:
                    rec.host.remove(e)
                    removed += 1
                except ValueError:
                    break
            if removed:
                self._touch_version(rec)
        return removed > 0

    def index_of(self, value) -> int:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return -1
        try:
            return rec.host.index(self._e(value))
        except ValueError:
            return -1

    def last_index_of(self, value) -> int:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return -1
        e = self._e(value)
        for i in range(len(rec.host) - 1, -1, -1):
            if rec.host[i] == e:
                return i
        return -1

    def contains(self, value) -> bool:
        return self.index_of(value) >= 0

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host)

    def is_empty(self) -> bool:
        return self.size() == 0

    def read_all(self) -> PyList:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(e) for e in list(rec.host)]

    def range(self, from_index: int, to_index: int) -> PyList:
        """LRANGE (inclusive bounds, like the reference readAll(from, to))."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return []
        return [self._d(e) for e in rec.host[from_index : to_index + 1]]

    def trim(self, from_index: int, to_index: int) -> None:
        """LTRIM (inclusive)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host[:] = rec.host[from_index : to_index + 1]
            self._touch_version(rec)

    def clear(self) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host.clear()
            self._touch_version(rec)

    def __len__(self):
        return self.size()

    def __iter__(self) -> Iterator:
        return iter(self.read_all())

    def __getitem__(self, index):
        return self.get(index)

    def __setitem__(self, index, value):
        self.fast_set(index, value)
