"""ScoredSortedSet: the ZSET object.

Parity target: ``org/redisson/RedissonScoredSortedSet.java`` (2,084 LoC) —
ZADD (+NX/XX/GT/LT), ZSCORE/ZINCRBY, ZRANK/ZREVRANK, ZRANGE/ZRANGEBYSCORE
(+REV, +WITHSCORES), ZPOPMIN/MAX, ZCOUNT, ZREM/ZREMRANGEBY*, ZRANDMEMBER,
ZUNIONSTORE/ZINTERSTORE/ZDIFFSTORE, firstScore/lastScore.

Representation: member(encoded) -> score dict plus a lazily rebuilt sorted
index (score, encoded-member) — rebuild is O(n log n) amortized over reads
after writes; ranks follow Redis tie-break rules (score, then lexicographic
member).  Bulk analytics (rank of a large batch, percentile scans) are the
device upgrade path via argsort kernels; the host index is the semantic
reference implementation.
"""
from __future__ import annotations

import bisect
import math
import random
from typing import Any, Dict, Iterable, List, Optional, Tuple

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord

_INF = math.inf


class ScoredSortedSet(RExpirable):
    _kind = "zset"

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name,
            self._kind,
            lambda: StateRecord(kind=self._kind, host={"scores": {}, "index": None}),
        )

    def _e(self, v) -> bytes:
        return self._codec.encode(v)

    def _d(self, raw: bytes):
        return self._codec.decode(raw)

    @staticmethod
    def _index_of(rec) -> List[Tuple[float, bytes]]:
        if rec.host["index"] is None:
            rec.host["index"] = sorted(
                ((s, m) for m, s in rec.host["scores"].items()), key=lambda p: (p[0], p[1])
            )
        return rec.host["index"]

    @staticmethod
    def _dirty(rec):
        rec.host["index"] = None

    # -- writes -------------------------------------------------------------

    def add(self, score: float, member) -> bool:
        """ZADD one member; True if newly added (not merely updated)."""
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            fresh = e not in rec.host["scores"]
            rec.host["scores"][e] = float(score)
            self._dirty(rec)
            self._touch_version(rec)
        self._signal_waiters()
        return fresh

    def _signal_waiters(self) -> None:
        """Wake parked take_first/take_last (BZPOPMIN/MAX analog)."""
        self._engine.signal_queue_waiters(self._name)

    def add_all(self, entries: Dict[Any, float]) -> int:
        """ZADD many: {member: score}; returns count of new members."""
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for member, score in entries.items():
                e = self._e(member)
                if e not in rec.host["scores"]:
                    n += 1
                rec.host["scores"][e] = float(score)
            self._dirty(rec)
            self._touch_version(rec)
        self._signal_waiters()
        return n

    def add_all_if_absent(self, entries: Dict[Any, float]) -> int:
        """ZADD NX many (RScoredSortedSet.addAllIfAbsent): count ADDED."""
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for member, score in entries.items():
                e = self._e(member)
                if e not in rec.host["scores"]:
                    rec.host["scores"][e] = float(score)
                    n += 1
            if n:
                self._dirty(rec)
                self._touch_version(rec)
        if n:
            self._signal_waiters()
        return n

    def add_all_if_exist(self, entries: Dict[Any, float]) -> int:
        """ZADD XX CH many: count of existing members whose score CHANGED."""
        n = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for member, score in entries.items():
                e = self._e(member)
                old = rec.host["scores"].get(e)
                if old is not None and old != float(score):
                    rec.host["scores"][e] = float(score)
                    n += 1
            if n:
                self._dirty(rec)
                self._touch_version(rec)
        return n

    def _add_all_cmp(self, entries: Dict[Any, float], pred) -> int:
        n = 0
        fresh = 0
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for member, score in entries.items():
                e = self._e(member)
                old = rec.host["scores"].get(e)
                if old is None or pred(float(score), old):
                    rec.host["scores"][e] = float(score)
                    n += 1
                    fresh += old is None
            if n:
                self._dirty(rec)
                self._touch_version(rec)
        if fresh:
            self._signal_waiters()
        return n

    def add_all_if_greater(self, entries: Dict[Any, float]) -> int:
        """ZADD GT CH many: count added-or-raised."""
        return self._add_all_cmp(entries, lambda new, old: new > old)

    def add_all_if_less(self, entries: Dict[Any, float]) -> int:
        """ZADD LT CH many."""
        return self._add_all_cmp(entries, lambda new, old: new < old)

    def add_score_and_get_rank(self, member, delta: float) -> Optional[int]:
        """ZINCRBY + ZRANK atomically (addScoreAndGetRank)."""
        with self._engine.locked(self._name):
            self.add_score(member, delta)
            return self.rank(member)

    def add_score_and_get_rev_rank(self, member, delta: float) -> Optional[int]:
        with self._engine.locked(self._name):
            self.add_score(member, delta)
            return self.rev_rank(member)

    def first_entry(self) -> Optional[Tuple[Any, float]]:
        """(member, score) of the lowest-scored member (firstEntry)."""
        entries = self.entry_range(0, 0)
        return entries[0] if entries else None

    def last_entry(self) -> Optional[Tuple[Any, float]]:
        entries = self.entry_range(-1, -1)
        return entries[0] if entries else None

    def rank_entry(self, member) -> Optional[Tuple[int, float]]:
        """(rank, score) in one locked read (rankEntry)."""
        with self._engine.locked(self._name):
            r = self.rank(member)
            return None if r is None else (r, self.get_score(member))

    def rev_rank_entry(self, member) -> Optional[Tuple[int, float]]:
        with self._engine.locked(self._name):
            r = self.rev_rank(member)
            return None if r is None else (r, self.get_score(member))

    def add_if_absent(self, score: float, member) -> bool:
        """ZADD NX."""
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if e in rec.host["scores"]:
                return False
            rec.host["scores"][e] = float(score)
            self._dirty(rec)
            self._touch_version(rec)
        self._signal_waiters()
        return True

    def add_if_exists(self, score: float, member) -> bool:
        """ZADD XX CH (RedissonScoredSortedSet.addIfExistsAsync): True only
        when an existing member's score actually CHANGED."""
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = rec.host["scores"].get(e)
            if old is None or old == float(score):
                return False
            rec.host["scores"][e] = float(score)
            self._dirty(rec)
            self._touch_version(rec)
            return True

    def add_if_greater(self, score: float, member) -> bool:
        """ZADD GT (update only if new score is greater)."""
        return self._add_cmp(score, member, lambda new, old: new > old)

    def add_if_less(self, score: float, member) -> bool:
        """ZADD LT."""
        return self._add_cmp(score, member, lambda new, old: new < old)

    def _add_cmp(self, score, member, pred) -> bool:
        """ZADD GT|LT CH (addIfGreater/LessAsync): True when the member was
        ADDED or its score CHANGED — not merely touched with an equal score."""
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = rec.host["scores"].get(e)
            if old is not None and not pred(float(score), old):
                return False
            rec.host["scores"][e] = float(score)
            self._dirty(rec)
            self._touch_version(rec)
            fresh = old is None
        if fresh:  # a GT/LT add can introduce a member: wake parked takers
            self._signal_waiters()
        return fresh or old != float(score)

    def add_score(self, member, delta: float) -> float:
        """ZINCRBY."""
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            new = rec.host["scores"].get(e, 0.0) + float(delta)
            rec.host["scores"][e] = new
            self._dirty(rec)
            self._touch_version(rec)
        self._signal_waiters()
        return new

    def remove(self, member) -> bool:
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.host["scores"].pop(e, None) is None:
                return False
            self._dirty(rec)
            self._touch_version(rec)
            return True

    def remove_all(self, members: Iterable) -> bool:
        changed = False
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            for m in members:
                if rec.host["scores"].pop(self._e(m), None) is not None:
                    changed = True
            if changed:
                self._dirty(rec)
                self._touch_version(rec)
        return changed

    def remove_range_by_rank(self, start: int, end: int) -> int:
        """ZREMRANGEBYRANK (inclusive, negative indexes allowed)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            n = len(idx)
            s, e = _norm_range(start, end, n)
            victims = [m for _, m in idx[s : e + 1]]
            for m in victims:
                del rec.host["scores"][m]
            if victims:
                self._dirty(rec)
                self._touch_version(rec)
            return len(victims)

    def remove_range_by_score(
        self, lo: float, lo_inc: bool, hi: float, hi_inc: bool
    ) -> int:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            victims = [
                m
                for m, s in rec.host["scores"].items()
                if _in_score(s, lo, lo_inc, hi, hi_inc)
            ]
            for m in victims:
                del rec.host["scores"][m]
            if victims:
                self._dirty(rec)
                self._touch_version(rec)
            return len(victims)

    # -- reads --------------------------------------------------------------

    def get_score(self, member) -> Optional[float]:
        rec = self._engine.store.get(self._name)
        if rec is None:
            return None
        return rec.host["scores"].get(self._e(member))

    def contains(self, member) -> bool:
        return self.get_score(member) is not None

    def size(self) -> int:
        rec = self._engine.store.get(self._name)
        return 0 if rec is None else len(rec.host["scores"])

    def rank(self, member) -> Optional[int]:
        """ZRANK (0-based, ascending)."""
        e = self._e(member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            score = rec.host["scores"].get(e)
            if score is None:
                return None
            idx = self._index_of(rec)
            i = bisect.bisect_left(idx, (score, e))
            return i

    def rev_rank(self, member) -> Optional[int]:
        r = self.rank(member)
        return None if r is None else self.size() - 1 - r

    def value_range(self, start: int, end: int, reverse: bool = False) -> List:
        """ZRANGE / ZREVRANGE by rank, inclusive."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            n = len(idx)
            s, e = _norm_range(start, end, n)
            picked = idx[s : e + 1]
        if reverse:
            picked = list(reversed(self._rev_slice(idx, start, end)))
            return [self._d(m) for _, m in picked]
        return [self._d(m) for _, m in picked]

    @staticmethod
    def _rev_slice(idx, start, end):
        n = len(idx)
        rev = list(reversed(idx))
        s, e = _norm_range(start, end, n)
        return list(reversed(rev[s : e + 1]))

    def entry_range(self, start: int, end: int) -> List[Tuple[Any, float]]:
        """ZRANGE WITHSCORES -> [(member, score)]."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            s, e = _norm_range(start, end, len(idx))
            return [(self._d(m), sc) for sc, m in idx[s : e + 1]]

    def value_range_by_score(
        self,
        lo: float = -_INF,
        lo_inc: bool = True,
        hi: float = _INF,
        hi_inc: bool = True,
        offset: int = 0,
        count: Optional[int] = None,
    ) -> List:
        """ZRANGEBYSCORE with LIMIT offset count."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            picked = [m for sc, m in idx if _in_score(sc, lo, lo_inc, hi, hi_inc)]
        picked = picked[offset : offset + count if count is not None else None]
        return [self._d(m) for m in picked]

    def count(self, lo: float, lo_inc: bool, hi: float, hi_inc: bool) -> int:
        """ZCOUNT."""
        rec = self._engine.store.get(self._name)
        if rec is None:
            return 0
        return sum(1 for s in rec.host["scores"].values() if _in_score(s, lo, lo_inc, hi, hi_inc))

    def first(self):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            return self._d(idx[0][1]) if idx else None

    def last(self):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            return self._d(idx[-1][1]) if idx else None

    def first_score(self) -> Optional[float]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            return idx[0][0] if idx else None

    def last_score(self) -> Optional[float]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            return idx[-1][0] if idx else None

    def poll_first(self):
        """ZPOPMIN."""
        e = self.poll_first_entry()
        return None if e is None else e[0]

    def poll_first_entry(self):
        """ZPOPMIN with score: (member, score) or None."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            if not idx:
                return None
            sc, m = idx[0]
            del rec.host["scores"][m]
            self._dirty(rec)
            self._touch_version(rec)
            return self._d(m), sc

    def poll_last(self):
        """ZPOPMAX."""
        e = self.poll_last_entry()
        return None if e is None else e[0]

    def poll_last_entry(self):
        """ZPOPMAX with score: (member, score) or None."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            if not idx:
                return None
            sc, m = idx[-1]
            del rec.host["scores"][m]
            self._dirty(rec)
            self._touch_version(rec)
            return self._d(m), sc

    def random_member(self):
        rec = self._engine.store.get(self._name)
        if rec is None or not rec.host["scores"]:
            return None
        return self._d(random.choice(list(rec.host["scores"].keys())))

    def read_all(self) -> List:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            return [self._d(m) for _, m in self._index_of(rec)]

    # -- RSortable (readSort/sortTo — the Redis SORT surface) ----------------

    def _bucket_value(self, pattern: str, member_str: str):
        from redisson_tpu.client.objects.bucket import Bucket

        if pattern == "#":
            return member_str
        return Bucket(
            self._engine, pattern.replace("*", member_str, 1), self._codec
        ).get()

    def _sorted_members(self, order: str, by_pattern: Optional[str], alpha: bool):
        members = self.read_all()
        if by_pattern is not None:
            def key(m):
                v = self._bucket_value(by_pattern, str(m))
                return str(v) if alpha else float(v if v is not None else 0)
        else:
            key = (lambda m: str(m)) if alpha else (lambda m: float(m))
        return sorted(members, key=key, reverse=(order.upper() == "DESC"))

    def read_sort(
        self,
        order: str = "ASC",
        offset: Optional[int] = None,
        count: Optional[int] = None,
        by_pattern: Optional[str] = None,
        get_patterns: Optional[List[str]] = None,
        alpha: bool = False,
    ) -> List:
        """RSortable.readSort (Redis SORT): sort members by themselves or a
        BY bucket pattern; optional GET projection; LIMIT offset/count."""
        out = self._sorted_members(order, by_pattern, alpha)
        if offset is not None or count is not None:
            off = offset or 0
            out = out[off : off + count] if count is not None else out[off:]
        if get_patterns:
            proj = []
            for m in out:
                for g in get_patterns:
                    proj.append(self._bucket_value(g, str(m)))
            return proj
        return out

    def read_sort_alpha(self, order: str = "ASC", offset=None, count=None,
                        by_pattern=None, get_patterns=None) -> List:
        return self.read_sort(order, offset, count, by_pattern, get_patterns,
                              alpha=True)

    def sort_to(
        self,
        dest_name: str,
        order: str = "ASC",
        offset: Optional[int] = None,
        count: Optional[int] = None,
        by_pattern: Optional[str] = None,
        get_patterns: Optional[List[str]] = None,
        alpha: bool = False,
    ) -> int:
        """SORT ... STORE dest: result lands as a LIST (Redis stores sort
        output as a list regardless of source type)."""
        from redisson_tpu.client.objects.queue import Deque

        out = self.read_sort(order, offset, count, by_pattern, get_patterns, alpha)
        dest = Deque(self._engine, dest_name, self._codec)
        with self._engine.locked(dest._name):
            self._engine.store.delete(dest._name)
            for v in out:
                dest.add_last(v)
        return len(out)

    def __len__(self):
        return self.size()

    def __iter__(self):
        return iter(self.read_all())

    # -- store algebra (ZUNIONSTORE / ZINTERSTORE / ZDIFFSTORE) --------------

    def _gather(self, names):
        out = []
        for nm in names:
            rec = self._engine.store.get(nm)
            out.append({} if rec is None else dict(rec.host["scores"]))
        return out

    @staticmethod
    def _accumulate(maps, op: str, aggregate: str = "SUM") -> Dict[bytes, float]:
        """ONE accumulator for union/inter/diff — shared by the store ops
        AND the read_* variants so aggregation semantics cannot drift."""
        if op == "union":
            acc: Dict[bytes, float] = {}
            for mp in maps:
                for m, s in mp.items():
                    acc[m] = _agg(aggregate, acc[m], s) if m in acc else s
            return acc
        if op == "inter":
            common = set(maps[0]) if maps else set()
            for mp in maps[1:]:
                common &= set(mp)
            acc = {}
            for m in common:
                v = maps[0][m]
                for mp in maps[1:]:
                    v = _agg(aggregate, v, mp[m])
                acc[m] = v
            return acc
        acc = dict(maps[0]) if maps else {}
        for mp in maps[1:]:
            for m in mp:
                acc.pop(m, None)
        return acc

    def _combine_store(self, names, op: str, aggregate: str = "SUM") -> int:
        names = [self._map_name(n) for n in names]
        with self._engine.locked_many((self._name, *names)):
            rec = self._rec_or_create()
            acc = self._accumulate(self._gather((self._name, *names)), op, aggregate)
            rec.host["scores"] = acc
            self._dirty(rec)
            self._touch_version(rec)
        self._signal_waiters()
        return len(acc)

    def union(self, *names: str, aggregate: str = "SUM") -> int:
        return self._combine_store(names, "union", aggregate)

    def intersection(self, *names: str, aggregate: str = "SUM") -> int:
        return self._combine_store(names, "inter", aggregate)

    def diff(self, *names: str) -> int:
        return self._combine_store(names, "diff")

    # -- combination reads (readUnion/readIntersection/readDiff) -------------

    def _combine_read(self, names, op: str, aggregate: str = "SUM") -> List:
        names = [self._map_name(n) for n in names]
        with self._engine.locked_many((self._name, *names)):
            maps = self._gather((self._name, *names))
        acc = self._accumulate(maps, op, aggregate)
        return [self._d(m) for _s, m in sorted((s, m) for m, s in acc.items())]

    def read_union(self, *names: str, aggregate: str = "SUM") -> List:
        """ZUNION read — leaves this set untouched (RScoredSortedSet.readUnion)."""
        return self._combine_read(names, "union", aggregate)

    def read_intersection(self, *names: str, aggregate: str = "SUM") -> List:
        return self._combine_read(names, "inter", aggregate)

    def read_diff(self, *names: str) -> List:
        return self._combine_read(names, "diff")

    def count_intersection(self, *names: str, limit: int = 0) -> int:
        """ZINTERCARD (RScoredSortedSet.countIntersection) — counts the
        accumulator directly; decoding/sorting members to len() them would
        pay the full read cost for a number."""
        names = tuple(self._map_name(n) for n in names)
        with self._engine.locked_many((self._name, *names)):
            n = len(self._accumulate(self._gather((self._name, *names)), "inter"))
        return min(n, limit) if limit else n

    # -- rank-returning adds / member surgery --------------------------------

    def add_and_get_rank(self, score: float, member) -> int:
        """ZADD + ZRANK in one locked step (addAndGetRank)."""
        with self._engine.locked(self._name):
            self.add(score, member)
            return self.rank(member)

    def add_and_get_rev_rank(self, score: float, member) -> int:
        with self._engine.locked(self._name):
            self.add(score, member)
            return self.rev_rank(member)

    def replace(self, old_member, new_member) -> bool:
        """Rename a member keeping its score (RScoredSortedSet.replace)."""
        eo, en = self._e(old_member), self._e(new_member)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            score = rec.host["scores"].pop(eo, None)
            if score is None:
                return False
            rec.host["scores"][en] = score
            self._dirty(rec)
            self._touch_version(rec)
        self._signal_waiters()
        return True

    def retain_all(self, values: Iterable) -> bool:
        """Keep only `values`; True if anything was removed."""
        keep = {self._e(v) for v in values}
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            victims = [m for m in rec.host["scores"] if m not in keep]
            for m in victims:
                del rec.host["scores"][m]
            if victims:
                self._dirty(rec)
                self._touch_version(rec)
            return bool(victims)

    def random_entries(self, count: int) -> Dict:
        """ZRANDMEMBER WITHSCORES as a dict (randomEntries)."""
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            items = list(rec.host["scores"].items())
        picked = random.sample(items, min(count, len(items)))
        return {self._d(m): s for m, s in picked}

    # -- reversed ranges ------------------------------------------------------

    def value_range_reversed(self, start: int, end: int) -> List:
        """ZREVRANGE by rank (valueRangeReversed)."""
        return [m for m, _s in self.entry_range_reversed(start, end)]

    def entry_range_reversed(self, start: int, end: int) -> List[Tuple[Any, float]]:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = list(reversed(self._index_of(rec)))
        lo, hi = _norm_range(start, end, len(idx))
        return [(self._d(m), s) for s, m in (idx[lo : hi + 1] if hi >= lo else [])]

    # -- counted + blocking pops ---------------------------------------------

    def _poll_many(self, count: int, first: bool) -> List:
        """ONE index build + one slice + one batched delete — popping
        through poll_*_entry would re-sort the whole set per element."""
        if count <= 0:
            return []
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            idx = self._index_of(rec)
            victims = idx[:count] if first else idx[: -count - 1 : -1]
            if not victims:
                return []
            for _s, m in victims:
                del rec.host["scores"][m]
            self._dirty(rec)
            self._touch_version(rec)
            return [self._d(m) for _s, m in victims]

    def poll_first_many(self, count: int) -> List:
        """ZPOPMIN with count (pollFirst(count))."""
        return self._poll_many(count, first=True)

    def poll_last_many(self, count: int) -> List:
        return self._poll_many(count, first=False)

    def _poll_blocking(self, poll_fn, timeout: Optional[float]):
        import time as _t

        deadline = None if timeout is None else _t.time() + timeout
        entry = self._engine.queue_wait_entry(self._name)
        while True:
            v = poll_fn()
            if v is not None:
                return v
            remaining = None if deadline is None else deadline - _t.time()
            if remaining is not None and remaining <= 0:
                return None
            entry.wait_for(min(1.0, remaining) if remaining is not None else 1.0)

    def take_first(self):
        """BZPOPMIN parked on add wakeups (takeFirst)."""
        return self._poll_blocking(self.poll_first, None)

    def take_last(self):
        return self._poll_blocking(self.poll_last, None)

    def poll_first_blocking(self, timeout: Optional[float]):
        return self._poll_blocking(self.poll_first, timeout)

    def poll_last_blocking(self, timeout: Optional[float]):
        return self._poll_blocking(self.poll_last, timeout)


def _agg(mode: str, a: float, b: float) -> float:
    if mode == "SUM":
        return a + b
    if mode == "MIN":
        return min(a, b)
    if mode == "MAX":
        return max(a, b)
    raise ValueError(f"unknown aggregate {mode!r}")


def _in_score(s: float, lo: float, lo_inc: bool, hi: float, hi_inc: bool) -> bool:
    lo_ok = s > lo or (lo_inc and s == lo)
    hi_ok = s < hi or (hi_inc and s == hi)
    return lo_ok and hi_ok


def _norm_range(start: int, end: int, n: int) -> Tuple[int, int]:
    if start < 0:
        start = max(0, n + start)
    if end < 0:
        end = n + end
    return start, min(end, n - 1)
