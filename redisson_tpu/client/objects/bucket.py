"""Bucket family: single-value holders and atomic counters.

Parity targets:
  * RBucket — ``org/redisson/RedissonBucket.java`` (394 LoC): get/set,
    getAndSet, trySet (SETNX), compareAndSet (CAS Lua), setIfExists,
    getAndDelete, TTL variants.
  * RBuckets — ``RedissonBuckets.java``: MGET/MSET/MSETNX cross-key grouping.
  * RAtomicLong / RAtomicDouble — ``RedissonAtomicLong.java`` (INCR family).
  * RIdGenerator — ``RedissonIdGenerator.java`` (allocation-block counter).

These are control-plane objects: scalar values with compare-and-mutate
semantics.  The reference makes them atomic with server-side Lua; here every
compound op runs under the object's record lock (the per-shard sequencer
discipline, SURVEY.md §7.1 item 5) — same atomicity, no device round-trip.
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterable, List, Optional

from redisson_tpu.client.objects.base import RExpirable
from redisson_tpu.core.store import StateRecord

_SENTINEL = object()


class Bucket(RExpirable):
    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, "bucket", lambda: StateRecord(kind="bucket", host={"v": _SENTINEL})
        )

    def get(self) -> Any:
        rec = self._engine.store.get(self._name)
        if rec is None or rec.host["v"] is _SENTINEL:
            return None
        return self._codec.decode(rec.host["v"])

    def set(self, value: Any, ttl: Optional[float] = None) -> None:
        data = self._codec.encode(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["v"] = data
            rec.expire_at = time.time() + ttl if ttl is not None else None
            self._touch_version(rec)

    def get_and_set(self, value: Any) -> Any:
        with self._engine.locked(self._name):
            old = self.get()
            self.set(value)
            return old

    def try_set(self, value: Any, ttl: Optional[float] = None) -> bool:
        """SETNX semantics (RedissonBucket trySet)."""
        with self._engine.locked(self._name):
            if self.get() is not None:
                return False
            self.set(value, ttl)
            return True

    def set_if_exists(self, value: Any) -> bool:
        with self._engine.locked(self._name):
            if self.get() is None:
                return False
            self.set(value)
            return True

    def compare_and_set(self, expect: Any, update: Any) -> bool:
        """CAS via encoded-value equality (RedissonBucket compareAndSet Lua)."""
        with self._engine.locked(self._name):
            cur = self.get()
            if cur != expect:
                return False
            self.set(update)
            return True

    def set_if_absent(self, value: Any, ttl: Optional[float] = None) -> bool:
        """RBucket.setIfAbsent — the modern name for trySet."""
        return self.try_set(value, ttl)

    def set_and_keep_ttl(self, value: Any) -> None:
        """RBucket.setAndKeepTTL (SET ... KEEPTTL): replace the value
        without disturbing the record's expiry."""
        data = self._codec.encode(value)
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["v"] = data  # expire_at untouched
            self._touch_version(rec)

    def get_and_expire(self, ttl: float) -> Any:
        """RBucket.getAndExpire (GETEX EX): read + set expiry atomically."""
        with self._engine.locked(self._name):
            old = self.get()
            if old is not None:
                self._engine.store.expire(self._name, time.time() + ttl)
            return old

    def get_and_clear_expire(self) -> Any:
        """RBucket.getAndClearExpire (GETEX PERSIST)."""
        with self._engine.locked(self._name):
            old = self.get()
            if old is not None:
                self._engine.store.expire(self._name, None)
            return old

    def get_and_delete(self) -> Any:
        with self._engine.locked(self._name):
            old = self.get()
            self._engine.store.delete(self._name)
            return old

    def size(self) -> int:
        """Encoded payload size in bytes (STRLEN analog)."""
        rec = self._engine.store.get(self._name)
        if rec is None or rec.host["v"] is _SENTINEL:
            return 0
        return len(rec.host["v"])


class Buckets:
    """Multi-key get/set (RedissonBuckets.java — MGET/MSET with per-slot
    grouping; grouping is moot in-process but the API surface is kept)."""

    def __init__(self, engine, codec=None):
        self._engine = engine
        self._codec = codec or engine.default_codec

    def get(self, *names: str) -> Dict[str, Any]:
        out: Dict[str, Any] = {}
        for nm in names:
            v = Bucket(self._engine, nm, self._codec).get()
            if v is not None:
                out[nm] = v
        return out

    def set(self, values: Dict[str, Any]) -> None:
        for nm, v in values.items():
            Bucket(self._engine, nm, self._codec).set(v)

    def try_set(self, values: Dict[str, Any]) -> bool:
        """MSETNX: all-or-nothing if any key exists."""
        # handles map names (NameMapper); the lock must cover the MAPPED keys
        handles = {nm: Bucket(self._engine, nm, self._codec) for nm in sorted(values)}
        with self._engine.locked_many([h._name for h in handles.values()]):
            for h in handles.values():
                if h.get() is not None:
                    return False
            for nm, h in handles.items():
                h.set(values[nm])
            return True


class AtomicLong(RExpirable):
    _kind = "atomic_long"
    _zero = 0

    def _coerce(self, v):
        return int(v)

    def _rec_or_create(self) -> StateRecord:
        return self._engine.store.get_or_create(
            self._name, self._kind, lambda: StateRecord(kind=self._kind, host={"v": self._zero})
        )

    def get(self):
        rec = self._engine.store.get(self._name)
        return self._zero if rec is None else rec.host["v"]

    def set(self, value) -> None:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["v"] = self._coerce(value)
            self._touch_version(rec)

    def add_and_get(self, delta):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            rec.host["v"] = rec.host["v"] + self._coerce(delta)
            self._touch_version(rec)
            return rec.host["v"]

    def get_and_add(self, delta):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = rec.host["v"]
            rec.host["v"] = old + self._coerce(delta)
            self._touch_version(rec)
            return old

    def increment_and_get(self):
        return self.add_and_get(1)

    def decrement_and_get(self):
        return self.add_and_get(-1)

    def get_and_increment(self):
        return self.get_and_add(1)

    def get_and_decrement(self):
        return self.get_and_add(-1)

    def compare_and_set(self, expect, update) -> bool:
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            if rec.host["v"] != expect:
                return False
            rec.host["v"] = self._coerce(update)
            self._touch_version(rec)
            return True

    def get_and_set(self, value):
        with self._engine.locked(self._name):
            rec = self._rec_or_create()
            old = rec.host["v"]
            rec.host["v"] = self._coerce(value)
            self._touch_version(rec)
            return old

    def get_and_delete(self):
        """RAtomicLong.getAndDelete: read the counter and drop the record
        atomically (a later read restarts from zero)."""
        with self._engine.locked(self._name):
            rec = self._engine.store.get(self._name)
            old = self._zero if rec is None else rec.host["v"]
            self._engine.store.delete(self._name)
            return old


class AtomicDouble(AtomicLong):
    """RAtomicDouble (INCRBYFLOAT family)."""

    _kind = "atomic_double"
    _zero = 0.0

    def _coerce(self, v):
        return float(v)


class IdGenerator(RExpirable):
    """RIdGenerator (``org/redisson/RedissonIdGenerator.java``): ids handed
    out from a locally cached allocation block refilled from a shared counter."""

    _kind = "id_generator"

    def __init__(self, engine, name, codec=None):
        super().__init__(engine, name, codec)
        self._local_next = 0
        self._local_limit = 0

    def try_init(self, start: int = 0, allocation_size: int = 5000) -> bool:
        with self._engine.locked(self._name):
            if self._engine.store.exists(self._name):
                return False
            self._engine.store.put(
                self._name,
                StateRecord(kind=self._kind, host={"next": start, "block": allocation_size}),
            )
            return True

    def next_id(self) -> int:
        if self._local_next < self._local_limit:
            v = self._local_next
            self._local_next += 1
            return v
        with self._engine.locked(self._name):
            rec = self._engine.store.get_or_create(
                self._name,
                self._kind,
                lambda: StateRecord(kind=self._kind, host={"next": 0, "block": 5000}),
            )
            start = rec.host["next"]
            rec.host["next"] = start + rec.host["block"]
            self._touch_version(rec)
            self._local_next = start + 1
            self._local_limit = start + rec.host["block"]
            return start
