"""ReplicatedRedisson: master-discovery client over plain replicated nodes.

Parity target: ``connection/ReplicatedConnectionManager.java`` (270 LoC) —
the Azure Redis Cache / AWS ElastiCache shape where a replication group
exposes N plain endpoints and NO cluster protocol: the client itself polls
every configured node to learn which one is currently master (the
reference polls ``INFO replication`` per node; here the ``ROLE`` verb
answers the same question in one structured reply) and moves writes when
the answer changes.  Promotion itself is external (the cloud service or an
operator runs the failover), exactly as in the reference.

TPU-first shape: not a parallel manager class hierarchy — this is the
cluster client with a different *view source*.  The role scan synthesizes
a one-shard full-range view ([0..16383] -> elected master) and every other
mechanism (routing core, retry machine, redirect handling, pools,
balancers, scheduled refresh) is inherited unchanged from
``ClusterRedisson``.  The replica set ALSO comes from the client-side scan
(nodes answering "slave"), not from the master's own registry: a replica
the master forgot across a restart still serves reads, which is the
reference's client-side discovery contract (ReplicatedConnectionManager
builds the slave set from the node list, not from the master).
"""
from __future__ import annotations

import threading
from collections import Counter
from typing import Dict, List, Optional, Tuple

from redisson_tpu.client.cluster import ClusterRedisson
from redisson_tpu.net.client import NodeClient, parse_address
from redisson_tpu.utils.crc16 import MAX_SLOT


def _norm(addr: str) -> str:
    host, port = parse_address(addr)
    return f"{host}:{port}"


class ReplicatedRedisson(ClusterRedisson):
    """Replicated-topology facade (ReplicatedConnectionManager analog)."""

    def __init__(
        self,
        nodes: List[str],
        config=None,
        scan_interval: float = 1.0,
        **kw,
    ):
        # attrs the overridden _fetch_view needs must exist BEFORE the base
        # __init__ runs its first refresh_topology()
        self._nodes = [_norm(a) for a in nodes]
        self._probes: Dict[str, NodeClient] = {}
        self._probe_lock = threading.Lock()
        self._last_scan: Dict[str, Tuple[str, Optional[str]]] = {}
        self._current_master: Optional[str] = None
        self._pending_master: Optional[str] = None
        # replicated groups are small and role flips are externally driven,
        # so the default poll is tighter than cluster's 5s scanInterval
        # (the reference's ReplicatedConnectionManager reuses scanInterval;
        # callers can pass their own)
        super().__init__(nodes, config=config, scan_interval=scan_interval, **kw)

    # -- discovery -----------------------------------------------------------

    def _probe(self, addr: str) -> NodeClient:
        """Persistent single-shot probe client per configured node (the node
        list is static in replicated mode, so probes live for the client's
        lifetime instead of reconnecting every scan tick)."""
        with self._probe_lock:
            pc = self._probes.get(addr)
            if pc is None:
                pkw = dict(self._node_kw)
                pkw.update(ping_interval=0, retry_attempts=0, pool_size=1)
                pc = self._probes[addr] = NodeClient(addr, **pkw)
            return pc

    def _role_scan(self) -> Dict[str, Tuple[str, Optional[str]]]:
        """addr -> ("master", None) | ("replica", master_addr) for every
        configured node that answered ROLE; silent nodes are absent.

        Reported master addresses are normalized through the same parser as
        the configured node list so votes/membership compare equal.  The
        remaining contract (documented, not resolvable client-side): the
        address family must match — a group wired with ``REPLICAOF
        127.0.0.1 ...`` cannot be vote-matched against a node list of
        hostnames, since equating them would need DNS on every scan tick."""
        scan: Dict[str, Tuple[str, Optional[str]]] = {}
        for addr in self._nodes:
            try:
                role = self._probe(addr).execute("ROLE", timeout=2.0, retry_attempts=0)
            except Exception:  # noqa: BLE001 — node down: absent from scan
                continue
            kind = role[0].decode() if isinstance(role[0], bytes) else str(role[0])
            if kind in ("slave", "replica"):
                mh = role[1].decode() if isinstance(role[1], bytes) else str(role[1])
                scan[addr] = ("replica", _norm(f"{mh}:{int(role[2])}"))
            else:
                scan[addr] = ("master", None)
        return scan

    def _elect(self, scan: Dict[str, Tuple[str, Optional[str]]]) -> Optional[str]:
        """Pick the write target among nodes claiming master.

        Replica votes rank first: the group's own replication links are the
        best evidence of who the real master is, and they must be able to
        move a LONG-RUNNING client off a demoted-but-still-claiming old
        master (an external failover that never stops the old node) — a
        freshly started client would elect by votes, and two clients of one
        group must not disagree on the write target.  Stability second: the
        current master keeps the role only among claimants with EQUAL top
        votes (a transient co-claimant with no replica backing must not
        flap writes).  Final tiebreak is node-list order, matching the
        reference's first-found behavior."""
        masters = [a for a, (k, _) in scan.items() if k == "master"]
        if not masters:
            return None
        votes = Counter(m for (k, m) in scan.values() if k == "replica" and m)
        top_votes = max(votes.get(a, 0) for a in masters)
        top = [a for a in masters if votes.get(a, 0) == top_votes]
        if self._current_master in top:
            return self._current_master
        top.sort(key=self._nodes.index)
        return top[0]

    # -- view source override ------------------------------------------------

    def _fetch_view(self):
        """Role scan -> synthesized one-shard full-range CLUSTER SLOTS view.

        Returning None (no node claims master — e.g. the promotion window
        after a master death, before the external failover lands) keeps the
        previous view, so reads keep flowing from replicas while writes
        fail fast until the next scan finds the promoted node."""
        scan = self._role_scan()
        self._last_scan = scan
        master = self._elect(scan)
        if master is None:
            return None
        # publication waits for the table swap (_refresh_topology_locked):
        # current_master and entry_for_slot must never disagree, and a
        # failed install must not anchor the next election's stickiness
        self._pending_master = master
        host, port = parse_address(master)
        return [[0, MAX_SLOT - 1, [host, port, f"replicated:{master}"]]]

    _replica_discovery = False  # replicas come from the scan, not REPLICAS

    def _refresh_topology_locked(self) -> bool:
        swapped = super()._refresh_topology_locked()
        if not swapped:
            return False
        self._current_master = self._pending_master
        # replica set from the client-side scan (see module docstring) —
        # but ONLY nodes replicating the ELECTED master: a replica still
        # following a stale claimant never receives the elected master's
        # op-log, and installing it as a read target would serve silently
        # stale reads forever, not mere replication lag
        scan = self._last_scan
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            reps = [
                a
                for a, (k, m) in scan.items()
                if k == "replica" and m == e.address and a != e.address
            ]
            e.sync_replicas(reps)
        return swapped

    # -- admin ---------------------------------------------------------------

    @property
    def current_master(self) -> Optional[str]:
        return self._current_master

    def shutdown(self) -> None:
        super().shutdown()
        with self._probe_lock:
            for p in self._probes.values():
                p.close()
            self._probes.clear()

    @classmethod
    def create(cls, config) -> "ReplicatedRedisson":
        from redisson_tpu.client.cluster import (
            READ_MASTER,
            READ_MASTER_SLAVE,
            READ_REPLICA,
        )

        rsc = config.replicated_servers_config
        if rsc is None or not rsc.node_addresses:
            raise ValueError("config.use_replicated_servers() with node_addresses required")
        modes = {
            "MASTER": READ_MASTER,
            "SLAVE": READ_REPLICA,
            "REPLICA": READ_REPLICA,
            "MASTER_SLAVE": READ_MASTER_SLAVE,
        }
        key = str(rsc.read_mode).upper()
        if key not in modes:
            raise ValueError(
                f"unknown read_mode {rsc.read_mode!r}; expected one of {sorted(modes)}"
            )
        ssl_ctx = rsc.build_ssl_context()
        return cls(
            rsc.node_addresses,
            config=config,
            scan_interval=rsc.scan_interval,
            read_mode=modes[key],
            dns_monitoring_interval=rsc.dns_monitoring_interval,
            username=rsc.username,
            password=rsc.password,
            client_name=rsc.client_name,
            ssl_context=ssl_ctx,
            pool_size=rsc.connection_pool_size,
            timeout=rsc.timeout,
            connect_timeout=rsc.connect_timeout,
            retry_attempts=rsc.retry_attempts,
            retry_interval=rsc.retry_interval,
            ping_interval=rsc.ping_connection_interval,
        )
