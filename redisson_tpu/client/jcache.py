"""JCache: the JSR-107 javax.cache surface over MapCache.

Parity target: ``org/redisson/jcache/`` (13 files — JCache, JCacheManager,
JCachingProvider; SURVEY.md §2.7).  The reference implements javax.cache.Cache
on top of the same eviction/TTL machinery as RMapCache; this module mirrors
the JSR-107 contract Python-side: get/put/getAndPut/putIfAbsent/replace/
remove(key[, oldValue])/invoke + ExpiryPolicy (created/updated/accessed TTLs)
+ a CacheManager registry keyed by name.

Semantic notes carried over from the spec (and the reference's JCache.java):
  * `put` returns None; `get_and_put` returns the previous value.
  * `remove(key, old)` only removes on value match.
  * Expiry durations: CREATED applies on insert, UPDATED re-arms on replace,
    ACCESSED re-arms on read (mapped onto MapCache's max_idle).
  * A closed cache raises IllegalStateException analog (RuntimeError).
"""
from __future__ import annotations

import math
from typing import Any, Callable, Dict, Iterable, Optional

from redisson_tpu.client.objects.map import MapCache


class ExpiryPolicy:
    """Durations in seconds; None = eternal (javax.cache.expiry analog)."""

    def __init__(
        self,
        creation: Optional[float] = None,
        update: Optional[float] = None,
        access: Optional[float] = None,
    ):
        self.creation = creation
        self.update = update
        self.access = access

    @classmethod
    def eternal(cls) -> "ExpiryPolicy":
        return cls()

    @classmethod
    def created(cls, ttl: float) -> "ExpiryPolicy":
        return cls(creation=ttl)

    @classmethod
    def touched(cls, ttl: float) -> "ExpiryPolicy":
        # TouchedExpiryPolicy: any interaction re-arms — maps to max_idle
        return cls(access=ttl)


class CacheConfig:
    def __init__(
        self,
        expiry: Optional[ExpiryPolicy] = None,
        store_by_value: bool = True,
        statistics_enabled: bool = True,
    ):
        self.expiry = expiry or ExpiryPolicy.eternal()
        self.store_by_value = store_by_value
        self.statistics_enabled = statistics_enabled


class CacheStatistics:
    __slots__ = ("hits", "misses", "puts", "removals")

    def __init__(self):
        self.hits = self.misses = self.puts = self.removals = 0

    @property
    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else math.nan


class Cache:
    """javax.cache.Cache analog backed by one MapCache record."""

    def __init__(self, manager: "CacheManager", name: str, config: CacheConfig):
        self._manager = manager
        self._name = name
        self._config = config
        self._map = MapCache(manager._engine, f"jcache:{name}")
        manager._engine.eviction.schedule(f"jcache:{name}", self._map.reap_expired)
        self._closed = False
        self.statistics = CacheStatistics()

    # -- helpers -------------------------------------------------------------

    def _check_open(self):
        if self._closed:
            raise RuntimeError(f"cache '{self._name}' is closed")

    def _stat(self, attr: str, n: int = 1) -> None:
        if self._config.statistics_enabled:
            setattr(self.statistics, attr, getattr(self.statistics, attr) + n)

    def _put_with_policy(self, key, value):
        """Spec-accurate expiry arming (JSR-107 §ExpiryPolicy): the creation
        duration governs inserts; the update duration governs overwrites —
        and when the update duration is unspecified, the entry's remaining
        TTL is preserved (CreatedExpiryPolicy.getExpiryForUpdate == null)."""
        e = self._config.expiry
        with self._manager._engine.locked(self._map.name):
            if not self._map.contains_key(key):
                return self._map.put_with_ttl(key, value, ttl=e.creation, max_idle=e.access)
            if e.update is not None:
                return self._map.put_with_ttl(key, value, ttl=e.update, max_idle=e.access)
            remaining = self._map.remain_time_to_live_entry(key)
            return self._map.put_with_ttl(key, value, ttl=remaining, max_idle=e.access)

    @property
    def name(self) -> str:
        return self._name

    # -- JSR-107 surface -----------------------------------------------------

    def get(self, key):
        self._check_open()
        v = self._map.get(key)
        self._stat("misses" if v is None else "hits")
        return v

    def get_all(self, keys: Iterable) -> Dict:
        self._check_open()
        return {k: v for k in keys if (v := self.get(k)) is not None}

    def contains_key(self, key) -> bool:
        self._check_open()
        return self._map.contains_key(key)

    def put(self, key, value) -> None:
        self._check_open()
        self._put_with_policy(key, value)
        self._stat("puts")

    def get_and_put(self, key, value):
        self._check_open()
        old = self._put_with_policy(key, value)
        self._stat("puts")
        return old

    def put_all(self, entries: Dict) -> None:
        for k, v in entries.items():
            self.put(k, v)

    def put_if_absent(self, key, value) -> bool:
        self._check_open()
        e = self._config.expiry
        prev = self._map.put_if_absent_with_ttl(
            key, value, ttl=e.creation, max_idle=e.access
        )
        if prev is None:
            self._stat("puts")
            return True
        return False

    def remove(self, key, old_value=None) -> bool:
        self._check_open()
        if old_value is not None:
            ok = self._map.remove_if_equals(key, old_value)
        else:
            ok = self._map.fast_remove(key) > 0
        if ok:
            self._stat("removals")
        return ok

    def get_and_remove(self, key):
        self._check_open()
        old = self._map.remove(key)
        if old is not None:
            self._stat("removals")
        return old

    def _replace_with_policy(self, key, value):
        """Replace-if-present honoring the update expiry duration — going
        straight to Map.replace would reset the cell's TTL/max-idle to None
        via MapCache._raw_put, silently making the entry eternal."""
        with self._manager._engine.locked(self._map.name):
            if not self._map.contains_key(key):
                return None, False
            old = self._put_with_policy(key, value)
            return old, True

    def replace(self, key, value, old_value=None) -> bool:
        self._check_open()
        if old_value is not None:
            with self._manager._engine.locked(self._map.name):
                if self._map.get(key) != old_value:
                    return False
                self._put_with_policy(key, value)
                self._stat("puts")
                return True
        _, ok = self._replace_with_policy(key, value)
        if ok:
            self._stat("puts")
        return ok

    def get_and_replace(self, key, value):
        self._check_open()
        old, ok = self._replace_with_policy(key, value)
        if ok:
            self._stat("puts")
        return old

    def remove_all(self, keys: Optional[Iterable] = None) -> None:
        self._check_open()
        if keys is None:
            n = self._map.size()
            self._map.clear()
            self._stat("removals", n)
        else:
            self._stat("removals", self._map.fast_remove(*list(keys)))

    def clear(self) -> None:
        self._check_open()
        self._map.clear()

    def invoke(self, key, processor: Callable[["MutableEntry"], Any]):
        """EntryProcessor: atomic read-modify-write on one entry."""
        self._check_open()
        with self._manager._engine.locked(self._map.name):
            entry = MutableEntry(self, key)
            result = processor(entry)
            entry._apply()
            return result

    def iterator(self):
        self._check_open()
        return iter(self._map.read_all_entry_set())

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            try:
                self._manager._engine.eviction.unschedule(f"jcache:{self._name}")
            except RuntimeError:
                pass  # engine already shut down
            self._manager._caches.pop(self._name, None)

    @property
    def closed(self) -> bool:
        return self._closed

    def __iter__(self):
        return self.iterator()


class MutableEntry:
    """javax.cache.processor.MutableEntry analog."""

    def __init__(self, cache: Cache, key):
        self._cache = cache
        self.key = key
        self._value = cache._map.get(key)
        self._exists = self._value is not None
        self._op: Optional[str] = None  # None | "set" | "remove"

    @property
    def value(self):
        return self._value

    def exists(self) -> bool:
        return self._exists

    def set_value(self, value) -> None:
        self._value = value
        self._exists = True
        self._op = "set"

    def remove(self) -> None:
        self._exists = False
        self._op = "remove"

    def _apply(self) -> None:
        if self._op == "set":
            self._cache._put_with_policy(self.key, self._value)
        elif self._op == "remove":
            self._cache._map.fast_remove(self.key)


class CacheManager:
    """javax.cache.CacheManager analog (jcache/JCacheManager role)."""

    def __init__(self, engine):
        self._engine = engine
        self._caches: Dict[str, Cache] = {}
        self._closed = False

    def create_cache(self, name: str, config: Optional[CacheConfig] = None) -> Cache:
        if self._closed:
            raise RuntimeError("cache manager is closed")
        if name in self._caches:
            raise ValueError(f"cache '{name}' already exists")
        cache = Cache(self, name, config or CacheConfig())
        self._caches[name] = cache
        return cache

    def get_cache(self, name: str) -> Optional[Cache]:
        return self._caches.get(name)

    def get_or_create_cache(self, name: str, config: Optional[CacheConfig] = None) -> Cache:
        return self._caches.get(name) or self.create_cache(name, config)

    def cache_names(self):
        return list(self._caches)

    def destroy_cache(self, name: str) -> None:
        cache = self._caches.pop(name, None)
        if cache is not None:
            cache._map.clear()
            cache.close()

    def close(self) -> None:
        for cache in list(self._caches.values()):
            cache.close()
        self._closed = True
