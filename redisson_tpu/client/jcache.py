"""JCache: the JSR-107 javax.cache surface over MapCache.

Parity target: ``org/redisson/jcache/`` (13 files — JCache, JCacheManager,
JCachingProvider; SURVEY.md §2.7).  The reference implements javax.cache.Cache
on top of the same eviction/TTL machinery as RMapCache; this module mirrors
the JSR-107 contract Python-side: get/put/getAndPut/putIfAbsent/replace/
remove(key[, oldValue])/invoke + ExpiryPolicy (created/updated/accessed TTLs)
+ a CacheManager registry keyed by name.

Semantic notes carried over from the spec (and the reference's JCache.java):
  * `put` returns None; `get_and_put` returns the previous value.
  * `remove(key, old)` only removes on value match.
  * Expiry durations: CREATED applies on insert, UPDATED re-arms on replace,
    ACCESSED re-arms on read (mapped onto MapCache's max_idle).
  * A closed cache raises IllegalStateException analog (RuntimeError).
  * Read/write-through (`jcache/JCache.java:77-104,406-421,1257-1290`):
    a CacheLoader fills misses when `read_through` is set; a CacheWriter is
    invoked BEFORE the cache mutates when `write_through` is set, and a
    writer failure leaves the cache unchanged (CacheWriterException).
  * Entry listeners (`jcache/JCache.java:3154-3312`): created/updated/
    removed/expired events with optional filter, `old_value_required`, and
    a `synchronous` flag — synchronous listeners run inline in the mutating
    call (a listener error propagates to the caller, per spec), async ones
    ride the engine events pool.  `clear()` fires NO events (JSR-107
    distinguishes it from removeAll exactly this way).
  * Statistics mirror CacheStatisticsMXBean: hits/misses/gets/puts/
    removals/evictions + average get/put/remove µs.
"""
from __future__ import annotations

import math
import time
from typing import Any, Callable, Dict, Iterable, List, Optional

from redisson_tpu.client.objects.map import MapCache


class CacheException(RuntimeError):
    """javax.cache.CacheException analog."""


class CacheLoaderException(CacheException):
    """Wraps a CacheLoader failure (javax.cache.integration)."""


class CacheWriterException(CacheException):
    """Wraps a CacheWriter failure; the cache is left unmodified."""


class CacheLoader:
    """javax.cache.integration.CacheLoader analog.  Subclass or duck-type
    `load`; `load_all` defaults to per-key loads."""

    def load(self, key):  # pragma: no cover - SPI default
        raise NotImplementedError

    def load_all(self, keys: Iterable) -> Dict:
        return {k: v for k in keys if (v := self.load(k)) is not None}


class CacheWriter:
    """javax.cache.integration.CacheWriter analog (write/delete + bulk)."""

    def write(self, key, value):  # pragma: no cover - SPI default
        raise NotImplementedError

    def delete(self, key):  # pragma: no cover - SPI default
        raise NotImplementedError

    def write_all(self, entries: Dict) -> None:
        for k, v in entries.items():
            self.write(k, v)

    def delete_all(self, keys: Iterable) -> None:
        for k in keys:
            self.delete(k)


class CacheEntryEvent:
    """javax.cache.event.CacheEntryEvent analog (JCacheEntryEvent.java)."""

    __slots__ = ("cache", "event_type", "key", "value", "old_value")

    def __init__(self, cache, event_type, key, value, old_value=None):
        self.cache = cache
        self.event_type = event_type  # 'created'|'updated'|'removed'|'expired'
        self.key = key
        self.value = value
        self.old_value = old_value

    def __repr__(self):
        return (f"CacheEntryEvent({self.event_type}, key={self.key!r}, "
                f"value={self.value!r}, old={self.old_value!r})")


class CacheEntryListenerConfiguration:
    """MutableCacheEntryListenerConfiguration analog.  `listener` is an
    object exposing any of on_created/on_updated/on_removed/on_expired
    (each called with one CacheEntryEvent); `filter(event) -> bool` gates
    delivery; `synchronous` listeners run inline in the mutating call."""

    def __init__(self, listener, filter: Optional[Callable] = None,
                 old_value_required: bool = False, synchronous: bool = False):
        self.listener = listener
        self.filter = filter
        self.old_value_required = old_value_required
        self.synchronous = synchronous


class ExpiryPolicy:
    """Durations in seconds; None = eternal (javax.cache.expiry analog)."""

    def __init__(
        self,
        creation: Optional[float] = None,
        update: Optional[float] = None,
        access: Optional[float] = None,
    ):
        self.creation = creation
        self.update = update
        self.access = access

    @classmethod
    def eternal(cls) -> "ExpiryPolicy":
        return cls()

    @classmethod
    def created(cls, ttl: float) -> "ExpiryPolicy":
        return cls(creation=ttl)

    @classmethod
    def touched(cls, ttl: float) -> "ExpiryPolicy":
        # TouchedExpiryPolicy: any interaction re-arms — maps to max_idle
        return cls(access=ttl)


class CacheConfig:
    def __init__(
        self,
        expiry: Optional[ExpiryPolicy] = None,
        store_by_value: bool = True,
        statistics_enabled: bool = True,
        loader: Optional[CacheLoader] = None,
        writer: Optional[CacheWriter] = None,
        read_through: bool = False,
        write_through: bool = False,
        listener_configurations: Optional[Iterable[CacheEntryListenerConfiguration]] = None,
    ):
        self.expiry = expiry or ExpiryPolicy.eternal()
        self.store_by_value = store_by_value
        self.statistics_enabled = statistics_enabled
        self.loader = loader
        self.writer = writer
        self.read_through = read_through and loader is not None
        self.write_through = write_through and writer is not None
        self.listener_configurations = list(listener_configurations or ())


class CacheStatistics:
    """CacheStatisticsMXBean analog: counters + average op times (µs)."""

    __slots__ = ("hits", "misses", "puts", "removals", "evictions",
                 "_get_ns", "_put_ns", "_remove_ns")

    def __init__(self):
        self.clear()

    def clear(self) -> None:
        self.hits = self.misses = self.puts = self.removals = 0
        self.evictions = 0
        self._get_ns = self._put_ns = self._remove_ns = 0

    @property
    def gets(self) -> int:
        return self.hits + self.misses

    @property
    def hit_ratio(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else math.nan

    @property
    def miss_ratio(self) -> float:
        total = self.hits + self.misses
        return self.misses / total if total else math.nan

    def _avg_us(self, total_ns: int, count: int) -> float:
        return (total_ns / count) / 1e3 if count else 0.0

    @property
    def average_get_time_us(self) -> float:
        return self._avg_us(self._get_ns, self.gets)

    @property
    def average_put_time_us(self) -> float:
        return self._avg_us(self._put_ns, self.puts)

    @property
    def average_remove_time_us(self) -> float:
        return self._avg_us(self._remove_ns, self.removals)


class Cache:
    """javax.cache.Cache analog backed by one MapCache record."""

    def __init__(self, manager: "CacheManager", name: str, config: CacheConfig):
        self._manager = manager
        self._name = name
        self._config = config
        self._map = MapCache(manager._engine, f"jcache:{name}")
        manager._engine.eviction.schedule(f"jcache:{name}", self._map.reap_expired)
        self._closed = False
        self.statistics = CacheStatistics()
        self._listeners: List[CacheEntryListenerConfiguration] = []
        for lc in config.listener_configurations:
            self.register_cache_entry_listener(lc)
        # TTL/idle expiry surfaces from MapCache's lazy reap + sweeper, not
        # from this layer, so expired events ride the map's hub channel.
        self._expired_token = self._map.add_entry_listener(
            "expired", self._on_map_expired
        )

    # -- helpers -------------------------------------------------------------

    def _check_open(self):
        if self._closed:
            raise RuntimeError(f"cache '{self._name}' is closed")

    def _stat(self, attr: str, n: int = 1) -> None:
        if self._config.statistics_enabled:
            setattr(self.statistics, attr, getattr(self.statistics, attr) + n)

    def _timed(self, bucket: str, t0: float) -> None:
        if self._config.statistics_enabled:
            ns = int((time.perf_counter() - t0) * 1e9)
            setattr(self.statistics, bucket, getattr(self.statistics, bucket) + ns)

    # -- entry listeners -----------------------------------------------------

    def register_cache_entry_listener(
        self, lc: CacheEntryListenerConfiguration
    ) -> None:
        """JCache.registerCacheEntryListener (jcache/JCache.java:3154-3283)."""
        if lc in self._listeners:
            raise ValueError("listener configuration already registered")
        self._listeners.append(lc)

    def deregister_cache_entry_listener(
        self, lc: CacheEntryListenerConfiguration
    ) -> None:
        try:
            self._listeners.remove(lc)
        except ValueError:
            pass

    def _on_map_expired(self, key, value, _old) -> None:
        self._stat("evictions")
        # EXPIRED events expose the expired value as the old value too
        self._dispatch("expired", key, value, value, force_async=True)

    def _dispatch(self, kind: str, key, value, old, force_async: bool = False) -> None:
        """Deliver one event to every matching listener.  Synchronous
        listeners run inline (errors propagate, per JSR-107 §synchronous);
        async ones ride the engine events pool in FIFO order.  Expiry events
        are always async — they originate on the reap path."""
        if not self._listeners:
            return
        method = f"on_{kind}"
        for lc in self._listeners:
            fn = getattr(lc.listener, method, None)
            if fn is None:
                continue
            ev = CacheEntryEvent(
                self, kind, key, value, old if lc.old_value_required else None
            )
            if lc.filter is not None and not lc.filter(ev):
                continue
            if lc.synchronous and not force_async:
                fn(ev)
            else:
                try:
                    self._manager._engine.events_pool.submit(fn, ev)
                except RuntimeError:
                    pass  # engine shutting down: events are best-effort

    def _after_put(self, key, value, old) -> None:
        if old is None:
            self._dispatch("created", key, value, None)
        else:
            self._dispatch("updated", key, value, old)

    # -- read/write-through --------------------------------------------------

    def _load(self, key):
        """Read-through fill on a miss (jcache/JCache.java:406-421)."""
        try:
            value = self._config.loader.load(key)
        except Exception as e:  # noqa: BLE001 - spec wraps any loader error
            raise CacheLoaderException(f"loader failed for {key!r}") from e
        if value is not None:
            e = self._config.expiry
            old = self._map.put_with_ttl(key, value, ttl=e.creation, max_idle=e.access)
            self._after_put(key, value, old)
        return value

    def _write(self, key, value) -> None:
        """Write-through: the writer runs BEFORE the cache mutates, so a
        failing writer leaves the cache unchanged (jcache/JCache.java:1257-1290)."""
        if self._config.write_through:
            try:
                self._config.writer.write(key, value)
            except Exception as e:  # noqa: BLE001
                raise CacheWriterException(f"writer failed for {key!r}") from e

    def _delete(self, key) -> None:
        if self._config.write_through:
            try:
                self._config.writer.delete(key)
            except Exception as e:  # noqa: BLE001
                raise CacheWriterException(f"writer delete failed for {key!r}") from e

    def _put_with_policy(self, key, value):
        """Spec-accurate expiry arming (JSR-107 §ExpiryPolicy): the creation
        duration governs inserts; the update duration governs overwrites —
        and when the update duration is unspecified, the entry's remaining
        TTL is preserved (CreatedExpiryPolicy.getExpiryForUpdate == null)."""
        e = self._config.expiry
        with self._manager._engine.locked(self._map.name):
            if not self._map.contains_key(key):
                return self._map.put_with_ttl(key, value, ttl=e.creation, max_idle=e.access)
            if e.update is not None:
                return self._map.put_with_ttl(key, value, ttl=e.update, max_idle=e.access)
            remaining = self._map.remain_time_to_live_entry(key)
            return self._map.put_with_ttl(key, value, ttl=remaining, max_idle=e.access)

    @property
    def name(self) -> str:
        return self._name

    # -- JSR-107 surface -----------------------------------------------------

    def get(self, key):
        self._check_open()
        t0 = time.perf_counter()
        v = self._map.get(key)
        self._stat("misses" if v is None else "hits")
        if v is None and self._config.read_through:
            v = self._load(key)
        self._timed("_get_ns", t0)
        return v

    def get_all(self, keys: Iterable) -> Dict:
        self._check_open()
        t0 = time.perf_counter()
        keys = list(keys)
        out = {}
        missing = []
        for k in keys:
            v = self._map.get(k)
            self._stat("misses" if v is None else "hits")
            if v is None:
                missing.append(k)
            else:
                out[k] = v
        if missing and self._config.read_through:
            # bulk fill mirrors JCache.getAll's loadAll path (JCache.java:406)
            try:
                loaded = self._config.loader.load_all(missing)
            except Exception as e:  # noqa: BLE001
                raise CacheLoaderException("loadAll failed") from e
            exp = self._config.expiry
            for k, v in loaded.items():
                if v is None:
                    continue
                old = self._map.put_with_ttl(k, v, ttl=exp.creation, max_idle=exp.access)
                self._after_put(k, v, old)
                out[k] = v
        self._timed("_get_ns", t0)
        return out

    def load_all(self, keys: Iterable, replace_existing: bool = False,
                 completion_listener: Optional[Callable] = None) -> None:
        """Cache.loadAll (jcache/JCache.java:1117-1160): warm the cache from
        the loader; `completion_listener(exc_or_None)` fires when done."""
        self._check_open()
        if self._config.loader is None:
            if completion_listener is not None:
                completion_listener(None)
            return
        targets = list(keys)
        if not replace_existing:
            targets = [k for k in targets if not self._map.contains_key(k)]
        try:
            loaded = self._config.loader.load_all(targets)
        except Exception as e:  # noqa: BLE001 - only LOADER errors wrap; a
            # put/listener failure below is a cache bug and must surface as-is
            exc = CacheLoaderException("loadAll failed")
            exc.__cause__ = e
            if completion_listener is not None:
                completion_listener(exc)
                return
            raise exc from e
        exp = self._config.expiry
        for k, v in loaded.items():
            if v is None:
                continue
            old = self._map.put_with_ttl(k, v, ttl=exp.creation, max_idle=exp.access)
            self._after_put(k, v, old)
        if completion_listener is not None:
            completion_listener(None)

    def contains_key(self, key) -> bool:
        self._check_open()
        return self._map.contains_key(key)

    def put(self, key, value) -> None:
        self.get_and_put(key, value)

    def get_and_put(self, key, value):
        self._check_open()
        t0 = time.perf_counter()
        # writer + cache mutate under ONE record lock (reentrant) so the
        # external store and the cache can't interleave to different orders
        with self._manager._engine.locked(self._map.name):
            self._write(key, value)
            old = self._put_with_policy(key, value)
        self._stat("puts")
        self._timed("_put_ns", t0)
        self._after_put(key, value, old)
        return old

    def put_all(self, entries: Dict) -> None:
        """Bulk write-through rides writer.write_all; a failing writer keeps
        ALL entries out of the cache (jcache/JCache.java:1641 discipline)."""
        self._check_open()
        t0 = time.perf_counter()
        with self._manager._engine.locked(self._map.name):
            if self._config.write_through and entries:
                try:
                    self._config.writer.write_all(dict(entries))
                except Exception as e:  # noqa: BLE001
                    raise CacheWriterException("writeAll failed") from e
            applied = [(k, v, self._put_with_policy(k, v)) for k, v in entries.items()]
        for k, v, old in applied:
            self._stat("puts")
            self._after_put(k, v, old)
        self._timed("_put_ns", t0)

    def put_if_absent(self, key, value) -> bool:
        self._check_open()
        t0 = time.perf_counter()
        e = self._config.expiry
        with self._manager._engine.locked(self._map.name):
            if self._map.contains_key(key):
                return False
            self._write(key, value)
            self._map.put_with_ttl(key, value, ttl=e.creation, max_idle=e.access)
        self._stat("puts")
        self._timed("_put_ns", t0)
        self._dispatch("created", key, value, None)
        return True

    def remove(self, key, old_value=None) -> bool:
        self._check_open()
        t0 = time.perf_counter()
        with self._manager._engine.locked(self._map.name):
            if old_value is not None:
                cur = self._map.get(key)
                if cur != old_value:
                    return False
                self._delete(key)
                ok = self._map.fast_remove(key) > 0
                old = old_value
            else:
                old = self._map.get(key)
                # spec: write-through delete fires even for an absent key
                self._delete(key)
                ok = self._map.fast_remove(key) > 0
        if ok:
            self._stat("removals")
            self._timed("_remove_ns", t0)
            self._dispatch("removed", key, old, old)
        return ok

    def get_and_remove(self, key):
        self._check_open()
        t0 = time.perf_counter()
        with self._manager._engine.locked(self._map.name):
            old = self._map.get(key)
            self._delete(key)
            if old is not None:
                self._map.fast_remove(key)
        if old is not None:
            self._stat("removals")
            self._timed("_remove_ns", t0)
            self._dispatch("removed", key, old, old)
        return old

    def replace(self, key, value, old_value=None) -> bool:
        self._check_open()
        t0 = time.perf_counter()
        if old_value is not None:
            with self._manager._engine.locked(self._map.name):
                if self._map.get(key) != old_value:
                    return False
                self._write(key, value)
                self._put_with_policy(key, value)
            self._stat("puts")
            self._timed("_put_ns", t0)
            self._dispatch("updated", key, value, old_value)
            return True
        with self._manager._engine.locked(self._map.name):
            if not self._map.contains_key(key):
                return False
            self._write(key, value)
            old = self._put_with_policy(key, value)
        self._stat("puts")
        self._timed("_put_ns", t0)
        self._dispatch("updated", key, value, old)
        return True

    def get_and_replace(self, key, value):
        self._check_open()
        t0 = time.perf_counter()
        with self._manager._engine.locked(self._map.name):
            if not self._map.contains_key(key):
                return None
            self._write(key, value)
            old = self._put_with_policy(key, value)
        self._stat("puts")
        self._timed("_put_ns", t0)
        self._dispatch("updated", key, value, old)
        return old

    def remove_all(self, keys: Optional[Iterable] = None) -> None:
        """removeAll DOES notify per key and write-through-deletes, unlike
        clear() (JSR-107 distinguishes them; jcache/JCache.java:1811-1845)."""
        self._check_open()
        t0 = time.perf_counter()
        if keys is None:
            keys = self._map.read_all_keys()
        keys = list(keys)
        if self._config.write_through and keys:
            try:
                self._config.writer.delete_all(list(keys))
            except Exception as e:  # noqa: BLE001
                raise CacheWriterException("deleteAll failed") from e
        for k in keys:
            with self._manager._engine.locked(self._map.name):
                old = self._map.get(k)
                removed = self._map.fast_remove(k) > 0
            if removed:
                self._stat("removals")
                self._dispatch("removed", k, old, old)
        self._timed("_remove_ns", t0)

    def clear(self) -> None:
        # clear() is the event-free, writer-free wipe (JSR-107 §Cache.clear)
        self._check_open()
        self._map.clear()

    def invoke(self, key, processor: Callable[["MutableEntry"], Any]):
        """EntryProcessor: atomic read-modify-write on one entry, with
        read-through on access and write-through + events on apply."""
        self._check_open()
        with self._manager._engine.locked(self._map.name):
            entry = MutableEntry(self, key)
            result = processor(entry)
            entry._apply()
        entry._notify()
        return result

    def invoke_all(self, keys: Iterable, processor: Callable[["MutableEntry"], Any]) -> Dict:
        return {k: self.invoke(k, processor) for k in keys}

    def iterator(self):
        self._check_open()
        return iter(self._map.read_all_entry_set())

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._map.remove_entry_listener(self._expired_token)
            self._listeners.clear()
            try:
                self._manager._engine.eviction.unschedule(f"jcache:{self._name}")
            except RuntimeError:
                pass  # engine already shut down
            self._manager._caches.pop(self._name, None)

    @property
    def closed(self) -> bool:
        return self._closed

    def __iter__(self):
        return self.iterator()


class MutableEntry:
    """javax.cache.processor.MutableEntry analog (JMutableEntry.java).

    `value` triggers a read-through load on a miss (JSR-107 §EntryProcessor);
    set_value/remove are buffered and applied — with write-through — after
    the processor returns, still under the record lock."""

    def __init__(self, cache: Cache, key):
        self._cache = cache
        self.key = key
        self._value = cache._map.get(key)
        self._old = self._value
        self._exists = self._value is not None
        self._loaded = False
        self._op: Optional[str] = None  # None | "set" | "remove"

    @property
    def value(self):
        if (self._value is None and self._op is None and not self._loaded
                and self._cache._config.read_through):
            self._loaded = True
            try:
                self._value = self._cache._config.loader.load(self.key)
            except Exception as e:  # noqa: BLE001
                raise CacheLoaderException(f"loader failed for {self.key!r}") from e
            self._exists = self._value is not None
        return self._value

    def exists(self) -> bool:
        return self._exists

    def set_value(self, value) -> None:
        self._value = value
        self._exists = True
        self._op = "set"

    def remove(self) -> None:
        self._exists = False
        self._op = "remove"

    def _apply(self) -> None:
        if self._op == "set":
            self._cache._write(self.key, self._value)
            self._cache._put_with_policy(self.key, self._value)
            self._cache._stat("puts")
        elif self._op == "remove":
            # write-through delete fires even when the entry was absent from
            # the cache (e.g. remove() after a read-through load) — the
            # processor explicitly removed the external row
            self._cache._delete(self.key)
            if self._old is not None:
                self._cache._map.fast_remove(self.key)
                self._cache._stat("removals")
        elif self._loaded and self._value is not None:
            # a read-through hit inside the processor populates the cache
            self._cache._put_with_policy(self.key, self._value)

    def _notify(self) -> None:
        if self._op == "set":
            self._cache._after_put(self.key, self._value, self._old)
        elif self._op == "remove" and self._old is not None:
            self._cache._dispatch("removed", self.key, self._old, self._old)
        elif self._loaded and self._op is None and self._value is not None:
            self._cache._dispatch("created", self.key, self._value, None)


class CacheManager:
    """javax.cache.CacheManager analog (jcache/JCacheManager role)."""

    def __init__(self, engine):
        self._engine = engine
        self._caches: Dict[str, Cache] = {}
        self._closed = False

    def create_cache(self, name: str, config: Optional[CacheConfig] = None) -> Cache:
        if self._closed:
            raise RuntimeError("cache manager is closed")
        if name in self._caches:
            raise ValueError(f"cache '{name}' already exists")
        cache = Cache(self, name, config or CacheConfig())
        self._caches[name] = cache
        return cache

    def get_cache(self, name: str) -> Optional[Cache]:
        return self._caches.get(name)

    def get_or_create_cache(self, name: str, config: Optional[CacheConfig] = None) -> Cache:
        return self._caches.get(name) or self.create_cache(name, config)

    def cache_names(self):
        return list(self._caches)

    def destroy_cache(self, name: str) -> None:
        cache = self._caches.pop(name, None)
        if cache is not None:
            cache._map.clear()
            cache.close()

    def close(self) -> None:
        for cache in list(self._caches.values()):
            cache.close()
        self._closed = True
