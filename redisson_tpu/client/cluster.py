"""ClusterRedisson: slot-routed client over an N-master server topology.

Parity targets (SURVEY.md §2.2, §3.6):
  * ``cluster/ClusterConnectionManager.java:84-180`` — topology discovery
    (CLUSTER SLOTS from any reachable seed), slot->entry table[16384],
    scheduled topology refresh (scanInterval).
  * ``connection/MasterSlaveEntry.java:106-299`` — per-shard master +
    replica set with freeze/unfreeze and balancer-driven read routing
    (ReadMode MASTER / SLAVE / MASTER_SLAVE).
  * ``command/RedisExecutor.java`` redirect handling — MOVED replies refresh
    the topology and re-route, bounded by max_redirects.

TPU-first departure: there is no gossip; the slot map is installed by the
launcher/failover coordinator (harness.ClusterRunner, server/monitor.py) via
CLUSTER SETVIEW, and clients treat MOVED + periodic refresh as the only
discovery protocol — the data plane stays entirely in the server processes
next to their chips.
"""
from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from redisson_tpu.client import routing
from redisson_tpu.net import commands as C
from redisson_tpu.net.balancer import LoadBalancer, RoundRobinLoadBalancer
from redisson_tpu.net.client import ConnectionError_, NodeClient, parse_address
from redisson_tpu.net.resp import RespError
from redisson_tpu.utils.crc16 import MAX_SLOT, calc_slot

READ_MASTER = "master"
READ_REPLICA = "replica"
READ_MASTER_SLAVE = "master_slave"

# Default sweep-cut lag bound for replica-read profiles, DERIVED from the
# replication shipper's cadence (server/replication.py): the master's
# ``offset`` ticks once per sweep CUT and the shipper sweeps every 0.2 s by
# default, so a HEALTHY replica is at most ~2 cuts behind at any instant —
# the cut currently in flight on the link plus the cut forming at the
# master (the heartbeat, throttled to interval/2, keeps an idle link's lag
# at 0).  Bounding lag at 2 cuts therefore admits every healthy replica
# (~0.4 s of writes at the default cadence) while redirecting reads off a
# replica whose link has actually stalled — without the operator having to
# know the shipper's internals.  Explicit ``max_staleness_ms`` /
# ``max_staleness_offset`` values override the derivation entirely.
DEFAULT_REPLICA_STALENESS_OFFSET = 2


class ShardEntry:
    """One shard: master client + replica clients + read balancer
    (MasterSlaveEntry analog)."""

    def __init__(self, address: str, balancer: Optional[LoadBalancer] = None, **node_kw):
        self.address = address
        self.master = NodeClient(address, **node_kw)
        self.replicas: Dict[str, NodeClient] = {}
        self.balancer = balancer or RoundRobinLoadBalancer()
        self._node_kw = node_kw

    def sync_replicas(self, addresses: List[str]) -> None:
        for addr in addresses:
            if addr not in self.replicas:
                # replica connections arm READONLY at handshake (ISSUE 17):
                # a cluster replica -MOVEDs keyed reads from plain conns
                self.replicas[addr] = NodeClient(
                    addr, readonly=True, **self._node_kw
                )
        for addr in list(self.replicas):
            if addr not in addresses:
                self.replicas.pop(addr).close()

    def read_node(self, read_mode: str) -> NodeClient:
        if read_mode == READ_MASTER or not self.replicas:
            return self.master
        pool = list(self.replicas.values())
        if read_mode == READ_MASTER_SLAVE:
            pool = pool + [self.master]
        return self.balancer.pick(pool) or self.master

    def close(self) -> None:
        self.master.close()
        for r in self.replicas.values():
            r.close()


from redisson_tpu.client.remote import RemoteSurface


class ClusterRedisson(RemoteSurface):
    """Slot-routed facade sharing the Remote* handle surface (the handles
    call ``client.execute``/``client.objcall``; routing happens here)."""

    # refresh asks each master for its replica set (REPLICAS); replicated
    # mode discovers replicas client-side instead and sets this False
    _replica_discovery = True

    def __init__(
        self,
        seeds: List[str],
        config=None,
        read_mode: str = READ_MASTER,
        balancer: Optional[LoadBalancer] = None,
        scan_interval: float = 5.0,
        dns_monitoring_interval: float = 5.0,
        max_redirects: int = 5,
        max_staleness_ms: Optional[int] = None,
        max_staleness_offset: Optional[int] = None,
        **node_kw,
    ):
        from redisson_tpu.config import Config

        self.config = config or Config()
        self.read_mode = read_mode
        self.max_redirects = max_redirects
        # bounded-staleness contract (ISSUE 17): with either bound set,
        # every replica-served read pipelines a REPLSTATE MAXSTALE probe in
        # the SAME frame and the client redirects to the master when the
        # answer is too stale.  max_staleness_ms bounds time since the
        # replica's last applied push/heartbeat; max_staleness_offset bounds
        # sweep-cut lag against the highest offset this client has seen any
        # node of the shard prove.
        self.max_staleness_ms = max_staleness_ms
        if (max_staleness_offset is None and max_staleness_ms is None
                and read_mode != READ_MASTER):
            # replica-read profiles are staleness-bounded BY DEFAULT: the
            # sweep-cut lag bound derived from the shipper's cadence (see
            # DEFAULT_REPLICA_STALENESS_OFFSET).  Any explicit bound —
            # either axis — overrides the derivation.
            max_staleness_offset = DEFAULT_REPLICA_STALENESS_OFFSET
        self.max_staleness_offset = max_staleness_offset
        self.read_stats: Dict[str, int] = {
            "replica_reads": 0,
            "replica_redirects_stale": 0,
            "replica_fallbacks": 0,
        }
        self._shard_offsets: Dict[str, int] = {}  # master addr -> max offset seen
        if balancer is None and read_mode != READ_MASTER:
            # replica-read profiles default to lane-occupancy scoring
            # (ISSUE 18): each read leg steers to the candidate whose
            # device lanes are idlest per its scraped CLUSTER QOS ledger,
            # not just round-robin.  One shared instance — it keys its
            # scrape cache by node address.
            from redisson_tpu.net.balancer import OccupancyLoadBalancer

            balancer = OccupancyLoadBalancer()
        self._balancer_factory = balancer
        self._node_kw = dict(node_kw)
        # config-level SPIs ride every node connection of the cluster
        self._node_kw.setdefault("credentials_resolver", self.config.credentials_resolver)
        self._node_kw.setdefault("command_mapper", self.config.command_mapper)
        # one ConnectionEventsHub shared by every node of the cluster:
        # listeners see per-ADDRESS edge-triggered connect/disconnect
        from redisson_tpu.net.detectors import ConnectionEventsHub

        self.events_hub = self._node_kw.setdefault(
            "events_hub", ConnectionEventsHub()
        )
        self._seeds = list(seeds)
        self._entries: Dict[str, ShardEntry] = {}  # master address -> entry
        self._slots: List[Optional[str]] = [None] * MAX_SLOT  # slot -> master address
        self._lock = threading.RLock()
        # refreshes serialize: two concurrent refreshes building entries for
        # the same new address would leak the loser's connections
        self._refresh_lock = threading.Lock()
        self._closed = threading.Event()
        self.refresh_topology()
        self._scan_interval = scan_interval
        self._scan_thread: Optional[threading.Thread] = None
        if scan_interval and scan_interval > 0:
            self._scan_thread = threading.Thread(
                target=self._scan_loop, daemon=True, name="rtpu-cluster-scan"
            )
            self._scan_thread.start()
        # DNS re-resolution for hostname seeds (connection/DNSMonitor.java):
        # an A-record flip behind a stable name triggers a topology refresh.
        # <= 0 disables (the reference's dnsMonitoringInterval=-1)
        self._dns = None
        if dns_monitoring_interval and dns_monitoring_interval > 0:
            from redisson_tpu.net.dns import DNSMonitor

            self._dns = DNSMonitor(
                seeds,
                lambda _ep, _old, _new: self.refresh_topology(),
                interval=dns_monitoring_interval,
            ).start()

    @classmethod
    def create(cls, config) -> "ClusterRedisson":
        """Build from Config.use_cluster_servers() (ClusterServersConfig
        analog: node addresses, scanInterval, readMode, pool/retry knobs)."""
        csc = config.use_cluster_servers()
        if not csc.node_addresses:
            raise ValueError("cluster_servers_config.node_addresses is empty")
        modes = {
            "MASTER": READ_MASTER,
            "SLAVE": READ_REPLICA,
            "REPLICA": READ_REPLICA,
            "MASTER_SLAVE": READ_MASTER_SLAVE,
        }
        key = str(csc.read_mode).upper()
        if key not in modes:
            raise ValueError(
                f"unknown read_mode {csc.read_mode!r}; expected one of {sorted(modes)}"
            )
        read_mode = modes[key]
        return cls(
            list(csc.node_addresses),
            config=config,
            read_mode=read_mode,
            scan_interval=csc.scan_interval,
            dns_monitoring_interval=getattr(csc, "dns_monitoring_interval", 5.0),
            password=csc.password,
            username=csc.username,
            ssl_context=csc.build_ssl_context(),
            client_name=csc.client_name,
            pool_size=csc.connection_pool_size,
            timeout=csc.timeout,
            connect_timeout=csc.connect_timeout,
            retry_attempts=csc.retry_attempts,
            retry_interval=csc.retry_interval,
            ping_interval=csc.ping_connection_interval,
        )

    # -- topology ------------------------------------------------------------

    def _fetch_view(self) -> Optional[List[Any]]:
        """CLUSTER SLOTS from any reachable node (entries first, then seeds)."""
        with self._lock:
            candidates = [e.master for e in self._entries.values()]
        for node in candidates:
            try:
                # single-shot: a dead candidate costs one refused connect,
                # not retries-with-backoff — the NEXT candidate is the retry
                return node.execute("CLUSTER", "SLOTS", timeout=5.0, retry_attempts=0)
            except Exception:  # noqa: BLE001 — try the next node
                continue
        for seed in self._seeds:
            probe = None
            try:
                # probes carry the same credentials as data connections —
                # an AUTH-required cluster must bootstrap too
                kw = dict(self._node_kw)
                kw.update(ping_interval=0, retry_attempts=0)
                probe = NodeClient(seed, **kw)
                return probe.execute("CLUSTER", "SLOTS", timeout=5.0)
            except Exception:  # noqa: BLE001
                continue
            finally:
                if probe is not None:
                    probe.close()
        return None

    def refresh_topology(self) -> bool:
        """Re-read CLUSTER SLOTS and swap the routing table.

        All network I/O (entry construction, REPLICAS discovery) happens
        OUTSIDE self._lock — one dead node's connect timeouts must not stall
        entry_for_slot for healthy shards.  The lock only guards the final
        table swap."""
        if self._closed.is_set():
            return False
        with self._refresh_lock:
            return self._refresh_topology_locked()

    def _refresh_topology_locked(self) -> bool:
        view = self._fetch_view()
        if view is None:
            return False
        new_slots, masters = routing.parse_view(view)
        nat = self.config.nat_mapper
        if nat is not None:
            # NatMapper SPI: advertised addresses -> reachable addresses
            # (container/NAT topologies, api/NatMapper.java role).  Mapped
            # once per DISTINCT address — a real mapper may do table/DNS
            # work, and the slot array has 16384 entries
            table = {a: nat.map(a) for a in masters}
            new_slots = [None if a is None else table.get(a, a) for a in new_slots]
            masters = {table[a]: None for a in masters}
        with self._lock:
            existing = dict(self._entries)
        fresh: Dict[str, ShardEntry] = {}
        for addr in masters:
            # gate EVERY entry on ONE single-shot ping: a dead master must
            # leave the routing table (keyless commands and stale-slot
            # fallbacks would otherwise keep picking it), and must cost one
            # refused connect, not retries-with-backoff under the refresh
            # lock.  EXISTING entries get grace: a healthy-but-slow shard
            # (GC pause, first XLA compile) failing ONE probe must not have
            # its warm pools torn down — eviction needs two consecutive
            # failed refreshes.  New entries admit only on a clean ping.
            entry = existing.get(addr)
            created = False
            try:
                if entry is None:
                    entry = ShardEntry(
                        addr, balancer=self._balancer_factory, **self._node_kw
                    )
                    created = True
                entry.master.execute("PING", timeout=2.0, retry_attempts=0)
                entry.refresh_failures = 0
                fresh[addr] = entry
            except Exception:  # noqa: BLE001 — node down or stalled
                if created or entry is None:
                    # construction itself failed (unparseable address, TLS
                    # context error) or never happened: nothing to grace
                    if entry is not None:
                        entry.close()
                    continue
                entry.refresh_failures = getattr(entry, "refresh_failures", 0) + 1
                if entry.refresh_failures < 2:
                    fresh[addr] = entry  # grace period: keep routing to it
                # else: dropped from fresh -> closed as retired below
        # replica discovery per master (REPLICAS command) — still outside
        # lock, single-shot for the same reason.  Subclasses that already
        # know the replica set from their own scan (replicated mode) turn
        # this off instead of paying the round-trip and overwriting it.
        if self._replica_discovery:
            for addr, entry in fresh.items():
                try:
                    reps = entry.master.execute(
                        "REPLICAS", timeout=5.0, retry_attempts=0
                    )
                    rep_addrs = [r.decode() if isinstance(r, bytes) else r for r in reps]
                    if self.config.nat_mapper is not None:
                        # replicas advertise internal addresses too
                        rep_addrs = [self.config.nat_mapper.map(a) for a in rep_addrs]
                    entry.sync_replicas(rep_addrs)
                except Exception:  # noqa: BLE001 — master briefly down
                    pass
        with self._lock:
            if self._closed.is_set():
                # shutdown raced this refresh: do NOT repopulate a closed
                # client — close anything we just opened and bail
                retired = [e for a, e in fresh.items() if a not in self._entries]
                swapped = False
            else:
                retired = [e for a, e in self._entries.items() if a not in fresh]
                self._entries = fresh
                self._slots = [a if a in fresh else None for a in new_slots]
                swapped = True
        for e in retired:
            e.close()
        return swapped

    def _scan_loop(self) -> None:
        while not self._closed.wait(self._scan_interval):
            try:
                self.refresh_topology()
            except Exception:  # noqa: BLE001 — keep scanning
                pass

    def wait_routable(self, timeout: float = 30.0,
                      full_coverage: bool = True) -> bool:
        """Block until the cluster actually serves: every hash slot has a
        live owner in the routing table (with ``full_coverage``) and every
        routed master answers PING.  The barrier callers need after a
        process-level start/restart (cluster/supervisor.py) or a failover
        storm — node processes report READY when their listener binds,
        which is before the topology view reaches them.  Returns False on
        deadline instead of raising (the caller owns the failure story)."""
        deadline = time.monotonic() + timeout
        while True:
            try:
                self.refresh_topology()
                with self._lock:
                    addrs = {a for a in self._slots if a is not None}
                    covered = all(a is not None for a in self._slots)
                    entries = [
                        self._entries[a] for a in addrs if a in self._entries
                    ]
                if addrs and (covered or not full_coverage) \
                        and len(entries) == len(addrs):
                    for e in entries:
                        e.master.execute("PING", timeout=2.0, retry_attempts=0)
                    return True
            except Exception:  # noqa: BLE001 — not routable yet
                pass
            if time.monotonic() >= deadline:
                return False
            time.sleep(0.1)

    def entry_for_slot(self, slot: int) -> ShardEntry:
        with self._lock:
            addr = self._slots[slot]
            if addr is None or addr not in self._entries:
                raise ConnectionError_(f"no entry serves slot {slot}")
            return self._entries[addr]

    def entries(self) -> List[ShardEntry]:
        with self._lock:
            return list(self._entries.values())

    # -- command path (RedisExecutor redirect state machine) ------------------
    # routing decisions live in client/routing.py — the PURE core shared
    # with the async cluster client so the two flavors cannot drift

    _ALL_SHARD = routing.ALL_SHARD

    def _route(self, cmd: str, args: tuple) -> Tuple[Optional[int], bool]:
        return routing.route(cmd, args)

    def execute(self, *cmd_args, timeout: Optional[float] = None) -> Any:
        cmd = str(cmd_args[0]).upper()
        if cmd in self._ALL_SHARD:
            return self._execute_all_shards(cmd, cmd_args, timeout)
        slot, write = self._route(cmd, cmd_args[1:])
        if slot == -1:  # cross-slot DEL/UNLINK: per-shard sub-commands
            return self._execute_split_keys(cmd_args, timeout)
        last: Optional[BaseException] = None
        for attempt in range(self.max_redirects + 1):
            try:
                if slot is None:
                    entries = self.entries()
                    if not entries:
                        raise ConnectionError_("no cluster entries")
                    # rotate per redirect attempt: pinning keyless commands
                    # to entries[0] forever starves them when that one node
                    # is down but not yet pruned from the table
                    entry = entries[attempt % len(entries)]
                    node = entry.master
                    if self.read_mode != READ_MASTER and not write \
                            and routing.replica_readable(cmd, cmd_args[1:]):
                        # keyless FT reads ride the replica plane too
                        # (ISSUE 18): same staleness probe + master
                        # re-serve as keyed replica reads
                        cand = entry.read_node(self.read_mode)
                        if cand is not entry.master:
                            return self._execute_replica_read(
                                entry, cand, cmd_args, timeout
                            )
                else:
                    entry = self.entry_for_slot(slot)
                    if write:
                        node = entry.master
                    else:
                        node = entry.read_node(self.read_mode)
                        if node is not entry.master:
                            return self._execute_replica_read(
                                entry, node, cmd_args, timeout
                            )
                return node.execute(*cmd_args, timeout=timeout)
            except RespError as e:
                msg = str(e)
                if msg.startswith("MOVED "):
                    # MOVED <slot> <host>:<port> — refresh and re-route
                    # (cluster/ClusterConnectionManager topology diff analog)
                    last = e
                    self.refresh_topology()
                    continue
                if msg.startswith("ASK "):
                    # ASK <slot> <host:port> — one-shot redirect into the
                    # migration window; NO topology refresh (the view still
                    # names the draining owner until finalization)
                    try:
                        return self._execute_asking(msg.split()[2], cmd_args, timeout)
                    except RespError as e2:
                        if str(e2).startswith(("MOVED ", "ASK ", "TRYAGAIN")):
                            # stale window (chained reshard / lost view):
                            # feed it back into the redirect loop
                            last = e2
                            self.refresh_topology()
                            continue
                        raise
                    except (ConnectionError, OSError, TimeoutError) as e2:
                        # importing node dropped mid-hop: same transport-retry
                        # rules as the primary path (writes keep at-most-once)
                        if write and isinstance(e2, TimeoutError):
                            raise
                        last = e2
                        self.refresh_topology()
                        time.sleep(min(0.1 * (attempt + 1), 1.0))
                        continue
                if msg.startswith("TRYAGAIN"):
                    # multi-key op spanning a live migration window: neither
                    # node holds every key yet — back off and retry
                    # (RedisExecutor treats TRYAGAIN as a scheduled retry)
                    last = e
                    time.sleep(min(0.05 * (attempt + 1), 0.5))
                    continue
                raise
            except (ConnectionError, OSError, TimeoutError) as e:
                if write and isinstance(e, TimeoutError):
                    # the command may already have been written — re-sending a
                    # non-idempotent write (INCR, OBJCALL put, lock ops) could
                    # double-apply it.  At-most-once for writes, matching the
                    # no-retry-after-write rule NodeClient._with_retry enforces
                    # one layer down.
                    raise
                last = e
                self.refresh_topology()
                time.sleep(min(0.1 * (attempt + 1), 1.0))
                continue
        assert last is not None
        raise last

    def _execute_replica_read(self, entry: ShardEntry, node: NodeClient,
                              cmd_args, timeout) -> Any:
        """Replica-served read under the bounded-staleness contract
        (ISSUE 17).  With a staleness bound configured, the REPLSTATE
        MAXSTALE probe rides the SAME pipelined frame as the read — one
        round trip, one connection — and its reply decides CLIENT-side
        whether the answer is admissible: too stale (or never synced, or a
        reply-shape surprise) and the master re-serves.  Transport failure
        mid-read drains to the master too (reads are idempotent); redirect
        errors re-enter the outer redirect loop like master-served reads."""
        probe = (self.max_staleness_ms is not None
                 or self.max_staleness_offset is not None)
        try:
            if not probe:
                reply = node.execute(*cmd_args, timeout=timeout)
                self.read_stats["replica_reads"] += 1
                return reply
            ms = self.max_staleness_ms
            replies = node.execute_many(
                [("REPLSTATE", "MAXSTALE", int(1 << 30 if ms is None else ms)),
                 tuple(cmd_args)],
                timeout=timeout,
            )
        except (ConnectionError, OSError, TimeoutError):
            self.read_stats["replica_fallbacks"] += 1
            return entry.master.execute(*cmd_args, timeout=timeout)
        state, reply = replies[0], replies[1]
        if isinstance(reply, RespError) and str(reply).startswith(
            ("MOVED ", "ASK ", "TRYAGAIN", "CLUSTERDOWN", "RECOVERING")
        ):
            # fenced / migrating / mid-hand-off slot: NEVER replica-served —
            # the outer redirect loop re-routes exactly as for a master read
            raise reply
        if isinstance(state, RespError) or not self._fresh_enough(entry, state):
            self.read_stats["replica_redirects_stale"] += 1
            return entry.master.execute(*cmd_args, timeout=timeout)
        if isinstance(reply, RespError):
            raise reply
        self.read_stats["replica_reads"] += 1
        return reply

    def _fresh_enough(self, entry: ShardEntry, state) -> bool:
        """Judge one REPLSTATE reply ([role, applied_offset, staleness_ms,
        view_epoch]) against the configured bounds.  A node that answers as
        master (promotion raced the read) is authoritative by definition."""
        try:
            role, offset, stale_ms = state[0], int(state[1]), int(state[2])
        except (TypeError, ValueError, IndexError):
            return False
        role = role.decode() if isinstance(role, (bytes, bytearray)) else str(role)
        if role != "replica":
            return True
        if stale_ms < 0:
            return False  # never synced: always too stale
        if self.max_staleness_ms is not None and stale_ms > self.max_staleness_ms:
            return False
        hw = self._shard_offsets.get(entry.address, 0)
        if self.max_staleness_offset is not None \
                and hw - offset > self.max_staleness_offset:
            return False
        if offset > hw:
            # a replica can only prove an offset its master has cut: reads
            # advance the client's per-shard high-water for the lag bound
            self._shard_offsets[entry.address] = offset
        return True

    def _execute_asking(self, target: str, cmd_args, timeout) -> Any:
        """ASKING + command on ONE connection of the importing node (the
        RedisExecutor ASK path: same connection, no slot-table update)."""
        if self.config.nat_mapper is not None:
            target = self.config.nat_mapper.map(target)  # ASK advertises too
        with self._lock:
            entry = self._entries.get(target)
        transient = None
        try:
            if entry is not None:
                node = entry.master
            else:
                # target not in the current view (fresh master taking its
                # first slots): transient link with the same credentials
                kw = dict(self._node_kw)
                kw.update(ping_interval=0, retry_attempts=0)
                transient = node = NodeClient(target, **kw)
            replies = node.execute_many([("ASKING",), tuple(cmd_args)], timeout=timeout)
            reply = replies[1]
            if isinstance(reply, RespError):
                raise reply
            return reply
        finally:
            if transient is not None:
                transient.close()

    def _execute_all_shards(self, cmd: str, cmd_args, timeout) -> Any:
        merge = self._ALL_SHARD[cmd]
        out: List[Any] = []
        for entry in self.entries():
            reply = entry.master.execute(*cmd_args, timeout=timeout)
            out.append(reply)
        if merge == "concat":
            return [x for r in out for x in (r or [])]
        if merge == "sum":
            return sum(int(r) for r in out)
        return out[0] if out else None

    def _execute_split_keys(self, cmd_args, timeout) -> int:
        """DEL/UNLINK across slots: group keys per owning shard, sum counts
        (the per-entry grouping of RedissonKeys.deleteAsync)."""
        cmd = cmd_args[0]
        groups = routing.group_by_slot(list(cmd_args[1:]))
        total = 0
        for slot, keys in groups.items():
            total += int(self.execute(cmd, *keys, timeout=timeout) or 0)
        return total

    def _group_replies(self, entry: ShardEntry, cmds, timeout) -> List[Any]:
        """One shard group's pipelined replies for execute_many — replica-
        served when EVERY command of the group is replica-readable
        (ISSUE 18 satellite: the read-only legs of FT.MSEARCH /
        execute_many cross-shard fan-outs ride the PR 17 replica plane
        instead of pinning to masters), master-served otherwise.  The
        group's staleness probe rides the SAME frame (one REPLSTATE row
        ahead of the group); a stale verdict or transport failure re-serves
        the WHOLE group from the master (reads are idempotent); per-command
        redirect rows (-MOVED/-ASK/...) surface to the caller exactly as
        master-served rows do, preserving redirect parity."""
        node = None
        if self.read_mode != READ_MASTER and entry.replicas and all(
            routing.replica_readable(str(c[0]), tuple(c[1:])) for c in cmds
        ):
            cand = entry.read_node(self.read_mode)
            if cand is not entry.master:
                node = cand
        if node is None:
            return entry.master.execute_many(cmds, timeout=timeout)
        probe = (self.max_staleness_ms is not None
                 or self.max_staleness_offset is not None)
        try:
            if not probe:
                replies = node.execute_many(cmds, timeout=timeout)
                self.read_stats["replica_reads"] += len(cmds)
                return replies
            ms = self.max_staleness_ms
            replies = node.execute_many(
                [("REPLSTATE", "MAXSTALE",
                  int(1 << 30 if ms is None else ms))]
                + [tuple(c) for c in cmds],
                timeout=timeout,
            )
        except (ConnectionError, OSError, TimeoutError):
            self.read_stats["replica_fallbacks"] += 1
            return entry.master.execute_many(cmds, timeout=timeout)
        state, rest = replies[0], replies[1:]
        if isinstance(state, RespError) or not self._fresh_enough(entry, state):
            self.read_stats["replica_redirects_stale"] += 1
            return entry.master.execute_many(cmds, timeout=timeout)
        self.read_stats["replica_reads"] += len(cmds)
        return rest

    def execute_many(self, commands, timeout: Optional[float] = None):
        """Per-slot grouped pipeline (executeBatchedAsync per-entry grouping,
        CommandAsyncService.java:575-640): one pipelined frame per shard,
        results stitched back in submission order.  Entries are snapshotted
        once; commands whose shard vanished mid-flight fall back to the
        redirect-aware execute()."""
        with self._lock:
            slot_table = list(self._slots)
            entries = dict(self._entries)
        writes: List[bool] = [False] * len(commands)
        results: List[Any] = [None] * len(commands)

        def run_group(addr, idxs):
            entry = entries.get(addr) if addr is not None else next(iter(entries.values()), None)
            try:
                if entry is None:
                    raise ConnectionError_(f"no entry for {addr}")
                replies = self._group_replies(
                    entry, [commands[i] for i in idxs], timeout
                )
            except (ConnectionError, OSError, TimeoutError) as group_err:
                # topology changed under us: redirect-aware per-command path.
                # After a TIMEOUT the frame may already be written server-side,
                # so writes must NOT re-execute (at-most-once): the whole call
                # raises, like the single-command path.  Reads are safe to
                # re-run; their failures also propagate (the pre-existing
                # contract — transport errors raise, only per-command RESP
                # errors come back as data rows).
                if isinstance(group_err, TimeoutError) and any(
                    writes[i] for i in idxs
                ):
                    raise
                replies = [self.execute(*commands[i], timeout=timeout) for i in idxs]
            for i, r in zip(idxs, replies):
                if isinstance(r, RespError) and str(r).startswith(
                    ("MOVED ", "CLUSTERDOWN", "ASK ", "TRYAGAIN")
                ):
                    # pipelined frames return per-command errors as values;
                    # redirects re-route through the redirect-aware execute()
                    # (a migrated slot must not surface as a silent error row)
                    try:
                        r = self.execute(*commands[i], timeout=timeout)
                    except Exception as e:  # noqa: BLE001 — keep the error as data
                        r = e if isinstance(r, RespError) else r
                results[i] = r

        def run_segment(seg: List[int]) -> None:
            groups: Dict[Optional[str], List[int]] = {}
            for i in seg:
                c = commands[i]
                slot, w = self._route(str(c[0]), tuple(c[1:]))
                writes[i] = w
                addr = None if slot in (None, -1) else slot_table[slot]
                groups.setdefault(addr, []).append(i)
            if len(groups) <= 1:
                for addr, idxs in groups.items():
                    run_group(addr, idxs)
            else:
                # shards execute their frames CONCURRENTLY (per-shard order
                # is preserved inside each frame) — a multi-shard batch costs
                # one shard's latency, not the sum (CommandBatchService
                # writes all entries in parallel)
                import concurrent.futures as _cf

                with _cf.ThreadPoolExecutor(max_workers=min(len(groups), 16)) as pool:
                    futs = [
                        pool.submit(run_group, a, idxs) for a, idxs in groups.items()
                    ]
                    for f in futs:
                        f.result()

        # scatter-gather commands (KEYS/DBSIZE/FLUSHALL) act as ordering
        # barriers: everything submitted before one completes before it runs,
        # everything after starts after — submission-order semantics hold
        # even for a (\"SET\", ...), (\"FLUSHALL\",) batch.  Transport errors
        # raise, matching execute().
        segment: List[int] = []
        for i, c in enumerate(commands):
            cmd = str(c[0]).upper()
            if cmd in self._ALL_SHARD:
                if segment:
                    run_segment(segment)
                    segment = []
                results[i] = self._execute_all_shards(cmd, tuple(c), timeout)
            else:
                segment.append(i)
        if segment:
            run_segment(segment)
        return results

    def objcall_many(self, ops, caller=None, timeout: Optional[float] = None):
        """OBJCALLM with per-shard grouping: one frame + one pickle per
        shard, shards concurrent (the executeBatchedAsync discipline applied
        to the generic object wire).  Per-op MOVED/ASK errors from a stale
        view re-route through the single-op redirect-aware objcall.  Ops may
        be 6-tuples whose trailing element is a pickled codec blob (the
        OBJCALL codec-frame contract)."""
        caller = caller or self.caller_id()
        with self._lock:
            slot_table = list(self._slots)
            entries = dict(self._entries)
        ops = [tuple(op) for op in ops]
        groups = routing.group_by_slot_owner(slot_table, [op[1] for op in ops])
        results: List[Any] = [None] * len(ops)

        def reroute_one(i):
            """Single-op redirect-aware fallback, codec preserved."""
            import pickle as _pickle

            f, n, m, a, kw = ops[i][:5]
            codec = _pickle.loads(ops[i][5]) if len(ops[i]) > 5 else None
            return self.objcall(f, n, m, a, kw, caller=caller, codec=codec)

        def run_group(addr, idxs):
            import pickle as _pickle

            from redisson_tpu.client.remote import _unwrap_many

            entry = entries.get(addr) if addr is not None else next(iter(entries.values()), None)
            try:
                if entry is None:
                    raise ConnectionError_(f"no entry for {addr}")
                payload = _pickle.dumps([ops[i] for i in idxs])
                replies = _unwrap_many(
                    entry.master.execute("OBJCALLM", payload, caller, timeout=timeout),
                    self,
                )
            except TimeoutError:
                # The OBJCALLM frame was written and may have EXECUTED
                # server-side; re-running every op through the per-op path
                # would double-apply non-idempotent writes (map puts, counter
                # adds, lock calls).  Same rule as execute()/run_group for
                # write+timeout: raise, let the caller decide.
                raise
            except (ConnectionError, OSError):
                # stale entry / connect refused: the failure happened before
                # the frame was written, so the per-op redirect-aware path
                # is safe for reads AND writes
                replies = []
                for i in idxs:
                    try:
                        replies.append(reroute_one(i))
                    except Exception as e:  # noqa: BLE001 — errors stay as data
                        replies.append(e)
            for i, r in zip(idxs, replies):
                if isinstance(r, RespError) and str(r).startswith(
                    ("MOVED ", "ASK ", "TRYAGAIN", "CLUSTERDOWN")
                ):
                    try:
                        r = reroute_one(i)
                    except Exception as e:  # noqa: BLE001
                        r = e
                results[i] = r

        if len(groups) <= 1:
            for addr, idxs in groups.items():
                run_group(addr, idxs)
        else:
            import concurrent.futures as _cf

            with _cf.ThreadPoolExecutor(max_workers=min(len(groups), 16)) as pool:
                futs = [pool.submit(run_group, a, idxs) for a, idxs in groups.items()]
                for f in futs:
                    f.result()
        return results

    def objcall_many_batch(
        self, ops, atomic: bool = False, timeout: Optional[float] = None
    ):
        """Cluster RemoteBatch flush: per-shard OBJCALLM grouping via
        objcall_many; atomic groups must colocate on ONE shard (the
        reference's cluster rule for REDIS_*_ATOMIC modes — use
        {hashtags}), shipped as a single OBJCALLMA frame to that owner."""
        wire_ops = [self._normalize_batch_op(op) for op in ops]
        if not atomic:
            return self.objcall_many(wire_ops, timeout=timeout)
        slots = {
            calc_slot(str(op[1]).encode()) for op in wire_ops if op[1]
        }
        if len(slots) > 1:
            raise RespError(
                "CROSSSLOT atomic batch spans multiple slots; use a {hashtag} "
                "to colocate every object of an atomic batch"
            )
        from redisson_tpu.client.remote import _unwrap_many
        import pickle as _pickle

        slot = slots.pop() if slots else None
        payload = _pickle.dumps(wire_ops)
        last: Optional[BaseException] = None
        for attempt in range(self.max_redirects + 1):
            entry = self.entry_for_slot(slot) if slot is not None else next(
                iter(self.entries()), None
            )
            if entry is None:
                raise ConnectionError_("no cluster entries")
            replies = _unwrap_many(
                entry.master.execute("OBJCALLMA", payload, self.caller_id(), timeout=timeout),
                self,
            )
            # a stale view bounces EVERY op with a routing error before any
            # applies (single-slot frame): refresh + full resend is safe.
            # Mixed results (some applied) must NOT resend — return as-is.
            routing_errs = [
                r for r in replies
                if isinstance(r, RespError)
                and str(r).startswith(("MOVED ", "ASK ", "TRYAGAIN", "CLUSTERDOWN"))
            ]
            if routing_errs and len(routing_errs) == len(replies):
                last = routing_errs[0]
                self.refresh_topology()
                time.sleep(min(0.05 * (attempt + 1), 0.5))
                continue
            return replies
        assert last is not None
        raise last

    def tx_groups(self, names):
        """Transaction commit grouping: one TXEXEC frame per slot owner
        (the per-MasterSlaveEntry grouping of the reference's commit batch,
        CommandBatchService executeBatchedAsync)."""
        with self._lock:
            slot_table = list(self._slots)
        groups: Dict[Optional[str], List[str]] = {}
        for n in names:
            slot = calc_slot(str(n).encode())
            groups.setdefault(slot_table[slot], []).append(n)
        return groups

    def txexec(
        self, group_key, versions, ops, timeout: Optional[float] = None
    ):
        """One commit frame straight to the owning master.  MOVED/ASK/
        TRYAGAIN raise to the caller (RemoteTransaction regroups after a
        topology refresh and retries — TXEXEC's whole-frame routing precheck
        guarantees a bounced frame applied nothing)."""
        import pickle as _pickle

        from redisson_tpu.client.remote import _unwrap_many

        entry = self._entries.get(group_key) if group_key is not None else None
        if entry is None:
            entry = next(iter(self.entries()), None)
        if entry is None:
            raise ConnectionError_("no cluster entries")
        reply = entry.master.execute(
            "TXEXEC", _pickle.dumps(versions), _pickle.dumps(ops),
            self.caller_id(), timeout=timeout,
        )
        return _unwrap_many(reply, self)

    def sync_replication(self, names, timeout: Optional[float] = None) -> None:
        """REPLFLUSH on every shard that owns one of `names` (syncSlaves)."""
        with self._lock:
            slot_table = list(self._slots)
            entries = dict(self._entries)
        addrs = {
            slot_table[calc_slot(str(n).encode())] for n in names if n
        }
        for addr in addrs:
            entry = entries.get(addr)
            if entry is not None:
                entry.master.execute("REPLFLUSH", timeout=timeout)

    def pubsub_for(self, name: str):
        """Channel subscriptions ride the shard that owns the channel's slot
        (SSUBSCRIBE semantics — RedissonShardedTopic analog)."""
        entry = self.entry_for_slot(calc_slot(name.encode()))
        return entry.master.pubsub()

    def publish_for(self, routing_name: str, channel, payload) -> int:
        """Publish on the exact node pubsub_for(routing_name) subscribed on —
        server pubsub hubs are node-local, so the publish and the
        subscription MUST land on the same master or fan-out silently drops
        (topic messages, local-cache invalidations)."""
        entry = self.entry_for_slot(calc_slot(routing_name.encode()))
        return int(entry.master.execute("PUBLISH", channel, payload) or 0)

    # -- object surface: inherited from RemoteSurface (same handle classes,
    #    routed through execute()/objcall()/pubsub_for() above) --------------

    def ping_all(self) -> Dict[str, bool]:
        out = {}
        for e in self.entries():
            try:
                out[e.address] = e.master.execute("PING") in (b"PONG", "PONG")
            except Exception:  # noqa: BLE001
                out[e.address] = False
        return out

    def shutdown(self) -> None:
        self._closed.set()
        # cancel element subscriptions FIRST (their daemon loops would
        # otherwise retry the closed cluster forever — same rule as the
        # single-node facade's shutdown)
        svc = self.__dict__.get("_elements_service")
        if svc is not None:
            svc.shutdown()
        plane = self.__dict__.get("tracking")
        if plane is not None:
            plane.close()
        if self._dns is not None:
            self._dns.stop()
        with self._lock:
            for e in self._entries.values():
                e.close()
            self._entries.clear()
