"""RedissonTpu: the entry facade (Redisson.create analog).

Parity target: ``org/redisson/Redisson.java:47-111`` — one client object
constructed from a Config, exposing ~90 `getXxx(name[, codec])` factory
methods over a shared execution stack (connection manager + command executor
in the reference; the embedded Engine here, or a remote connection in
client/remote mode).

Object handles are cheap and stateless — create them freely, exactly like the
reference (Redisson.java factory methods allocate a thin wrapper per call).
"""
from __future__ import annotations

from typing import Optional

from redisson_tpu.client.codec import Codec
from redisson_tpu.core.batch import Batch
from redisson_tpu.core.engine import Engine


class RedissonTpu:
    def __init__(self, engine: Engine):
        self._engine = engine

    @classmethod
    def create(cls, config=None) -> "RedissonTpu":
        """Embedded-mode client: data plane lives in this process on the
        local accelerator (Redisson.create(Config) analog)."""
        return cls(Engine(config))

    @property
    def engine(self) -> Engine:
        return self._engine

    # -- sketch / bit objects (the TPU-accelerated data plane) --------------

    def get_bloom_filter(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.bloom import BloomFilter

        return BloomFilter(self._engine, name, codec)

    def get_bloom_filter_array(self, name: str):
        from redisson_tpu.client.objects.bloom_array import BloomFilterArray

        return BloomFilterArray(self._engine, name)

    def get_sharded_bloom_filter_array(self, name: str):
        """Bloom bank whose bit plane is sharded over the device mesh
        (parallel/manager.py; SURVEY.md §5.7 capability jump)."""
        from redisson_tpu.client.objects.sharded import ShardedBloomFilterArray

        return ShardedBloomFilterArray(self._engine, name)

    def get_sharded_hll_array(self, name: str):
        """HLL bank whose tenant axis is sharded over the device mesh."""
        from redisson_tpu.client.objects.sharded import ShardedHllArray

        return ShardedHllArray(self._engine, name)

    def get_sharded_bit_set(self, name: str):
        """ONE logical bitset column-sharded over the device mesh."""
        from redisson_tpu.client.objects.sharded import ShardedBitSet

        return ShardedBitSet(self._engine, name)

    def get_hyper_log_log(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.hyperloglog import HyperLogLog

        return HyperLogLog(self._engine, name, codec)

    def get_hyper_log_log_array(self, name: str):
        from redisson_tpu.client.objects.hll_array import HyperLogLogArray

        return HyperLogLogArray(self._engine, name)

    def get_bit_set(self, name: str):
        from redisson_tpu.client.objects.bitset import BitSet

        return BitSet(self._engine, name)

    # -- value / counter objects -------------------------------------------

    def get_bucket(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.bucket import Bucket

        return Bucket(self._engine, name, codec)

    def get_buckets(self, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.bucket import Buckets

        return Buckets(self._engine, codec)

    def get_atomic_long(self, name: str):
        from redisson_tpu.client.objects.bucket import AtomicLong

        return AtomicLong(self._engine, name)

    def get_atomic_double(self, name: str):
        from redisson_tpu.client.objects.bucket import AtomicDouble

        return AtomicDouble(self._engine, name)

    def get_id_generator(self, name: str):
        from redisson_tpu.client.objects.bucket import IdGenerator

        return IdGenerator(self._engine, name)

    # -- maps / collections -------------------------------------------------

    def get_map(self, name: str, codec: Optional[Codec] = None, options=None):
        from redisson_tpu.client.objects.map import Map

        return Map(self._engine, name, codec, options)

    def get_map_cache(self, name: str, codec: Optional[Codec] = None, options=None):
        from redisson_tpu.client.objects.map import MapCache

        mc = MapCache(self._engine, name, codec, options)
        self._engine.eviction.schedule_for_record(self._engine, mc._name, mc.reap_expired)
        return mc

    def get_local_cached_map(self, name: str, codec: Optional[Codec] = None, options=None):
        from redisson_tpu.client.objects.localcache import LocalCachedMap

        return LocalCachedMap(self._engine, name, codec, options)

    def get_long_adder(self, name: str):
        from redisson_tpu.client.objects.adder import LongAdder

        return LongAdder(self._engine, name)

    def get_double_adder(self, name: str):
        from redisson_tpu.client.objects.adder import DoubleAdder

        return DoubleAdder(self._engine, name)

    def get_cache_manager(self):
        from redisson_tpu.client.jcache import CacheManager

        return CacheManager(self._engine)

    def get_set(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.set import Set

        return Set(self._engine, name, codec)

    def get_set_cache(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.set import SetCache

        sc = SetCache(self._engine, name, codec)
        self._engine.eviction.schedule_for_record(self._engine, sc._name, sc.reap_expired)
        return sc

    def get_sorted_set(self, name: str, codec: Optional[Codec] = None, key=None):
        from redisson_tpu.client.objects.set import SortedSet

        return SortedSet(self._engine, name, codec, key)

    def get_lex_sorted_set(self, name: str):
        from redisson_tpu.client.objects.set import LexSortedSet

        return LexSortedSet(self._engine, name)

    def get_scored_sorted_set(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.scoredsortedset import ScoredSortedSet

        return ScoredSortedSet(self._engine, name, codec)

    def get_list(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.list import RList

        return RList(self._engine, name, codec)

    def get_list_multimap(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.multimap import ListMultimap

        return ListMultimap(self._engine, name, codec)

    def get_set_multimap(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.multimap import SetMultimap

        return SetMultimap(self._engine, name, codec)

    def get_list_multimap_cache(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.multimap import ListMultimapCache

        mm = ListMultimapCache(self._engine, name, codec)
        self._engine.eviction.schedule_for_record(self._engine, mm._name, mm.reap_expired)
        return mm

    def get_set_multimap_cache(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.multimap import SetMultimapCache

        mm = SetMultimapCache(self._engine, name, codec)
        self._engine.eviction.schedule_for_record(self._engine, mm._name, mm.reap_expired)
        return mm

    # -- queues -------------------------------------------------------------

    def get_queue(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import Queue

        return Queue(self._engine, name, codec)

    def get_deque(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import Deque

        return Deque(self._engine, name, codec)

    def get_blocking_queue(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import BlockingQueue

        return BlockingQueue(self._engine, name, codec)

    def get_blocking_deque(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import BlockingDeque

        return BlockingDeque(self._engine, name, codec)

    def get_bounded_blocking_queue(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import BoundedBlockingQueue

        return BoundedBlockingQueue(self._engine, name, codec)

    def get_priority_queue(self, name: str, codec: Optional[Codec] = None, key=None):
        from redisson_tpu.client.objects.queue import PriorityQueue

        return PriorityQueue(self._engine, name, codec, key)

    def get_priority_deque(self, name: str, codec: Optional[Codec] = None, key=None):
        from redisson_tpu.client.objects.queue import PriorityDeque

        return PriorityDeque(self._engine, name, codec, key)

    def get_priority_blocking_queue(self, name: str, codec: Optional[Codec] = None, key=None):
        from redisson_tpu.client.objects.queue import PriorityBlockingQueue

        return PriorityBlockingQueue(self._engine, name, codec, key)

    def get_priority_blocking_deque(self, name: str, codec: Optional[Codec] = None, key=None):
        from redisson_tpu.client.objects.queue import PriorityBlockingDeque

        return PriorityBlockingDeque(self._engine, name, codec, key)

    def get_ring_buffer(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import RingBuffer

        return RingBuffer(self._engine, name, codec)

    def get_delayed_queue(self, destination_queue) -> "object":
        from redisson_tpu.client.objects.queue import DelayedQueue

        return DelayedQueue(
            self._engine,
            f"redisson_delay_queue:{{{destination_queue.name}}}",
            destination_queue._codec,
            destination_queue,
        )

    def get_transfer_queue(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.queue import TransferQueue

        return TransferQueue(self._engine, name, codec)

    # -- synchronizers ------------------------------------------------------

    def get_lock(self, name: str):
        from redisson_tpu.client.objects.lock import Lock

        return Lock(self._engine, name)

    def get_fair_lock(self, name: str):
        from redisson_tpu.client.objects.lock import FairLock

        return FairLock(self._engine, name)

    def get_spin_lock(self, name: str):
        from redisson_tpu.client.objects.lock import SpinLock

        return SpinLock(self._engine, name)

    def get_fenced_lock(self, name: str):
        from redisson_tpu.client.objects.lock import FencedLock

        return FencedLock(self._engine, name)

    def get_read_write_lock(self, name: str):
        from redisson_tpu.client.objects.lock import ReadWriteLock

        return ReadWriteLock(self._engine, name)

    def get_multi_lock(self, *locks):
        from redisson_tpu.client.objects.lock import MultiLock

        return MultiLock(*locks)

    def get_red_lock(self, *locks):
        from redisson_tpu.client.objects.lock import RedLock

        return RedLock(*locks)

    def get_semaphore(self, name: str):
        from redisson_tpu.client.objects.semaphore import Semaphore

        return Semaphore(self._engine, name)

    def get_permit_expirable_semaphore(self, name: str):
        from redisson_tpu.client.objects.semaphore import PermitExpirableSemaphore

        return PermitExpirableSemaphore(self._engine, name)

    def get_count_down_latch(self, name: str):
        from redisson_tpu.client.objects.semaphore import CountDownLatch

        return CountDownLatch(self._engine, name)

    def get_rate_limiter(self, name: str):
        from redisson_tpu.client.objects.semaphore import RateLimiter

        return RateLimiter(self._engine, name)

    # -- messaging ----------------------------------------------------------

    def get_topic(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.topic import Topic

        return Topic(self._engine, name, codec)

    def get_pattern_topic(self, pattern: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.topic import PatternTopic

        return PatternTopic(self._engine, pattern, codec)

    def get_sharded_topic(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.topic import ShardedTopic

        return ShardedTopic(self._engine, name, codec)

    def get_reliable_topic(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.topic import ReliableTopic

        return ReliableTopic(self._engine, name, codec)

    def get_stream(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.stream import Stream

        return Stream(self._engine, name, codec)

    # -- specialized --------------------------------------------------------

    def get_time_series(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.timeseries import TimeSeries

        return TimeSeries(self._engine, name, codec)

    def get_geo(self, name: str, codec: Optional[Codec] = None):
        from redisson_tpu.client.objects.geo import Geo

        return Geo(self._engine, name, codec)

    def get_binary_stream(self, name: str):
        from redisson_tpu.client.objects.binarystream import BinaryStream

        return BinaryStream(self._engine, name)

    def get_json_bucket(self, name: str):
        from redisson_tpu.client.objects.binarystream import JsonBucket

        return JsonBucket(self._engine, name)

    # -- batching (RBatch) --------------------------------------------------

    def create_batch(self, skip_result: bool = False, atomic: bool = False) -> Batch:
        return Batch(self._engine, skip_result=skip_result, atomic=atomic)

    # -- distributed services -----------------------------------------------

    def get_executor_service(self, name: str = "redisson_executor"):
        from redisson_tpu.services.executor import ExecutorService

        return ExecutorService(self._engine, name)

    def get_elements_subscribe_service(self):
        """ElementsSubscribeService analog (embedded flavor).  setdefault
        keeps the lazy init race-safe (one shared service instance)."""
        from redisson_tpu.services.elements import ElementsSubscribeService

        return self.__dict__.setdefault(
            "_elements_service", ElementsSubscribeService(self)
        )

    def get_scheduled_executor_service(self, name: str = "redisson_scheduler"):
        from redisson_tpu.services.executor import ScheduledExecutorService

        return ScheduledExecutorService(self._engine, name)

    def get_remote_service(self, name: str = "redisson_rs"):
        from redisson_tpu.services.remote import RemoteService

        return RemoteService(self._engine, name)

    def create_transaction(self, timeout: Optional[float] = None, options=None):
        """RedissonClient.createTransaction(TransactionOptions) analog; the
        bare `timeout` form is kept for back-compat."""
        from redisson_tpu.services.transactions import EmbeddedTransaction

        return EmbeddedTransaction(self._engine, timeout, options)

    def get_live_object_service(self):
        from redisson_tpu.services.liveobject import LiveObjectService

        return LiveObjectService(self)

    def get_map_reduce(self, mapper, reducer, collator=None, workers: int = 4, executor=None):
        from redisson_tpu.services.mapreduce import MapReduce

        return MapReduce(self._engine, mapper, reducer, collator, workers, executor)

    # -- keyspace admin (RKeys) --------------------------------------------

    def get_script(self):
        # engine-scoped so the sha cache survives across handles (the
        # reference caches shas per ServiceManager, not per RScript)
        from redisson_tpu.services.script import ScriptService

        return self._engine.service("script", lambda: ScriptService(self._engine))

    def get_function(self):
        from redisson_tpu.services.script import FunctionService

        return self._engine.service("function", lambda: FunctionService(self._engine))

    def get_search(self):
        from redisson_tpu.services.search import SearchService

        return self._engine.service("search", lambda: SearchService(self._engine))

    def get_nodes_group(self):
        from redisson_tpu.client.nodes import NodesGroup

        return NodesGroup.embedded(self._engine)

    def get_keys(self):
        from redisson_tpu.client.objects.keys import Keys

        return Keys(self._engine)

    # -- lifecycle ----------------------------------------------------------

    def shutdown(self) -> None:
        svc = getattr(self, "_elements_service", None)
        if svc is not None:
            svc.shutdown()
        self._engine.shutdown()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.shutdown()
        return False
