"""Synchronizer tests (RedissonLockTest / RedissonSemaphoreTest /
RedissonCountDownLatchTest / RedissonRateLimiterTest analogs), including
cross-thread contention like BaseConcurrentTest fan-outs."""
import threading
import time

import pytest

import redisson_tpu


@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


class TestLock:
    def test_reentrancy(self, client):
        lk = client.get_lock("l")
        lk.lock()
        lk.lock()
        assert lk.get_hold_count() == 2
        assert lk.is_held_by_current_thread()
        lk.unlock()
        assert lk.is_locked()
        lk.unlock()
        assert not lk.is_locked()

    def test_unlock_foreign_raises(self, client):
        lk = client.get_lock("l")
        lk.lock()
        err = []

        def alien():
            try:
                lk.unlock()
            except RuntimeError as e:
                err.append(e)

        t = threading.Thread(target=alien)
        t.start()
        t.join()
        assert err
        lk.unlock()

    def test_contention_handoff(self, client):
        lk = client.get_lock("l")
        order = []

        def worker(i):
            lk.lock()
            order.append(i)
            time.sleep(0.01)
            lk.unlock()

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(5.0)
        assert sorted(order) == [0, 1, 2, 3, 4]

    def test_try_lock_timeout(self, client):
        lk = client.get_lock("l")
        lk.lock()
        got = []

        def other():
            got.append(lk.try_lock(0.1))

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert got == [False]
        lk.unlock()

    def test_lease_expiry_allows_steal(self, client):
        lk = client.get_lock("l")
        lk.lock(lease_time=0.05)  # explicit short lease, no watchdog
        time.sleep(0.08)
        got = []

        def other():
            got.append(lk.try_lock(0.0))
            if got[0]:
                lk.unlock()

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert got == [True]

    def test_context_manager(self, client):
        with client.get_lock("l") as lk:
            assert lk.is_locked()
        assert not client.get_lock("l").is_locked()

    def test_force_unlock(self, client):
        lk = client.get_lock("l")
        lk.lock()
        assert lk.force_unlock()
        assert not lk.is_locked()
        assert not lk.force_unlock()


class TestSpecialLocks:
    def test_fenced_tokens_monotonic(self, client):
        fl = client.get_fenced_lock("f")
        t1 = fl.lock_and_get_token()
        fl.unlock()
        t2 = fl.lock_and_get_token()
        fl.unlock()
        assert t2 > t1

    def test_spin_lock(self, client):
        sl = client.get_spin_lock("s")
        sl.lock()
        assert sl.is_locked()
        assert sl.try_lock(0.0)  # reentrant from same thread
        sl.unlock()
        got = []

        def other():
            got.append(sl.try_lock(0.05))

        t = threading.Thread(target=other)
        t.start()
        t.join()
        assert got == [False]  # still held once by this thread
        sl.unlock()
        t2 = threading.Thread(target=lambda: got.append(sl.try_lock(0.5)))
        t2.start()
        t2.join()
        assert got[-1] is True

    def test_fair_lock_fifo(self, client):
        fl = client.get_fair_lock("fair")
        fl.lock()
        order = []
        threads = []

        def worker(i):
            fl.lock()
            order.append(i)
            fl.unlock()

        for i in range(4):
            t = threading.Thread(target=worker, args=(i,))
            t.start()
            threads.append(t)
            time.sleep(0.05)  # enqueue deterministically
        fl.unlock()
        for t in threads:
            t.join(5.0)
        assert order == [0, 1, 2, 3]  # FIFO grant order

    def test_read_write(self, client):
        rw = client.get_read_write_lock("rw")
        r1, r2, w = rw.read_lock(), rw.read_lock(), rw.write_lock()
        assert r1.try_lock(0.0)
        assert r2.try_lock(0.0)  # shared readers
        blocked = []

        def writer():
            blocked.append(w.try_lock(0.05))

        t = threading.Thread(target=writer)
        t.start()
        t.join()
        assert blocked == [False]
        r1.unlock()
        r2.unlock()
        assert rw.write_lock().try_lock(0.5)

    def test_write_then_read_same_thread(self, client):
        rw = client.get_read_write_lock("rw")
        w = rw.write_lock()
        w.lock()
        r = rw.read_lock()
        assert r.try_lock(0.0)  # downgrade allowed
        r.unlock()
        w.unlock()

    def test_multilock(self, client):
        l1, l2 = client.get_lock("m1"), client.get_lock("m2")
        ml = client.get_multi_lock(l1, l2)
        assert ml.try_lock(1.0)
        assert l1.is_locked() and l2.is_locked()
        ml.unlock()
        assert not l1.is_locked() and not l2.is_locked()

    def test_multilock_all_or_nothing(self, client):
        l1, l2 = client.get_lock("m1"), client.get_lock("m2")
        holder_release = threading.Event()

        def holder():
            l2.lock()
            holder_release.wait(3.0)
            l2.unlock()

        t = threading.Thread(target=holder)
        t.start()
        time.sleep(0.05)
        ml = client.get_multi_lock(l1, l2)
        assert not ml.try_lock(0.2)
        assert not l1.is_locked()  # rolled back
        holder_release.set()
        t.join()


class TestSemaphores:
    def test_semaphore(self, client):
        s = client.get_semaphore("s")
        assert s.try_set_permits(2)
        assert not s.try_set_permits(5)
        assert s.try_acquire()
        assert s.try_acquire()
        assert not s.try_acquire()
        s.release()
        assert s.available_permits() == 1
        assert s.drain_permits() == 1
        assert s.available_permits() == 0

    def test_semaphore_blocking(self, client):
        s = client.get_semaphore("s")
        s.try_set_permits(1)
        s.acquire()
        got = []

        def waiter():
            got.append(s.try_acquire(wait_time=2.0))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        s.release()
        t.join(3.0)
        assert got == [True]

    def test_permit_expirable(self, client):
        ps = client.get_permit_expirable_semaphore("ps")
        ps.try_set_permits(1)
        pid = ps.try_acquire()
        assert pid is not None
        assert ps.try_acquire() is None
        assert ps.release(pid)
        assert not ps.release(pid)  # double release
        pid2 = ps.try_acquire(lease_time=0.05)
        time.sleep(0.08)
        assert ps.available_permits() == 1  # lease expired back to pool
        assert not ps.release(pid2)
        assert ps.update_lease_time(pid2, 10.0) is False

    def test_count_down_latch(self, client):
        latch = client.get_count_down_latch("cdl")
        assert latch.try_set_count(2)
        assert not latch.try_set_count(3)
        done = []

        def waiter():
            done.append(latch.await_(3.0))

        t = threading.Thread(target=waiter)
        t.start()
        latch.count_down()
        assert latch.get_count() == 1
        latch.count_down()
        t.join(3.0)
        assert done == [True]
        assert latch.await_(0.0)

    def test_rate_limiter(self, client):
        rl = client.get_rate_limiter("rl")
        assert rl.try_set_rate(rl.OVERALL, 3, 0.2)
        assert not rl.try_set_rate(rl.OVERALL, 10, 1.0)
        assert rl.try_acquire()
        assert rl.try_acquire(2)
        assert not rl.try_acquire()  # exhausted
        assert rl.available_permits() == 0
        time.sleep(0.25)
        assert rl.try_acquire()  # window slid

    def test_rate_limiter_waits(self, client):
        rl = client.get_rate_limiter("rl")
        rl.try_set_rate(rl.OVERALL, 1, 0.1)
        assert rl.try_acquire()
        t0 = time.time()
        assert rl.try_acquire(timeout=1.0)
        assert time.time() - t0 >= 0.08

    def test_rate_limiter_validation(self, client):
        rl = client.get_rate_limiter("rl")
        with pytest.raises(RuntimeError):
            rl.try_acquire()
        rl.try_set_rate(rl.OVERALL, 2, 1.0)
        with pytest.raises(ValueError):
            rl.try_acquire(5)
        assert rl.get_config()["rate"] == 2


class TestTopics:
    def test_topic_pubsub(self, client):
        topic = client.get_topic("t")
        got = []
        lid = topic.add_listener(lambda ch, msg: got.append((ch, msg)))
        assert topic.count_subscribers() == 1
        n = topic.publish({"hello": "world"})
        assert n == 1
        assert got == [("t", {"hello": "world"})]
        topic.remove_listener(lid)
        assert topic.publish("x") == 0

    def test_pattern_topic(self, client):
        pt = client.get_pattern_topic("news.*")
        got = []
        pt.add_listener(lambda ch, msg: got.append((ch, msg)))
        client.get_topic("news.sports").publish("goal")
        client.get_topic("weather").publish("rain")
        assert got == [("news.sports", "goal")]

    def test_sharded_topic(self, client):
        st = client.get_sharded_topic("st")
        got = []
        st.add_listener(lambda ch, msg: got.append(msg))
        st.publish(1)
        assert got == [1]
        assert 0 <= st.slot() < 16384

    def test_reliable_topic(self, client):
        rt = client.get_reliable_topic("rt")
        s1 = rt.add_subscriber()
        rt.publish("m1")
        rt.publish("m2")
        s2 = rt.add_subscriber()  # starts at tail
        rt.publish("m3")
        assert rt.poll(s1, max_messages=10) == ["m1", "m2", "m3"]
        assert rt.poll(s2, max_messages=10) == ["m3"]
        # all consumed -> trimmed
        assert rt.size() == 0
        rt.remove_subscriber(s1)
        rt.remove_subscriber(s2)

    def test_reliable_topic_blocking_poll(self, client):
        rt = client.get_reliable_topic("rt")
        sid = rt.add_subscriber()
        got = []

        def sub():
            got.extend(rt.poll(sid, timeout=2.0))

        t = threading.Thread(target=sub)
        t.start()
        time.sleep(0.05)
        rt.publish("wake")
        t.join(3.0)
        assert got == ["wake"]


def test_remote_lock_push_wakeup_handoff_latency():
    """Contended remote-lock handoff parks on the unlock-channel push, not
    the poll loop: release-to-acquire must land well under the old poll
    backoff (VERDICT r2 #8 bar: <10ms on the hermetic backend)."""
    import threading
    import time

    from redisson_tpu.client.remote import RemoteRedisson
    from redisson_tpu.server.server import ServerThread

    with ServerThread(port=0) as st:
        holder = RemoteRedisson(st.address, timeout=30.0)
        waiter = RemoteRedisson(st.address, timeout=30.0)
        try:
            lock_h = holder.get_lock("push:lock")
            lock_w = waiter.get_lock("push:lock")
            lock_h.lock()
            acquired_at = []
            started = threading.Event()

            def contend():
                started.set()
                lock_w.lock()
                acquired_at.append(time.perf_counter())
                lock_w.unlock()

            t = threading.Thread(target=contend)
            t.start()
            started.wait(5)
            time.sleep(0.6)  # the waiter is parked (past any initial retry)
            released_at = time.perf_counter()
            lock_h.unlock()
            t.join(10)
            assert acquired_at, "waiter never acquired"
            handoff_ms = (acquired_at[0] - released_at) * 1e3
            assert handoff_ms < 50, f"handoff took {handoff_ms:.1f}ms (push not working)"
            # typical push handoff is ~1-5ms; 50ms bound keeps CI stable while
            # still far below the 250ms safety-poll that polling would cost
        finally:
            holder.shutdown()
            waiter.shutdown()


def test_remote_lock_handoff_without_pubsub_still_works():
    """Safety net: even if the push never arrives (e.g. subscribe raced the
    publish), the bounded poll completes the acquisition."""
    import threading
    import time

    from redisson_tpu.client.remote import RemoteLock, RemoteRedisson
    from redisson_tpu.server.server import ServerThread

    with ServerThread(port=0) as st:
        holder = RemoteRedisson(st.address, timeout=30.0)
        waiter = RemoteRedisson(st.address, timeout=30.0)
        try:
            lock_h = holder.get_lock("poll:lock")
            lock_w = waiter.get_lock("poll:lock")
            # break the push path for the waiter
            class _DeafPark(RemoteLock._UnlockPark):
                def __init__(self, client, name):
                    self._event = threading.Event()
                    self._pubsub = None
                    self._channel = ""
                    self._listener = lambda *_: None

            object.__setattr__(lock_w, "_UnlockPark", _DeafPark)
            lock_h.lock()
            done = []

            def contend():
                lock_w.lock()
                done.append(True)
                lock_w.unlock()

            t = threading.Thread(target=contend)
            t.start()
            time.sleep(0.3)
            lock_h.unlock()
            t.join(10)
            assert done, "poll safety net failed"
        finally:
            holder.shutdown()
            waiter.shutdown()
