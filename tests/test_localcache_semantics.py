"""LocalCachedMap behavioral depth, ported from RedissonLocalCachedMapTest
(53 @Test) — VERDICT r3 #7, round-4 batch 2: sync strategies, near-cache
bounds/TTL, cross-handle invalidation, embedded AND wire handles.
"""
import time

import pytest

import redisson_tpu
from redisson_tpu.client.objects.localcache import (
    EvictionPolicy,
    LocalCachedMapOptions,
    SyncStrategy,
)
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def server():
    with ServerThread(port=0) as st:
        yield st


@pytest.fixture(scope="module")
def remote_client(server):
    c = RemoteRedisson(server.address, timeout=60.0)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def remote_client2(server):
    c = RemoteRedisson(server.address, timeout=60.0)
    yield c
    c.shutdown()


@pytest.fixture(scope="module")
def embedded_client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


def wait_until(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.02)
    return pred()


def nm(tag):
    return f"lcsem-{tag}-{time.time_ns()}"


class TestNearCacheBasics:
    def test_read_populates_cache(self, embedded_client):
        name = nm("pop")
        writer = embedded_client.get_map(name)
        writer.put("k", "v")
        lcm = embedded_client.get_local_cached_map(name)
        assert lcm.get("k") == "v"       # miss -> fetch + populate
        assert lcm.get("k") == "v"       # hit
        assert lcm.hits >= 1 and lcm.misses >= 1
        assert lcm.cached_size() >= 1

    def test_own_writes_cached(self, embedded_client):
        lcm = embedded_client.get_local_cached_map(nm("own"))
        lcm.put("k", 1)
        hits0 = lcm.hits
        assert lcm.get("k") == 1
        assert lcm.hits == hits0 + 1  # served from the near cache

    def test_clear_local_cache_only(self, embedded_client):
        lcm = embedded_client.get_local_cached_map(nm("clr"))
        lcm.put("k", 1)
        lcm.clear_local_cache()
        assert lcm.cached_size() == 0
        assert lcm.get("k") == 1  # backing map untouched

    def test_pre_load_cache(self, embedded_client):
        name = nm("pre")
        writer = embedded_client.get_map(name)
        writer.put_all({f"k{i}": i for i in range(5)})
        lcm = embedded_client.get_local_cached_map(name)
        lcm.pre_load_cache()
        assert lcm.cached_size() == 5

    def test_destroy_detaches(self, embedded_client):
        lcm = embedded_client.get_local_cached_map(nm("dst"))
        lcm.put("k", 1)
        lcm.destroy()
        # backing data survives destroy (it detaches the near cache only)
        assert embedded_client.get_map(lcm.name if hasattr(lcm, "name") else lcm._name).get("k") == 1


class TestInvalidation:
    def test_embedded_peer_invalidation(self, embedded_client):
        name = nm("inv")
        a = embedded_client.get_local_cached_map(name)
        b = embedded_client.get_local_cached_map(name)
        a.put("k", 1)
        assert b.get("k") == 1  # cached in b
        a.put("k", 2)
        assert wait_until(lambda: b.get("k") == 2)

    def test_remove_invalidates_peers(self, embedded_client):
        name = nm("invr")
        a = embedded_client.get_local_cached_map(name)
        b = embedded_client.get_local_cached_map(name)
        a.put("k", 1)
        assert b.get("k") == 1
        a.remove("k")
        assert wait_until(lambda: b.get("k") is None)

    def test_clear_invalidates_peers(self, embedded_client):
        name = nm("invc")
        a = embedded_client.get_local_cached_map(name)
        b = embedded_client.get_local_cached_map(name)
        a.put_all({"x": 1, "y": 2})
        assert b.get("x") == 1
        a.clear()
        assert wait_until(lambda: b.get("x") is None and b.get("y") is None)

    def test_update_strategy_pushes_values(self, embedded_client):
        name = nm("upd")
        opts = LocalCachedMapOptions(sync_strategy=SyncStrategy.UPDATE)
        a = embedded_client.get_local_cached_map(name, options=opts)
        b = embedded_client.get_local_cached_map(name, options=opts)
        a.put("k", 1)
        assert wait_until(lambda: b.get("k") == 1)
        # the UPDATE message delivered the value: b's read was a cache HIT
        hits0 = b.hits
        b.get("k")
        assert b.hits > hits0

    def test_none_strategy_keeps_stale(self, embedded_client):
        name = nm("none")
        opts = LocalCachedMapOptions(sync_strategy=SyncStrategy.NONE)
        a = embedded_client.get_local_cached_map(name, options=opts)
        b = embedded_client.get_local_cached_map(name, options=opts)
        a.put("k", 1)
        assert b.get("k") == 1  # cached
        a.put("k", 2)
        time.sleep(0.3)
        assert b.get("k") == 1  # stale by contract (NONE strategy)
        b.clear_local_cache()
        assert b.get("k") == 2


class TestWireHandles:
    def test_cross_client_invalidation(self, remote_client, remote_client2):
        name = nm("wire")
        a = remote_client.get_local_cached_map(name)
        b = remote_client2.get_local_cached_map(name)
        a.put("k", 1)
        assert wait_until(lambda: b.get("k") == 1)
        a.put("k", 2)
        assert wait_until(lambda: b.get("k") == 2)
        a.fast_remove("k")
        assert wait_until(lambda: b.get("k") is None)

    def test_wire_and_objcall_mutations_agree(self, remote_client, remote_client2):
        """A plain-map OBJCALL mutation from another client must invalidate
        wire near caches (the server-side handle broadcasts)."""
        name = nm("ww")
        lcm = remote_client.get_local_cached_map(name)
        lcm.put("k", 1)
        assert lcm.get("k") == 1
        # another client mutates through its own LOCAL-CACHED handle
        peer = remote_client2.get_local_cached_map(name)
        peer.put("k", 99)
        assert wait_until(lambda: lcm.get("k") == 99)


class TestCacheBounds:
    def test_cache_size_lru_eviction_is_local_only(self, embedded_client):
        opts = LocalCachedMapOptions(
            cache_size=2, eviction_policy=EvictionPolicy.LRU
        )
        lcm = embedded_client.get_local_cached_map(nm("bound"), options=opts)
        for i in range(5):
            lcm.put(f"k{i}", i)
        assert lcm.cached_size() <= 2       # near cache bounded
        assert lcm.size() == 5              # backing map complete
        assert lcm.get("k0") == 0           # evicted locally, refetched

    def test_cache_ttl(self, embedded_client):
        opts = LocalCachedMapOptions(time_to_live=0.15)
        lcm = embedded_client.get_local_cached_map(nm("cttl"), options=opts)
        lcm.put("k", 1)
        assert lcm.get("k") == 1
        time.sleep(0.3)
        m0 = lcm.misses
        assert lcm.get("k") == 1  # near-cache entry expired: refetch
        assert lcm.misses > m0
