"""RedissonGeoTest ported (63 @Test — VERDICT r4 next-step #2 test-depth
campaign; the largest unported dedicated suite after zset/mapcache).

Parity: RedissonGeoTest.java test-for-test against the GeoSearchArgs
surface (api/geo/GeoSearchArgs).  Numeric deltas vs the reference's
literals come from Redis's 52-bit geohash quantization (positions shift by
~1e-7 deg, distances by <0.2m over 166km) — asserted with tolerances
instead; geohash strings match on the 10 leading chars (Redis zero-pads
the 11th from the quantized value).
"""
import pytest

import redisson_tpu
from redisson_tpu.client.objects.geo import GeoSearchArgs as A


@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


@pytest.fixture()
def geo(client):
    return client.get_geo("test")


PALERMO = (13.361389, 38.115556)
CATANIA = (15.087269, 37.502669)


def add_cities(geo):
    assert geo.add_all({"Palermo": PALERMO, "Catania": CATANIA}) == 2


def approx_map(got, want, rel=1e-3):
    assert set(got) == set(want)
    for k, v in want.items():
        assert got[k] == pytest.approx(v, rel=rel), k


def test_add(geo):
    assert geo.add(2.51, 3.12, "city1") == 1


def test_add_if_exists(geo):
    assert geo.add(2.51, 3.12, "city1") == 1
    assert geo.add_if_exists(2.9, 3.9, "city1") is True
    pos = geo.pos("city1")
    assert 3.8 <= pos["city1"][1] <= 3.9
    assert 2.8 <= pos["city1"][0] <= 3.0
    assert geo.add_if_exists(2.12, 3.5, "city2") is False


def test_try_add(geo):
    assert geo.add(2.51, 3.12, "city1") == 1
    assert geo.try_add(2.5, 3.1, "city1") is False
    assert geo.try_add(2.12, 3.5, "city2") is True


def test_add_entries(geo):
    assert geo.add_all({"city1": (3.11, 9.10321), "city2": (81.1231, 38.65478)}) == 2


def test_dist(geo):
    add_cities(geo)
    assert geo.dist("Palermo", "Catania", "m") == pytest.approx(166274.1516, rel=1e-5)


def test_dist_empty(geo):
    assert geo.dist("Palermo", "Catania", "m") is None


def test_hash(geo):
    add_cities(geo)
    h = geo.hash("Palermo", "Catania")
    assert h["Palermo"][:10] == "sqc8b49rny"
    assert h["Catania"][:10] == "sqdtr74hyu"


def test_hash_empty(geo):
    assert geo.hash("Palermo", "Catania") == {}


def test_pos4(geo):
    add_cities(geo)
    got = geo.pos("Palermo", "Catania")
    assert got["Palermo"] == pytest.approx(PALERMO, rel=1e-6)
    assert got["Catania"] == pytest.approx(CATANIA, rel=1e-6)


def test_pos1(geo):
    geo.add(0.123, 0.893, "hi")
    res = geo.pos("hi")
    assert res["hi"][0] is not None and res["hi"][1] is not None


def test_pos3(geo):
    geo.add(0.123, 0.893, "hi")
    res = geo.pos("hi", "123f", "sdfdsf")
    assert set(res) == {"hi"}


def test_pos2(geo):
    geo.add(*PALERMO, "Palermo")
    got = geo.pos("test2", "Palermo", "test3", "Catania", "test1")
    assert set(got) == {"Palermo"}


def test_pos(geo):
    add_cities(geo)
    got = geo.pos("test2", "Palermo", "test3", "Catania", "test1")
    assert set(got) == {"Palermo", "Catania"}


def test_pos_empty(geo):
    assert geo.pos("test2", "Palermo", "test3", "Catania", "test1") == {}


def test_box(geo):
    add_cities(geo)
    got = geo.search(A.from_coords(15.5, 38.5).box(5400, 5400, "km"))
    assert set(got) == {"Palermo", "Catania"}


def test_box_with_distance(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_coords(15.5, 38.5).box(5400, 5400, "km"))
    approx_map(got, {"Palermo": 191.4848, "Catania": 116.6784})


def test_box_with_position(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_coords(15.5, 38.5).box(5400, 5400, "km"))
    assert got["Palermo"] == pytest.approx(PALERMO, rel=1e-6)
    assert got["Catania"] == pytest.approx(CATANIA, rel=1e-6)


def test_box_store_search(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to("test-store", A.from_coords(15.5, 38.5).box(5400, 5400, "km")) == 2
    assert set(dest.read_all()) == {"Palermo", "Catania"}


def test_box_store_sorted(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_sorted_search_to("test-store", A.from_coords(15, 37).box(5400, 5400, "km")) == 2
    assert dest.read_all() == ["Catania", "Palermo"]


def test_radius(geo):
    add_cities(geo)
    assert set(geo.search(A.from_coords(15, 37).radius(200, "km"))) == {"Palermo", "Catania"}


def test_radius_count(geo):
    add_cities(geo)
    assert geo.search(A.from_coords(15, 37).radius(200, "km").with_count(1)) == ["Catania"]


def test_radius_order(geo):
    add_cities(geo)
    assert geo.search(A.from_coords(15, 37).radius(200, "km").with_order("DESC")) == ["Palermo", "Catania"]
    assert geo.search(A.from_coords(15, 37).radius(200, "km").with_order("ASC")) == ["Catania", "Palermo"]


def test_radius_order_count(geo):
    add_cities(geo)
    assert geo.search(A.from_coords(15, 37).radius(200, "km").with_order("DESC").with_count(1)) == ["Palermo"]
    assert geo.search(A.from_coords(15, 37).radius(200, "km").with_order("ASC").with_count(1)) == ["Catania"]


def test_radius_empty(geo):
    assert geo.search(A.from_coords(15, 37).radius(200, "km")) == []


def test_radius_with_distance(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km"))
    approx_map(got, {"Palermo": 190.4424, "Catania": 56.4413})


def test_radius_with_distance_count(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km").with_count(1))
    approx_map(got, {"Catania": 56.4413})


def test_radius_with_distance_order(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km").with_order("DESC"))
    assert list(got) == ["Palermo", "Catania"]
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km").with_order("ASC"))
    assert list(got) == ["Catania", "Palermo"]


def test_radius_with_distance_order_count(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km").with_order("DESC").with_count(1))
    approx_map(got, {"Palermo": 190.4424})
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km").with_order("ASC").with_count(1))
    approx_map(got, {"Catania": 56.4413})


def test_radius_with_distance_huge_amount(geo):
    for i in range(10_000):
        geo.add(10 + 0.000001 * i, 11 + 0.000001 * i, i)
    got = geo.search_with_distance(A.from_coords(10, 11).radius(200, "km"))
    assert len(got) == 10_000


def test_radius_with_position_huge_amount(geo):
    for i in range(10_000):
        geo.add(10 + 0.000001 * i, 11 + 0.000001 * i, i)
    got = geo.search_with_position(A.from_coords(10, 11).radius(200, "km"))
    assert len(got) == 10_000


def test_radius_with_distance_big_object(geo):
    big = "home:" + ",".join(str(i) for i in range(600))  # ~3KB member
    geo.add(13.361389, 38.115556, big)
    got = geo.search_with_distance(A.from_coords(15, 37).radius(200, "km"))
    assert set(got) == {big}


def test_radius_with_distance_empty(geo):
    assert geo.search_with_distance(A.from_coords(15, 37).radius(200, "km")) == {}


def test_radius_with_position(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_coords(15, 37).radius(200, "km"))
    assert set(got) == {"Palermo", "Catania"}
    assert got["Palermo"] == pytest.approx(PALERMO, rel=1e-6)


def test_radius_with_position_count(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_coords(15, 37).radius(200, "km").with_count(1))
    assert set(got) == {"Catania"}


def test_radius_with_position_order(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_coords(15, 37).radius(200, "km").with_order("DESC"))
    assert list(got) == ["Palermo", "Catania"]


def test_radius_with_position_order_count(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_coords(15, 37).radius(200, "km").with_order("DESC").with_count(1))
    assert list(got) == ["Palermo"]


def test_radius_with_position_empty(geo):
    assert geo.search_with_position(A.from_coords(15, 37).radius(200, "km")) == {}


def test_radius_member(geo):
    add_cities(geo)
    assert set(geo.search(A.from_member("Palermo").radius(200, "km"))) == {"Palermo", "Catania"}


def test_radius_member_count(geo):
    add_cities(geo)
    assert geo.search(A.from_member("Palermo").radius(200, "km").with_count(1)) == ["Palermo"]


def test_radius_member_order(geo):
    add_cities(geo)
    assert geo.search(A.from_member("Palermo").radius(200, "km").with_order("DESC")) == ["Catania", "Palermo"]
    assert geo.search(A.from_member("Palermo").radius(200, "km").with_order("ASC")) == ["Palermo", "Catania"]


def test_radius_member_order_count(geo):
    add_cities(geo)
    assert geo.search(A.from_member("Palermo").radius(200, "km").with_order("DESC").with_count(1)) == ["Catania"]


def test_radius_member_empty(geo):
    with pytest.raises(KeyError):
        geo.search(A.from_member("Palermo").radius(200, "km"))


def test_radius_member_with_distance(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_member("Palermo").radius(200, "km"))
    approx_map(got, {"Palermo": 0.0, "Catania": 166.2742}, rel=1e-3)
    assert got["Palermo"] == 0.0


def test_radius_member_with_distance_count(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_member("Palermo").radius(200, "km").with_count(1))
    assert set(got) == {"Palermo"}


def test_radius_member_with_distance_order(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_member("Palermo").radius(200, "km").with_order("DESC"))
    assert list(got) == ["Catania", "Palermo"]


def test_radius_member_with_distance_order_count(geo):
    add_cities(geo)
    got = geo.search_with_distance(A.from_member("Palermo").radius(200, "km").with_order("DESC").with_count(1))
    assert set(got) == {"Catania"}


def test_radius_member_with_distance_empty(geo):
    with pytest.raises(KeyError):
        geo.search_with_distance(A.from_member("Palermo").radius(200, "km"))


def test_radius_member_with_position(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_member("Palermo").radius(200, "km"))
    assert set(got) == {"Palermo", "Catania"}


def test_radius_member_with_position_count(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_member("Palermo").radius(200, "km").with_count(1))
    assert set(got) == {"Palermo"}


def test_radius_member_with_position_order(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_member("Palermo").radius(200, "km").with_order("DESC"))
    assert list(got) == ["Catania", "Palermo"]


def test_radius_member_with_position_order_count(geo):
    add_cities(geo)
    got = geo.search_with_position(A.from_member("Palermo").radius(200, "km").with_order("DESC").with_count(1))
    assert list(got) == ["Catania"]


def test_radius_member_with_position_empty(geo):
    with pytest.raises(KeyError):
        geo.search_with_position(A.from_member("Palermo").radius(200, "km"))


def test_radius_store(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to("test-store", A.from_coords(15, 37).radius(200, "km")) == 2
    assert set(dest.read_all()) == {"Palermo", "Catania"}


def test_radius_store_sorted(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_sorted_search_to("test-store", A.from_coords(15, 37).radius(200, "km")) == 2
    assert dest.read_all() == ["Catania", "Palermo"]


def test_radius_store_count(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to("test-store", A.from_coords(15, 37).radius(200, "km").with_count(1)) == 1
    assert dest.read_all() == ["Catania"]


def test_radius_store_sorted_count(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_sorted_search_to("test-store", A.from_coords(15, 37).radius(200, "km").with_count(1)) == 1
    assert dest.read_all() == ["Catania"]


def test_radius_store_order_count(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to(
        "test-store", A.from_coords(15, 37).radius(200, "km").with_order("DESC").with_count(1)) == 1
    assert dest.read_all() == ["Palermo"]


def test_radius_store_sorted_order_count(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_sorted_search_to(
        "test-store", A.from_coords(15, 37).radius(200, "km").with_order("DESC").with_count(1)) == 1
    assert dest.read_all() == ["Palermo"]


def test_radius_store_empty(client, geo):
    dest = client.get_geo("test-store")
    assert geo.store_search_to("test-store", A.from_coords(15, 37).radius(200, "km")) == 0
    assert dest.read_all() == []


def test_radius_store_member(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to("test-store", A.from_member("Palermo").radius(200, "km")) == 2
    assert set(dest.read_all()) == {"Palermo", "Catania"}


def test_radius_store_member_count(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to("test-store", A.from_member("Palermo").radius(200, "km").with_count(1)) == 1
    assert dest.read_all() == ["Palermo"]


def test_radius_store_member_order_count(client, geo):
    dest = client.get_geo("test-store")
    add_cities(geo)
    assert geo.store_search_to(
        "test-store", A.from_member("Palermo").radius(200, "km").with_order("DESC").with_count(1)) == 1
    assert dest.read_all() == ["Catania"]


def test_radius_store_member_empty(client, geo):
    with pytest.raises(KeyError):
        geo.store_search_to("test-store", A.from_member("Palermo").radius(200, "km"))


def test_store_overwrites_destination(client, geo):
    """GEOSEARCHSTORE replaces dest (Redis semantics), never merges."""
    dest = client.get_geo("test-store")
    dest.add(1.0, 1.0, "stale")
    add_cities(geo)
    geo.store_search_to("test-store", A.from_coords(15, 37).radius(200, "km"))
    assert "stale" not in dest.read_all()
