"""Distributed-services tests (RedissonExecutorServiceTest /
RedissonScheduledExecutorServiceTest / RedissonRemoteServiceTest /
RedissonTransactionTest / RedissonLiveObjectServiceTest / MapReduce tests)."""
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu.services.executor import CronExpression, inject_client
from redisson_tpu.services.liveobject import entity
from redisson_tpu.services.transactions import TransactionException


@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


def square(x):
    return x * x


def boom():
    raise ValueError("kapow")


@inject_client
def stamp_and_sleep(name, duration, client=None):
    import time as _t

    client.get_list(name).add(_t.time())
    _t.sleep(duration)
    return True


@inject_client
def uses_client(key, client=None):
    client.get_atomic_long(key).increment_and_get()
    return client.get_atomic_long(key).get()


class TestExecutor:
    def test_submit_and_result(self, client):
        ex = client.get_executor_service("ex")
        ex.register_workers(2)
        futs = [ex.submit(square, i) for i in range(10)]
        # generous budget: under full-suite load the worker threads compete
        # with every other module's pools, and a tight bound flakes
        assert [f.get(30.0) for f in futs] == [i * i for i in range(10)]
        assert ex.count_active_workers() == 2
        ex.shutdown()

    def test_task_failure_propagates(self, client):
        ex = client.get_executor_service("ex")
        ex.register_workers(1)
        f = ex.submit(boom)
        with pytest.raises(ValueError, match="kapow"):
            f.get(5.0)
        assert ex.task_state(f.task_id) == "failed"
        ex.shutdown()

    def test_cancel_queued(self, client):
        ex = client.get_executor_service("ex")  # no workers yet
        f = ex.submit(square, 3)
        assert ex.cancel_task(f.task_id)
        assert f.cancelled()
        assert not ex.cancel_task(f.task_id)
        ex.register_workers(1)
        time.sleep(0.1)
        assert ex.task_state(f.task_id) == "cancelled"
        ex.shutdown()

    def test_inject_client(self, client):
        ex = client.get_executor_service("ex")
        ex.register_workers(1)
        f = ex.submit(uses_client, "counter")
        assert f.get(5.0) == 1
        assert client.get_atomic_long("counter").get() == 1
        ex.shutdown()

    def test_tasks_survive_for_requeue(self, client):
        """Orphaned 'running' tasks go back to the queue (worker-death
        recovery, SURVEY.md §5.3)."""
        ex = client.get_executor_service("ex")
        f = ex.submit(square, 7)
        # simulate a worker that died mid-task
        task = ex._take_task()
        assert task is not None and task.state == "running"
        task.started_at -= 120  # claimed 2min ago, worker died
        assert ex.requeue_orphans(max_running_age=60) == 1
        ex.register_workers(1)
        assert f.get(5.0) == 49
        ex.shutdown()


class TestScheduler:
    def test_schedule_delay(self, client):
        sched = client.get_scheduled_executor_service("s")
        sched.register_workers(1)
        t0 = time.time()
        f = sched.schedule(0.1, square, 6)
        assert f.get(5.0) == 36
        assert time.time() - t0 >= 0.1
        sched.shutdown()

    def test_fixed_rate_and_cancel(self, client):
        # NB: tasks are pickled (serialized-task parity), so the task must hit
        # shared grid state — a closure over a local list would mutate a copy.
        sched = client.get_scheduled_executor_service("s")
        sched.register_workers(1)
        counter = client.get_atomic_long("ticks")
        sid = sched.schedule_at_fixed_rate(0.0, 0.05, uses_client, "ticks")
        time.sleep(0.22)
        assert sched.cancel_scheduled(sid)
        time.sleep(0.15)  # drain tasks already queued before the cancel
        n = counter.get()
        assert n >= 3
        time.sleep(0.15)
        assert counter.get() == n  # no new submissions after cancel
        sched.shutdown()

    def test_cron_parsing(self):
        c = CronExpression("*/15 3 * * 1-5")
        assert c.fields[0] == {0, 15, 30, 45}
        assert c.fields[1] == {3}
        t = time.localtime(c.next_fire(time.time()))
        assert t.tm_min in {0, 15, 30, 45} and t.tm_hour == 3
        with pytest.raises(ValueError):
            CronExpression("* * *")


class TestRemoteService:
    class Calc:
        def add(self, a, b):
            return a + b

        def fail(self):
            raise RuntimeError("remote boom")

    def test_invoke(self, client):
        rs = client.get_remote_service()
        rs.register("Calc", self.Calc(), workers=2)
        proxy = rs.get("Calc", timeout=5.0)
        assert proxy.add(2, 3) == 5
        with pytest.raises(RuntimeError, match="remote boom"):
            proxy.fail()
        rs.deregister()

    def test_ack_mode_and_timeout(self, client):
        rs = client.get_remote_service()
        rs.register("Calc", self.Calc(), workers=1)
        proxy = rs.get("Calc", timeout=5.0, ack_timeout=2.0)
        assert proxy.add(1, 1) == 2
        rs.deregister()
        from redisson_tpu.services.remote import RemoteServiceAckTimeout

        lonely = client.get_remote_service("nobody_home")
        proxy2 = lonely.get("Ghost", timeout=0.5, ack_timeout=0.3)
        with pytest.raises((RemoteServiceAckTimeout, TimeoutError)):
            proxy2.anything()


class TestTransactions:
    def test_commit_applies(self, client):
        tx = client.create_transaction()
        b = tx.get_bucket("b")
        m = tx.get_map("m")
        b.set("v1")
        m.put("k", 1)
        assert client.get_bucket("b").get() is None  # not yet visible
        tx.commit()
        assert client.get_bucket("b").get() == "v1"
        assert client.get_map("m").get("k") == 1

    def test_read_your_writes(self, client):
        tx = client.create_transaction()
        m = tx.get_map("m")
        m.put("k", 42)
        assert m.get("k") == 42
        m.remove("k")
        assert m.get("k") is None
        tx.rollback()
        assert client.get_map("m").get("k") is None

    def test_rollback_discards(self, client):
        tx = client.create_transaction()
        tx.get_bucket("b").set("x")
        tx.rollback()
        assert client.get_bucket("b").get() is None
        with pytest.raises(TransactionException):
            tx.commit()

    def test_optimistic_conflict(self, client):
        client.get_bucket("b").set("orig")
        tx = client.create_transaction()
        tb = tx.get_bucket("b")
        assert tb.get() == "orig"  # records version
        client.get_bucket("b").set("concurrent!")  # outside the tx
        tb.set("mine")
        with pytest.raises(TransactionException, match="changed concurrently"):
            tx.commit()
        assert client.get_bucket("b").get() == "concurrent!"

    def test_context_manager_commits(self, client):
        with client.create_transaction() as tx:
            tx.get_set("s").add("member")
        assert client.get_set("s").contains("member")

    def test_timeout(self, client):
        tx = client.create_transaction(timeout=0.05)
        time.sleep(0.08)
        with pytest.raises(TransactionException, match="timed out"):
            tx.get_bucket("b").set("late")


@entity(id_field="user_id", indexed=("city",))
class User:
    def __init__(self, user_id, name=None, city=None):
        self.user_id = user_id
        self.name = name
        self.city = city


class TestLiveObject:
    def test_persist_and_live_updates(self, client):
        svc = client.get_live_object_service()
        u = svc.persist(User("u1", name="Ada", city="London"))
        assert u.name == "Ada"
        u.name = "Ada Lovelace"  # write-through
        again = svc.get(User, "u1")
        assert again.name == "Ada Lovelace"
        assert again == u
        with pytest.raises(ValueError):
            svc.persist(User("u1"))

    def test_id_immutable(self, client):
        svc = client.get_live_object_service()
        u = svc.persist(User("u2", name="Bob"))
        with pytest.raises(AttributeError):
            u.user_id = "other"

    def test_indexed_search(self, client):
        svc = client.get_live_object_service()
        svc.persist(User("a", name="A", city="Paris"))
        svc.persist(User("b", name="B", city="Paris"))
        svc.persist(User("c", name="C", city="Tokyo"))
        hits = svc.find(User, city="Paris")
        assert {h.user_id for h in hits} == {"a", "b"}
        # index follows updates
        hits[0].city = "Tokyo"
        assert {h.user_id for h in svc.find(User, city="Tokyo")} >= {"c"}
        assert len(svc.find(User, city="Paris")) == 1
        with pytest.raises(ValueError):
            svc.find(User, name="A")  # not indexed

    def test_delete(self, client):
        svc = client.get_live_object_service()
        svc.persist(User("d", city="Oslo"))
        assert svc.delete(User, "d")
        assert svc.get(User, "d") is None
        assert not svc.delete(User, "d")
        assert svc.find(User, city="Oslo") == []


class TestMapReduce:
    def test_word_count_generic(self, client):
        m = client.get_map("src")
        m.put_all({i: "alpha beta gamma beta" for i in range(50)})

        def mapper(k, v, collector):
            for w in v.split():
                collector.emit(w, 1)

        def reducer(word, counts):
            return sum(counts)

        mr = client.get_map_reduce(mapper, reducer, workers=4)
        result = mr.execute(m)
        assert result == {"alpha": 50, "beta": 100, "gamma": 50}

    def test_collator_and_result_map(self, client):
        m = client.get_map("src")
        m.put_all({i: "x y" for i in range(10)})

        mr = client.get_map_reduce(
            lambda k, v, c: [c.emit(w, 1) for w in v.split()],
            lambda w, counts: sum(counts),
            collator=lambda result: sum(result.values()),
        )
        out_map = client.get_map("out")
        total = mr.execute(m, result_map=out_map)
        assert total == 20
        assert out_map.get("x") == 10

    def test_word_count_fast_path(self, client):
        from redisson_tpu.services.mapreduce import word_count

        m = client.get_map("src")
        m.put_all({i: "tick tock tick" for i in range(100)})
        counts = word_count(m, workers=8)
        assert counts == {"tick": 200, "tock": 100}

    def test_kernel_mapreduce(self, client):
        import numpy as np

        from redisson_tpu.services.mapreduce import KernelMapReduce

        def map_fn(v):
            return v % 16, v * 2  # key_id, mapped value

        kmr = KernelMapReduce(map_fn, reduce="sum", n_keys=16)
        values = np.arange(1600, dtype=np.int32)
        out = kmr.execute(values)
        # each key gets 100 values v with v%16==k; sum(2v)
        expected = np.asarray([sum(2 * v for v in range(k, 1600, 16)) for k in range(16)])
        np.testing.assert_array_equal(out, expected)

    def test_collection_source(self, client):
        lst = client.get_list("l")
        lst.add_all(["a b", "b c", "c d"])
        mr = client.get_map_reduce(
            lambda _k, v, c: [c.emit(w, 1) for w in v.split()],
            lambda w, counts: sum(counts),
            workers=2,
        )
        assert mr.execute(lst) == {"a": 1, "b": 2, "c": 2, "d": 1}


def _read_pair(ctx, keys, args):
    """Atomic cross-object read: holds both record locks like Lua would."""
    return (ctx.get_bucket(keys[0]).get(), ctx.get_map(keys[1]).get("v"))


class TestServiceEdges:
    """Edge behaviors modeled on the reference's service test classes
    (RedissonLiveObjectServiceTest / RedissonTransactionTest /
    RedissonExecutorServiceTest)."""

    def test_liveobject_index_follows_field_updates(self, client):
        @entity(id_field="id", indexed=("city",))
        class Person:
            def __init__(self, id=None, city=None):
                self.id = id
                self.city = city

        svc = client.get_live_object_service()
        p = svc.persist(Person(id=1, city="berlin"))
        assert [x.id for x in svc.find(Person, city="berlin")] == [1]
        p.city = "tokyo"  # indexed field update must move the index entry
        assert svc.find(Person, city="berlin") == []
        assert [x.id for x in svc.find(Person, city="tokyo")] == [1]
        svc.delete(Person, 1)
        assert svc.find(Person, city="tokyo") == []
        assert not svc.is_exists(Person, 1)

    def test_liveobject_multi_condition_and(self, client):
        @entity(id_field="id", indexed=("city", "tier"))
        class Acct:
            def __init__(self, id=None, city=None, tier=None):
                self.id = id
                self.city = city
                self.tier = tier

        svc = client.get_live_object_service()
        for i, (c, t) in enumerate([("a", 1), ("a", 2), ("b", 1)]):
            svc.persist(Acct(id=i, city=c, tier=t))
        assert [x.id for x in svc.find(Acct, city="a", tier=1)] == [0]
        with pytest.raises(ValueError, match="not indexed"):
            svc.find(Acct, id=1)

    def test_transaction_multi_object_commit_is_atomic(self, client):
        """An ATOMIC reader (script holding both record locks) never
        observes a commit's objects half-applied.  Two plain gets would not
        prove this — another commit can land between them."""
        import threading

        client.get_bucket("txa:b").set(0)
        client.get_map("txa:m").put("v", 0)
        svc = client.get_script()
        stop = threading.Event()
        torn = []

        def reader():
            while not stop.is_set():
                try:
                    a, b = svc.eval(_read_pair, ["txa:b", "txa:m"])
                except Exception as e:  # noqa: BLE001 — a dead reader must FAIL the test
                    torn.append(("reader-error", repr(e)))
                    return
                if a != b:
                    torn.append((a, b))

        t = threading.Thread(target=reader)
        t.start()
        try:
            for i in range(1, 40):
                tx = client.create_transaction()
                tx.get_bucket("txa:b").set(i)
                tx.get_map("txa:m").put("v", i)
                tx.commit()
        finally:
            stop.set()
            t.join(10)
        assert not torn, f"torn transaction observed: {torn[:5]}"

    def test_transaction_rollback_then_reuse_fails(self, client):
        tx = client.create_transaction()
        tx.get_bucket("txr:b").set(9)
        tx.rollback()
        assert client.get_bucket("txr:b").get() is None
        with pytest.raises(TransactionException):
            tx.get_bucket("txr:b").set(1)  # finished tx refuses new ops

    def test_executor_cancel_scheduled_before_fire(self, client):
        ex = client.get_scheduled_executor_service("sched-edge")
        ex.register_workers(1)
        fired = client.get_atomic_long("sched-edge:fired")
        f = ex.schedule(0.4, uses_client, "sched-edge:fired")
        assert ex.cancel_task(f.task_id)  # not yet fired: cancellable
        assert not ex.cancel_task(f.task_id)
        time.sleep(0.6)
        assert fired.get() == 0  # cancelled schedule never fires
        ex.shutdown()

    def test_delayed_queue_transfers_exactly_once(self, client):
        """However many transfer paths race (wheel timer + explicit calls),
        the element reaches the destination exactly once."""
        dest = client.get_blocking_queue("dqe:dest")
        dq = client.get_delayed_queue(dest)
        dq.offer("x", delay=0.5)  # generous pre-due window: a CI stall
        assert dq.transfer_due() == 0  # must not flake the early asserts
        assert dest.poll() is None
        time.sleep(0.6)
        dq.transfer_due()
        dq.transfer_due()
        assert dest.poll_blocking(2.0) == "x"
        assert dest.poll() is None  # exactly one copy arrived


class TestExecutorSubmitForms:
    """RExecutorService.submit(id, task) and submit(task, timeToLive)."""

    def test_submit_with_explicit_id(self, client):
        ex = client.get_executor_service("exid")
        ex.register_workers(1)
        f = ex.submit(square, 4, task_id="my-task")
        assert f.task_id == "my-task"
        assert f.get(10.0) == 16
        assert ex.task_state("my-task") == "finished"
        ex.shutdown()

    def test_duplicate_active_id_rejected(self, client):
        ex = client.get_executor_service("exdup")  # no workers: stays queued
        ex.submit(square, 1, task_id="dup")
        with pytest.raises(ValueError, match="already active"):
            ex.submit(square, 2, task_id="dup")
        ex.shutdown()

    def test_ttl_expires_unstarted_task(self, client):
        ex = client.get_executor_service("exttl")  # no workers yet
        f = ex.submit(square, 9, ttl=0.1)
        time.sleep(0.25)
        ex.register_workers(1)  # claims AFTER the ttl elapsed
        with pytest.raises(RuntimeError, match="expired"):
            f.get(10.0)
        assert ex.task_state(f.task_id) == "failed"
        ex.shutdown()

    def test_ttl_task_runs_if_claimed_in_time(self, client):
        ex = client.get_executor_service("exttl2")
        ex.register_workers(1)
        f = ex.submit(square, 5, ttl=30.0)
        assert f.get(10.0) == 25
        ex.shutdown()

    def test_ttl_expires_without_any_worker_claim(self, client):
        """Review fix: the TTL deadline fails the task via the engine timer
        even when NO worker ever claims it."""
        ex = client.get_executor_service("exttl3")  # never registers workers
        f = ex.submit(square, 2, ttl=0.1)
        with pytest.raises(RuntimeError, match="expired"):
            f.get(10.0)  # resolved by the timer, well before this timeout
        assert ex.task_state(f.task_id) == "failed"
        ex.shutdown()

    def test_duplicate_id_rejection_keeps_original_future(self, client):
        """Review fix: a rejected duplicate submit must not clobber the
        original submitter's future."""
        ex = client.get_executor_service("exdup2")
        f1 = ex.submit(square, 6, task_id="keep")
        with pytest.raises(ValueError):
            ex.submit(square, 7, task_id="keep")
        ex.register_workers(1)
        assert f1.get(10.0) == 36  # original future still resolves
        ex.shutdown()


class TestScheduleWithFixedDelay:
    def test_delay_counts_from_completion(self, client):
        """scheduleWithFixedDelay: runs never overlap — each delay starts
        after the previous run finishes (a fixed-rate schedule with a slow
        task would stack submissions)."""
        sched = client.get_scheduled_executor_service("swfd")
        sched.register_workers(2)
        stamps = client.get_list("swfd-stamps")
        sid = sched.schedule_with_fixed_delay(0.0, 0.15, stamp_and_sleep, "swfd-stamps", 0.1)
        time.sleep(0.9)
        assert sched.cancel_scheduled(sid)
        n = stamps.size()
        # each cycle costs >= 0.25s (0.1 run + 0.15 delay): 0.9s fits 3-4
        assert 2 <= n <= 4, n
        time.sleep(0.35)
        assert stamps.size() == n  # cancelled: no further runs
        sched.shutdown()
