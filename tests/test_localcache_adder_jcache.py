"""LocalCachedMap, adders, EvictionScheduler, JCache facade.

Parity seams: RedissonLocalCachedMap (near cache + invalidation topic,
cache/LocalCacheListener.java), RedissonBaseAdder (local counters + flush
topic), eviction/EvictionScheduler (self-tuning sweep), org/redisson/jcache
(JSR-107).
"""
import time

import pytest

from redisson_tpu.client.redisson import RedissonTpu
from redisson_tpu.client.objects.localcache import (
    EvictionPolicy,
    LocalCachedMapOptions,
    ReconnectionStrategy,
    SyncStrategy,
)
from redisson_tpu.core.eviction import EvictionScheduler


@pytest.fixture()
def client():
    c = RedissonTpu.create()
    yield c
    c.shutdown()


# -- LocalCachedMap ----------------------------------------------------------


def test_local_cache_hit_path(client):
    m = client.get_local_cached_map("lc:basic")
    m.put("a", 1)
    assert m.get("a") == 1  # served from cache (populated by put)
    assert m.hits >= 1
    assert m.cached_size() == 1


def test_invalidate_strategy_between_handles(client):
    opts = LocalCachedMapOptions(sync_strategy=SyncStrategy.INVALIDATE)
    m1 = client.get_local_cached_map("lc:inv", options=opts)
    m2 = client.get_local_cached_map("lc:inv", options=opts)
    m1.put("k", "v1")
    assert m2.get("k") == "v1"         # m2 caches it
    assert m2.cached_size() == 1
    m1.put("k", "v2")                   # must invalidate m2's copy
    assert "k" not in [k for k in m2.cached_keys()] or m2.get("k") == "v2"
    assert m2.get("k") == "v2"


def test_update_strategy_pushes_value(client):
    opts = LocalCachedMapOptions(sync_strategy=SyncStrategy.UPDATE)
    m1 = client.get_local_cached_map("lc:upd", options=opts)
    m2 = client.get_local_cached_map("lc:upd", options=opts)
    m1.put("k", "v1")
    # m2 received the pushed value without ever reading the shared map
    assert m2.cached_size() == 1
    hits_before = m2.hits
    assert m2.get("k") == "v1"
    assert m2.hits == hits_before + 1


def test_none_strategy_no_propagation(client):
    opts = LocalCachedMapOptions(sync_strategy=SyncStrategy.NONE)
    m1 = client.get_local_cached_map("lc:none", options=opts)
    m2 = client.get_local_cached_map("lc:none", options=opts)
    m1.put("k", "v1")
    assert m2.cached_size() == 0


def test_remove_invalidates_peers(client):
    m1 = client.get_local_cached_map("lc:rm")
    m2 = client.get_local_cached_map("lc:rm")
    m1.put("k", 1)
    m2.get("k")
    m1.remove("k")
    assert m2.cached_size() == 0
    assert m2.get("k") is None


def test_lru_eviction_bounds_cache(client):
    opts = LocalCachedMapOptions(cache_size=3, eviction_policy=EvictionPolicy.LRU)
    m = client.get_local_cached_map("lc:lru", options=opts)
    for i in range(5):
        m.put(f"k{i}", i)
    assert m.cached_size() == 3
    # underlying map still holds everything
    assert m.size() == 5
    assert m.get("k0") == 0  # miss -> refetch


def test_lfu_eviction_keeps_hot_keys(client):
    opts = LocalCachedMapOptions(cache_size=2, eviction_policy=EvictionPolicy.LFU)
    m = client.get_local_cached_map("lc:lfu", options=opts)
    m.put("hot", 1)
    for _ in range(5):
        m.get("hot")
    m.put("warm", 2)
    m.put("cold", 3)  # evicts the least-frequently-used of {warm, ...}
    assert "hot" in m.cached_keys()


def test_local_ttl_expires_cached_copy(client):
    opts = LocalCachedMapOptions(time_to_live=0.05)
    m = client.get_local_cached_map("lc:ttl", options=opts)
    m.put("k", 1)
    assert m.cached_size() == 1
    time.sleep(0.08)
    hits = m.hits
    assert m.get("k") == 1  # still in shared map; near-cache copy expired
    assert m.hits == hits   # that read was a miss


def test_reconnection_strategies(client):
    m = client.get_local_cached_map(
        "lc:rec", options=LocalCachedMapOptions(reconnection_strategy=ReconnectionStrategy.CLEAR)
    )
    m.put("a", 1)
    m.on_reconnect()
    assert m.cached_size() == 0

    m2 = client.get_local_cached_map(
        "lc:rec", options=LocalCachedMapOptions(reconnection_strategy=ReconnectionStrategy.LOAD)
    )
    m2.on_reconnect()
    assert m2.cached_size() == 1  # warmed from shared map


def test_clear_propagates(client):
    m1 = client.get_local_cached_map("lc:clear")
    m2 = client.get_local_cached_map("lc:clear")
    m1.put("a", 1)
    m2.get("a")
    m1.clear()
    assert m2.cached_size() == 0
    assert m1.size() == 0


# -- adders ------------------------------------------------------------------


def test_long_adder_sum_across_handles(client):
    a1 = client.get_long_adder("adder:l")
    a2 = client.get_long_adder("adder:l")
    for _ in range(10):
        a1.increment()
    a2.add(5)
    a2.decrement()
    assert a1.sum() == 14
    assert a2.sum() == 14


def test_long_adder_reset(client):
    a = client.get_long_adder("adder:reset")
    a.add(7)
    assert a.sum() == 7
    a.reset()
    assert a.sum() == 0


def test_double_adder(client):
    a1 = client.get_double_adder("adder:d")
    a2 = client.get_double_adder("adder:d")
    a1.add(1.5)
    a2.add(2.25)
    assert a1.sum() == pytest.approx(3.75)


def test_adder_destroy_flushes(client):
    a1 = client.get_long_adder("adder:destroy")
    a2 = client.get_long_adder("adder:destroy")
    a1.add(3)
    a1.destroy()
    assert a2.sum() == 3


# -- EvictionScheduler -------------------------------------------------------


def test_eviction_scheduler_sweeps_and_backs_off():
    sched = EvictionScheduler(min_delay=0.02, max_delay=0.5, start_delay=0.02)
    removed_per_call = [150, 150, 0, 0, 0]
    calls = []

    def sweep():
        calls.append(time.time())
        return removed_per_call[min(len(calls) - 1, len(removed_per_call) - 1)]

    sched.schedule("obj", sweep)
    deadline = time.time() + 5
    while len(calls) < 5 and time.time() < deadline:
        time.sleep(0.01)
    sched.close()
    assert len(calls) >= 5
    assert sched.total_removed >= 300


def test_eviction_scheduler_survives_failing_sweep():
    sched = EvictionScheduler(min_delay=0.01, max_delay=0.1)
    calls = []

    def bad():
        calls.append(1)
        raise RuntimeError("boom")

    sched.schedule("bad", bad)
    deadline = time.time() + 3
    while len(calls) < 2 and time.time() < deadline:
        time.sleep(0.01)
    sched.close()
    assert len(calls) >= 2  # the loop kept going after the exception


def test_map_cache_swept_by_engine_scheduler(client):
    client.engine.eviction.min_delay = 0.02
    client.engine.eviction.start_delay = 0.02
    mc = client.get_map_cache("sweep:mc")
    mc.put_with_ttl("k", "v", ttl=0.03)
    rec = client.engine.store.get("sweep:mc")
    deadline = time.time() + 5
    while rec.host and time.time() < deadline:
        time.sleep(0.02)
    assert not rec.host  # removed by the background sweep, not by an access


def test_unschedule_stops_task():
    sched = EvictionScheduler(min_delay=0.01, max_delay=0.1)
    calls = []
    sched.schedule("x", lambda: calls.append(1) or 0)
    deadline = time.time() + 3
    while not calls and time.time() < deadline:
        time.sleep(0.01)
    sched.unschedule("x")
    n = len(calls)
    time.sleep(0.1)
    assert len(calls) <= n + 1  # at most one in-flight sweep after unschedule
    sched.close()


# -- JCache ------------------------------------------------------------------


def test_jcache_basic_contract(client):
    from redisson_tpu.client.jcache import CacheConfig, ExpiryPolicy

    cm = client.get_cache_manager()
    cache = cm.create_cache("c1", CacheConfig(expiry=ExpiryPolicy.eternal()))
    assert cache.get("a") is None
    cache.put("a", 1)
    assert cache.get("a") == 1
    assert cache.get_and_put("a", 2) == 1
    assert cache.put_if_absent("a", 3) is False
    assert cache.put_if_absent("b", 9) is True
    assert cache.contains_key("b")
    assert cache.get_and_remove("b") == 9
    assert cache.remove("missing") is False
    cache.put("c", 5)
    assert cache.remove("c", 4) is False   # value mismatch -> keep
    assert cache.remove("c", 5) is True
    assert cache.statistics.hits > 0 and cache.statistics.puts > 0


def test_jcache_expiry_created(client):
    from redisson_tpu.client.jcache import CacheConfig, ExpiryPolicy

    cm = client.get_cache_manager()
    cache = cm.create_cache("cexp", CacheConfig(expiry=ExpiryPolicy.created(0.05)))
    cache.put("k", "v")
    assert cache.get("k") == "v"
    time.sleep(0.08)
    assert cache.get("k") is None


def test_jcache_invoke_atomic(client):
    cm = client.get_cache_manager()
    cache = cm.create_cache("cinv")
    cache.put("n", 10)

    def bump(entry):
        entry.set_value(entry.value + 1)
        return entry.value

    assert cache.invoke("n", bump) == 11
    assert cache.get("n") == 11

    def drop(entry):
        entry.remove()

    cache.invoke("n", drop)
    assert cache.get("n") is None


def test_jcache_manager_lifecycle(client):
    cm = client.get_cache_manager()
    cm.create_cache("x")
    assert cm.get_or_create_cache("x") is cm.get_cache("x")
    assert "x" in cm.cache_names()
    with pytest.raises(ValueError):
        cm.create_cache("x")
    cm.destroy_cache("x")
    assert cm.get_cache("x") is None
    c = cm.create_cache("y")
    cm.close()
    assert c.closed
    with pytest.raises(RuntimeError):
        c.get("a")


# -- review regressions ------------------------------------------------------


def test_localcache_replace_updates_near_cache(client):
    """A replace through one handle must not leave stale near-cache copies."""
    m1 = client.get_local_cached_map("lc:rep")
    m2 = client.get_local_cached_map("lc:rep")
    m1.put("k", 1)
    assert m2.get("k") == 1
    m1.replace("k", 2)
    assert m1.get("k") == 2
    assert m2.get("k") == 2
    assert m1.replace_if_equals("k", 2, 3) is True
    assert m2.get("k") == 3
    assert m1.remove_if_equals("k", 3) is True
    assert m2.get("k") is None


def test_localcache_put_if_absent_and_add_and_get(client):
    m1 = client.get_local_cached_map("lc:pia")
    m2 = client.get_local_cached_map("lc:pia")
    assert m1.put_if_absent("k", 5) is None
    assert m2.get("k") == 5
    assert m2.put_if_absent("k", 9) == 5  # no overwrite, no stale push
    assert m1.get("k") == 5
    m1.put("n", 10)
    assert m1.add_and_get("n", 2) == 12
    assert m2.get("n") == 12


def test_jcache_touched_expiry_via_put_if_absent(client):
    from redisson_tpu.client.jcache import CacheConfig, ExpiryPolicy

    cm = client.get_cache_manager()
    cache = cm.create_cache("ctouch", CacheConfig(expiry=ExpiryPolicy.touched(0.06)))
    assert cache.put_if_absent("k", 1) is True
    time.sleep(0.1)
    assert cache.get("k") is None  # idle-expired even via put_if_absent


def test_jcache_created_policy_not_rearmed_by_update(client):
    from redisson_tpu.client.jcache import CacheConfig, ExpiryPolicy

    cm = client.get_cache_manager()
    cache = cm.create_cache("crearm", CacheConfig(expiry=ExpiryPolicy.created(0.15)))
    cache.put("k", 1)
    time.sleep(0.08)
    cache.put("k", 2)  # update must NOT re-arm the created-TTL
    time.sleep(0.1)    # ~0.18s since creation > 0.15s
    assert cache.get("k") is None


def test_jcache_destroy_unschedules_sweep(client):
    cm = client.get_cache_manager()
    cm.create_cache("cgone")
    assert "jcache:cgone" in client.engine.eviction._tasks
    cm.destroy_cache("cgone")
    assert "jcache:cgone" not in client.engine.eviction._tasks


def test_checkpoint_save_during_concurrent_map_writes(tmp_path, client):
    """host state is serialized under the record lock — concurrent writers
    must not be able to tear the snapshot (dict-changed-size race)."""
    import threading

    from redisson_tpu.core import checkpoint

    m = client.get_map("race:map")
    stop = threading.Event()

    def writer():
        i = 0
        while not stop.is_set():
            m.put(f"k{i % 500}", i)
            i += 1

    threads = [threading.Thread(target=writer) for _ in range(3)]
    for t in threads:
        t.start()
    try:
        for round_ in range(10):
            checkpoint.save(client.engine, str(tmp_path / "race.ckpt"))
    finally:
        stop.set()
        for t in threads:
            t.join()
    assert checkpoint.load(RedissonTpu.create().engine, str(tmp_path / "race.ckpt")) >= 1
