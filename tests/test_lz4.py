"""Pure-python LZ4 block codec (utils/lz4block.py + client Lz4Codec) —
VERDICT r4 missing #4 / next-step #10; parity: codec/LZ4Codec.java.
"""
import os
import random

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu.client.codec import JsonCodec, Lz4Codec, StringCodec
from redisson_tpu.utils import lz4block


def rt(data: bytes) -> bytes:
    return lz4block.decompress(lz4block.compress(data), len(data))


@pytest.mark.parametrize("data", [
    b"",
    b"a",
    b"short",
    b"aaaaaaaaaaaa",                       # 12 bytes: under the match guard
    b"a" * 1000,                           # RLE (overlapping matches)
    b"abcd" * 500,                         # short-period repetition
    b"the quick brown fox " * 100,
    bytes(range(256)) * 64,                # long period
    b"x" * 14 + b"y",                      # literal run crossing the 15 nibble
    b"ab" * 7 + b"unique-tail-bytes!",
])
def test_roundtrip(data):
    assert rt(data) == data


def test_roundtrip_random_and_mixed():
    rng = random.Random(7)
    for n in (13, 100, 4096, 70_000):
        incompressible = bytes(rng.getrandbits(8) for _ in range(n))
        assert rt(incompressible) == incompressible
        mixed = incompressible[: n // 2] + b"Z" * (n // 2)
        assert rt(mixed) == mixed


def test_compression_actually_compresses():
    data = (b"redisson_tpu " * 1000) + os.urandom(100)
    packed = lz4block.compress(data)
    assert len(packed) < len(data) // 4


def test_long_match_and_literal_extension_encoding():
    # match length >> 15 and literal run >> 15 both take the 255-run path
    data = os.urandom(300) + b"q" * 100_000 + os.urandom(300)
    assert rt(data) == data


def test_decompress_rejects_malformed():
    data = b"hello world " * 50
    packed = lz4block.compress(data)
    with pytest.raises(ValueError):
        lz4block.decompress(packed[:-3], len(data))  # truncated
    with pytest.raises(ValueError):
        lz4block.decompress(packed, len(data) + 1)   # size mismatch
    with pytest.raises(ValueError):
        lz4block.decompress(b"\x01\x41\x09\x00\xff\xff", 100)  # bad offset


def test_format_literals_only_block():
    # a block of pure literals: token = len<<4, no offsets — decodable by
    # inspection against the published spec
    data = b"0123456789"
    packed = lz4block.compress(data)
    assert packed[0] == len(data) << 4
    assert packed[1:] == data


def test_codec_wraps_and_travels():
    c = Lz4Codec(JsonCodec())
    v = {"k": list(range(100)), "s": "x" * 500}
    assert c.decode(c.encode(v)) == v
    cs = Lz4Codec(StringCodec())
    assert cs.decode(cs.encode("hello " * 200)) == "hello " * 200


def test_codec_on_map_over_engine():
    client = redisson_tpu.create()
    try:
        m = client.get_map("lz4:m", codec=Lz4Codec())
        m.put("a", {"payload": "z" * 10_000})
        assert m.get("a") == {"payload": "z" * 10_000}
    finally:
        client.shutdown()


def test_codec_pickles_for_objcall():
    import pickle

    from redisson_tpu.net import safe_pickle

    c = Lz4Codec(JsonCodec())
    blob = pickle.dumps(c, protocol=4)
    c2 = safe_pickle.safe_loads(blob)
    assert c2.decode(c.encode([1, 2, 3])) == [1, 2, 3]
