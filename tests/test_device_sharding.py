"""Device-sharded serving (ISSUE 8): slot -> device placement over the
local mesh inside ONE server process.

Covers the tentpole contracts:

  * the 16384-slot table maps contiguously and completely onto
    ``jax.local_devices()``; records commit their banks to the owner device
    at EVERY install chokepoint (create / put / migration import);
  * device moves are FENCED slot handoffs riding the migration epoch
    discipline — kill-at-every-phase journaled rebalance property test,
    STALEEPOCH on a stale coordinator, bit-identical banks after resume;
  * the per-device warm pool: ``Engine.prewarm`` compiles every local
    device's kernels, and a device move re-hits the pool with ZERO rebuilds;
  * cross-device HLL / BitSet / MapReduce merges stay on-device
    (``IOStats.host_colocations`` == 0 — the zero-host-gather contract);
  * a coalesced run whose planes span devices falls back to per-record
    dispatch (CoalesceIneligible), never a host-side gather;
  * the wire surface: CLUSTER DEVICES / DEVMOVE (fenced, STALEEPOCH), and
    pipelined frames through the per-device dispatch plan preserve reply
    order across sharded/serial segment boundaries.
"""
import numpy as np
import pytest

from redisson_tpu.core.engine import Engine
from redisson_tpu.server.migration import (
    CoordinatorKilled,
    rebalance_devices,
    resume_device_rebalances,
)
from redisson_tpu.server.migration_journal import MigrationJournal
from redisson_tpu.server.placement import PlacementStaleEpoch, SlotPlacement
from redisson_tpu.utils.crc16 import MAX_SLOT, calc_slot


@pytest.fixture()
def engine():
    eng = Engine()
    eng.enable_placement()
    yield eng
    eng.shutdown()


def _names_on_distinct_devices(placement, n, prefix="dv"):
    """First `n` key names whose slots land on pairwise-distinct devices."""
    out, seen = [], set()
    i = 0
    while len(out) < n and i < 10_000:
        name = f"{prefix}{i}"
        d = placement.device_id_for_name(name)
        if d not in seen:
            seen.add(d)
            out.append(name)
        i += 1
    assert len(out) == n, f"only {len(out)} distinct devices reachable"
    return out


# -- placement table ----------------------------------------------------------


def test_owner_table_contiguous_and_complete():
    p = SlotPlacement()
    assert p.n_devices == 8  # conftest forces 8 host devices
    counts = p.slot_counts()
    assert sum(counts) == MAX_SLOT
    assert all(c == MAX_SLOT // 8 for c in counts)
    # contiguity: owner never decreases over the slot range
    owners = p.owner_snapshot()
    assert (np.diff(owners) >= 0).all()
    assert owners[0] == 0 and owners[-1] == 7


def test_spread_plan_4_8_4_shape():
    p = SlotPlacement()
    move_to_4 = p.spread_plan(4)
    assert move_to_4  # half the table moves off devices 4..7
    assert set(move_to_4.values()) <= set(range(4))
    for slot, dev in move_to_4.items():
        p.assign(slot, dev)
    assert p.slot_counts()[4:] == [0, 0, 0, 0]
    assert sum(p.slot_counts()) == MAX_SLOT
    move_back = p.spread_plan(8)
    for slot, dev in move_back.items():
        p.assign(slot, dev)
    assert p.slot_counts() == [MAX_SLOT // 8] * 8
    with pytest.raises(ValueError):
        p.spread_plan(0)
    with pytest.raises(ValueError):
        p.spread_plan(9)


def test_fence_stale_epoch_rejected_idempotent_accepted():
    p = SlotPlacement()
    assert p.assign(100, 3, epoch=5)
    assert p.epoch_of(100) == 5
    # same-epoch re-issue (the resume path) is accepted and idempotent
    assert not p.assign(100, 3, epoch=5)
    # a stale coordinator is fenced out loudly
    with pytest.raises(PlacementStaleEpoch, match="STALEEPOCH"):
        p.assign(100, 1, epoch=4)
    assert p.device_id_for_slot(100) == 3
    # a newer epoch supersedes; epoch-less manual moves stay unfenced
    assert p.assign(100, 2, epoch=6)
    assert p.assign(100, 4)
    # other slots are unaffected by slot 100's fence
    assert p.assign(101, 1, epoch=1)


def test_plan_frame_partitions_and_barriers():
    p = SlotPlacement()
    names = _names_on_distinct_devices(p, 3)
    cmds = [
        [b"SET", names[0].encode(), b"a"],
        [b"SET", names[1].encode(), b"b"],
        [b"DEL", names[0].encode()],          # not whitelisted: barrier
        [b"GET", names[1].encode()],
        [b"GET", names[2].encode()],
    ]
    plan = p.plan_frame(cmds)
    kinds = [k for k, _ in plan]
    assert kinds == ["sharded", "serial", "sharded"]
    first, barrier, second = (seg for _k, seg in plan)
    assert sorted(i for idxs in first.values() for i in idxs) == [0, 1]
    assert barrier == [2]
    assert sorted(i for idxs in second.values() for i in idxs) == [3, 4]
    # every bucket is single-device and indexes stay in frame order
    for seg in (first, second):
        for idxs in seg.values():
            assert idxs == sorted(idxs)


def test_plan_frame_none_when_no_parallelism():
    p = SlotPlacement()
    one = _names_on_distinct_devices(p, 1)[0].encode()
    # single command / single device / nothing shardable -> None
    assert p.plan_frame([[b"SET", one, b"x"]]) is None
    assert p.plan_frame([[b"SET", one, b"x"], [b"GET", one]]) is None
    assert p.plan_frame([[b"PING"], [b"PING"]]) is None
    # the bench A/B's 1-device leg: single_device_ok forces a plan
    forced = p.plan_frame(
        [[b"SET", one, b"x"], [b"GET", one]], single_device_ok=True
    )
    assert forced is not None and forced[0][0] == "sharded"
    # but a frame with NOTHING laneable stays None even forced
    assert p.plan_frame([[b"PING"], [b"PING"]], single_device_ok=True) is None


def test_cross_device_multikey_command_is_barrier():
    p = SlotPlacement()
    a, b = _names_on_distinct_devices(p, 2)
    cmds = [
        [b"SET", a.encode(), b"1"],
        [b"BITOP", b"OR", a.encode(), a.encode(), b.encode()],  # spans devices
        [b"SET", b.encode(), b"2"],
    ]
    assert p.device_index_for_command(cmds[1]) is None
    plan = p.plan_frame(cmds)
    assert [k for k, _ in plan] == ["sharded", "serial", "sharded"]


# -- record placement ---------------------------------------------------------


def test_records_commit_to_owner_device(engine):
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog
    from redisson_tpu.core import ioplane

    p = engine.placement
    names = _names_on_distinct_devices(p, 4, prefix="own")
    for name in names:
        HyperLogLog(engine, name).add_all([f"{name}:{j}" for j in range(20)])
    for name in names:
        rec = engine.store.get(name)
        got = ioplane.device_of(rec.arrays["regs"])
        assert got == p.device_for_name(name), name


def test_put_unguarded_places_like_migration_import(engine):
    """The migration/replication import chokepoint places too: a record
    installed via put_unguarded lands on its slot's owner device."""
    import jax.numpy as jnp

    from redisson_tpu.core import ioplane
    from redisson_tpu.core.store import StateRecord

    p = engine.placement
    name = "imp0"
    rec = StateRecord(
        kind="bitset", meta={}, arrays={"bits": jnp.zeros(64, jnp.uint8)}
    )
    engine.store.put_unguarded(name, rec)
    got = ioplane.device_of(engine.store.get(name).arrays["bits"])
    assert got == p.device_for_name(name)


def test_move_slot_records_fenced_and_bit_identical(engine):
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog
    from redisson_tpu.core import ioplane

    p = engine.placement
    name = "mv0"
    h = HyperLogLog(engine, name)
    h.add_all([f"k{j}" for j in range(500)])
    before = np.asarray(engine.store.get(name).arrays["regs"]).copy()
    count_before = h.count()
    slot = calc_slot(name.encode())
    src = p.device_id_for_slot(slot)
    dst = (src + 3) % p.n_devices
    moved = engine.move_slot_records(slot, dst, epoch=10)
    assert moved >= 1
    rec = engine.store.get(name)
    assert ioplane.device_of(rec.arrays["regs"]) == p.devices[dst]
    np.testing.assert_array_equal(np.asarray(rec.arrays["regs"]), before)
    assert h.count() == count_before
    # the losing coordinator is fenced out
    with pytest.raises(PlacementStaleEpoch, match="STALEEPOCH"):
        engine.move_slot_records(slot, src, epoch=9)
    assert ioplane.device_of(engine.store.get(name).arrays["regs"]) == p.devices[dst]


# -- per-device warm pool (satellite) -----------------------------------------


def test_prewarm_warms_every_device_and_move_hits_pool(engine):
    """--prewarm with placement on compiles every device's kernels (one
    pool entry per device per geometry), and a later device move finds its
    target already warm: ZERO rebuilds."""
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog

    p = engine.placement
    name = "warm:hll:devshard"
    HyperLogLog(engine, name).add_all(["seed"])
    first = engine.prewarm(names=[name])
    assert first >= p.n_devices  # at least one program set per device
    # everything is warm now: a second pass costs nothing
    assert engine.prewarm(names=[name]) == 0
    # a device move lands on an already-warm device: still zero rebuilds,
    # whichever device the slot hops to
    slot = calc_slot(name.encode())
    for dst in range(p.n_devices):
        engine.move_slot_records(slot, dst)
        assert engine.prewarm(names=[name], all_devices=False) == 0, dst


def test_prewarm_without_placement_keeps_historical_keys():
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog
    from redisson_tpu.core.warmpool import POOL

    eng = Engine()
    try:
        name = "warm:hll:classic"
        HyperLogLog(eng, name).add_all(["seed"])
        eng.prewarm(names=[name])
        # single-device engines key on device id -1 (the default device)
        assert any(
            k[0] == "hll" and k[-1] == -1
            for k in list(POOL._entries)
        )
    finally:
        eng.shutdown()


# -- journaled device rebalance: kill-at-every-phase (satellite) ---------------


def test_device_rebalance_kill_at_every_phase(engine, tmp_path):
    """For EVERY journal phase of a device rebalance, killing the
    coordinator right after that phase's entry and resuming ends with the
    slots on their target devices, banks bit-identical, journal terminal,
    and a stale coordinator fenced out with STALEEPOCH."""
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog
    from redisson_tpu.core import ioplane

    p = engine.placement
    jd = str(tmp_path / "journal")
    names = [f"reb{i}" for i in range(6)]
    for name in names:
        HyperLogLog(engine, name).add_all([f"{name}:{j}" for j in range(50)])
    baseline = {
        n: np.asarray(engine.store.get(n).arrays["regs"]).copy()
        for n in names
    }
    slots = sorted({calc_slot(n.encode()) for n in names})
    for phase in ("PLANNED", "DRAINING:1", "STABLE"):
        target_dev = {
            s: (p.device_id_for_slot(s) + 1) % p.n_devices for s in slots
        }
        with pytest.raises(CoordinatorKilled):
            rebalance_devices(
                engine, target_dev, journal_dir=jd, crash_after=phase
            )
        results = resume_device_rebalances(engine, jd)
        if phase == "STABLE":
            # the kill landed AFTER the terminal entry: the rebalance is
            # already complete, nothing is in flight to resume
            assert results == [], (phase, results)
            epoch = max(j.epoch for j in MigrationJournal.scan(jd))
        else:
            assert [r["action"] for r in results] == ["completed"], (
                phase, results,
            )
            epoch = results[0]["epoch"]
        assert not MigrationJournal.in_flight(jd), phase
        for name in names:
            slot = calc_slot(name.encode())
            rec = engine.store.get(name)
            assert (
                ioplane.device_of(rec.arrays["regs"])
                == p.devices[target_dev[slot]]
            ), (phase, name)
            np.testing.assert_array_equal(
                np.asarray(rec.arrays["regs"]), baseline[name]
            )
        # the losing (stale) coordinator cannot un-move any slot
        with pytest.raises(PlacementStaleEpoch, match="STALEEPOCH"):
            engine.move_slot_records(slots[0], 0, epoch=epoch - 1)


def test_rebalance_resume_skips_slots_a_newer_rebalance_owns(engine, tmp_path):
    """A crashed rebalance whose slots were since re-fenced HIGHER by a
    newer rebalance resumes without clobbering them (stale slots counted,
    not replayed)."""
    jd = str(tmp_path / "journal")
    slot = calc_slot(b"reb-stale")
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog

    HyperLogLog(engine, "reb-stale").add_all(["x"])
    with pytest.raises(CoordinatorKilled):
        rebalance_devices(
            engine, {slot: 2}, journal_dir=jd, crash_after="PLANNED"
        )
    # a NEWER rebalance moves the slot to device 5 and completes
    moved = rebalance_devices(engine, {slot: 5}, journal_dir=jd)
    assert moved >= 1
    results = resume_device_rebalances(engine, jd)
    assert [r["action"] for r in results] == ["completed"]
    assert results[0]["stale_slots"] == 1
    assert engine.placement.device_id_for_slot(slot) == 5
    assert resume_device_rebalances(engine, jd) == []  # idempotent


# -- cross-device merges stay on-device ---------------------------------------


def test_hll_union_across_devices_matches_single_device_and_stays_on_device():
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog
    from redisson_tpu.core import ioplane

    sharded = Engine()
    sharded.enable_placement()
    plain = Engine()
    try:
        names = _names_on_distinct_devices(sharded.placement, 4, prefix="hu")
        rng = np.random.default_rng(3)
        for name in names:
            keys = [f"{name}:{int(k)}" for k in rng.integers(0, 1 << 40, 300)]
            HyperLogLog(sharded, name).add_all(keys)
            HyperLogLog(plain, name).add_all(keys)
        ioplane.STATS.reset()
        got = HyperLogLog(sharded, names[0]).count_with(*names[1:])
        want = HyperLogLog(plain, names[0]).count_with(*names[1:])
        assert got == want
        snap = ioplane.STATS.snapshot()
        assert snap["host_colocations"] == 0
        assert snap["d2d_colocations"] > 0  # the merge really crossed devices
        # PFMERGE: destination keeps its committed owner device
        HyperLogLog(sharded, names[0]).merge_with(*names[1:])
        rec = sharded.store.get(names[0])
        assert ioplane.device_of(rec.arrays["regs"]) == (
            sharded.placement.device_for_name(names[0])
        )
        assert HyperLogLog(sharded, names[0]).count() == want
        assert ioplane.STATS.snapshot()["host_colocations"] == 0
    finally:
        sharded.shutdown()
        plain.shutdown()


def test_bitset_bitop_across_devices_stays_on_device():
    from redisson_tpu.client.objects.bitset import BitSet
    from redisson_tpu.core import ioplane

    eng = Engine()
    eng.enable_placement()
    try:
        a, b = _names_on_distinct_devices(eng.placement, 2, prefix="bo")
        BitSet(eng, a).set_each(np.array([1, 5, 9]))
        BitSet(eng, b).set_each(np.array([2, 5, 100]))
        ioplane.STATS.reset()
        BitSet(eng, a).or_(b)
        snap = ioplane.STATS.snapshot()
        assert snap["host_colocations"] == 0
        assert snap["d2d_colocations"] > 0
        got = np.asarray(BitSet(eng, a).get_each(np.arange(128)))
        assert sorted(np.nonzero(got)[0].tolist()) == [1, 2, 5, 9, 100]
    finally:
        eng.shutdown()


def test_wordcount_spreads_chunks_and_merges_without_host_gather():
    """The cross-device MapReduce acceptance: chunk extraction fans out
    across the local mesh and the merge back to the reduce device is d2d —
    ZERO host-side gathers (asserted via IOStats)."""
    import redisson_tpu
    from redisson_tpu.client.codec import StringCodec
    from redisson_tpu.core import ioplane
    from redisson_tpu.services.mapreduce import word_count

    c = redisson_tpu.create()
    try:
        c._engine.enable_placement()
        m = c.get_map("ds:wc", codec=StringCodec())
        rng = np.random.default_rng(5)
        vocab = [f"w{i}" for i in range(40)]
        entries = {
            f"d{i}": " ".join(vocab[j] for j in rng.integers(0, 40, 6))
            for i in range(3000)
        }
        m.put_all(entries)
        ioplane.STATS.reset()
        counts = word_count(m, workers=8)
        assert sum(counts.values()) == 3000 * 6
        snap = ioplane.STATS.snapshot()
        assert snap["host_colocations"] == 0
        assert snap["d2d_colocations"] > 0  # chunks really spread + merged
    finally:
        c.shutdown()


# -- coalescing stays per-device ----------------------------------------------


def test_coalesce_rejects_run_spanning_devices():
    """A fused run whose planes live on different devices is INELIGIBLE —
    the caller falls back to per-record dispatch; a cross-device stack
    through host memory must never happen."""
    import redisson_tpu
    from redisson_tpu.core import coalesce as CO

    c = redisson_tpu.create()
    try:
        engine = c._engine
        engine.enable_placement()
        names = _names_on_distinct_devices(engine.placement, 2, prefix="cx")
        for name in names:
            assert c.get_bloom_filter(name).try_init(20_000, 0.01)
        with pytest.raises(CO.CoalesceIneligible, match="span"):
            CO.fused_bloom_add_async(
                engine, names,
                [np.arange(10, dtype=np.int64)] * len(names),
            )
        # per-filter fallback works and lands on each record's own device
        for name in names:
            bf = c.get_bloom_filter(name)
            bf.add_all(np.arange(10, dtype=np.int64))
            assert bf.contains_each(np.arange(10, dtype=np.int64)).all()
    finally:
        c.shutdown()


def test_coalesce_same_device_run_still_fuses():
    import redisson_tpu
    from redisson_tpu.core import coalesce as CO

    c = redisson_tpu.create()
    try:
        engine = c._engine
        engine.enable_placement()
        p = engine.placement
        # names sharing ONE owner device
        home = p.device_id_for_name("sd0")
        names = [
            n for n in (f"sd{i}" for i in range(2000))
            if p.device_id_for_name(n) == home
        ][:4]
        assert len(names) == 4
        for name in names:
            assert c.get_bloom_filter(name).try_init(20_000, 0.01)
        keys = [np.arange(50, dtype=np.int64) * (i + 1) for i in range(4)]
        newly, lengths = CO.fused_bloom_add_async(engine, names, keys)
        flat = np.asarray(newly)
        off = 0
        for name, k, n in zip(names, keys, lengths):
            assert flat[off : off + n].all(), name  # valid region (padded)
            off += n
            assert c.get_bloom_filter(name).contains_each(k).all()
    finally:
        c.shutdown()


# -- per-device d2h gather ----------------------------------------------------


def test_gather_device_results_buckets_per_device():
    """Results spanning devices fetch as one merged transfer PER DEVICE
    (counted on that device's ledger), bit-identically."""
    import jax

    from redisson_tpu.core import ioplane

    devs = jax.local_devices()
    rng = np.random.default_rng(11)
    host_vals = [rng.integers(0, 255, 97).astype(np.uint8) for _ in range(6)]
    groups = [
        (jax.device_put(v, devs[i % 3]),) for i, v in enumerate(host_vals)
    ]
    ioplane.reset_device_stats()
    before = ioplane.STATS.snapshot()["blocking_syncs"]
    out = ioplane.gather_device_results(groups)
    for got, want in zip(out, host_vals):
        np.testing.assert_array_equal(got[0], want)
    after = ioplane.STATS.snapshot()["blocking_syncs"]
    assert after - before == 3  # one sync per touched device, not per group
    per_dev = ioplane.device_stats_snapshot()
    touched = [d for d, s in per_dev.items() if s["blocking_syncs"]]
    assert len(touched) == 3


# -- the wire surface ---------------------------------------------------------


@pytest.fixture(scope="module")
def sharded_server():
    from redisson_tpu.server import ServerThread

    with ServerThread(devices="all", workers=8) as st:
        yield st


def test_cluster_devices_and_devmove_wire(sharded_server):
    from redisson_tpu.net.resp import RespError

    st = sharded_server
    with st.client() as conn:
        reply = conn.execute("CLUSTER", "DEVICES")
        assert int(reply[0]) == 8
        assert sum(int(row[1]) for row in reply[1:]) == MAX_SLOT
        conn.execute("SET", "wired", "v")
        conn.execute("PFADD", "wired:hll", "a", "b", "c")  # device-array record
        slot = calc_slot(b"wired:hll")
        moved = conn.execute("CLUSTER", "DEVMOVE", 3, "EPOCH", 50, slot)
        assert int(moved) >= 1  # the HLL's regs actually hopped devices
        assert int(conn.execute("PFCOUNT", "wired:hll")) == 3
        assert bytes(conn.execute("GET", "wired")) == b"v"
        # stale coordinator over the wire: STALEEPOCH, nothing moves
        reply = conn.execute("CLUSTER", "DEVMOVE", 1, "EPOCH", 49, slot)
        assert isinstance(reply, RespError)
        assert str(reply).startswith("STALEEPOCH")
        assert st.server.engine.placement.device_id_for_slot(slot) == 3
        # placement state is visible in CONFIG GET
        view = st.server.config_view()
        assert view["placement-devices"] == 8


def test_sharded_frame_preserves_reply_order(sharded_server):
    st = sharded_server
    with st.client() as conn:
        n = 24
        sets = conn.execute_many(
            [("SET", f"ord{i}", f"v{i}") for i in range(n)]
        )
        assert all(bytes(r) == b"OK" for r in sets)
        # mixed frame: sharded segments around a serial barrier (DEL)
        replies = conn.execute_many(
            [("GET", f"ord{i}") for i in range(n)]
            + [("DEL", "ord0")]
            + [("GET", f"ord{i}") for i in range(n)]
        )
        assert [bytes(r) for r in replies[:n]] == [
            f"v{i}".encode() for i in range(n)
        ]
        assert int(replies[n]) == 1
        assert replies[n + 1] is None  # the barrier ordered the delete
        assert [bytes(r) for r in replies[n + 2 :]] == [
            f"v{i}".encode() for i in range(1, n)
        ]


def test_sharded_frame_bloom_runs_fuse_per_device(sharded_server):
    """Same-verb blob runs inside one frame still coalesce per device
    bucket, and the replies are correct and ordered."""
    st = sharded_server
    with st.client() as conn:
        names = [f"fr{i}" for i in range(8)]
        for name in names:
            assert conn.execute("BF.RESERVE", name, 0.01, 2000) in (b"OK", "OK")
        blob = np.arange(200, dtype="<i8").tobytes()
        adds = conn.execute_many(
            [("BF.MADD64", n, blob) for n in names], timeout=60.0
        )
        for r in adds:
            assert np.frombuffer(r, np.uint8).all()
        probes = conn.execute_many(
            [("BF.MEXISTS64", n, blob) for n in names], timeout=60.0
        )
        for r in probes:
            assert np.frombuffer(r, np.uint8).all()


def test_single_device_server_unchanged():
    """devices=None (the default) keeps the historical single-device
    server: no placement, no lanes, byte-identical dispatch path."""
    from redisson_tpu.server import ServerThread

    with ServerThread(port=0) as st:
        assert st.server.engine.placement is None
        assert st.server.engine.lanes is None
        with st.client() as conn:
            conn.execute("SET", "plain", "x")
            assert bytes(conn.execute("GET", "plain")) == b"x"
            assert conn.execute("CLUSTER", "DEVICES") == [0]


def test_mixed_journal_dir_resume_paths_never_cross(engine, tmp_path):
    """Device rebalances share the journal directory's epoch allocator
    with slot migrations, but each resume path settles ONLY its own kind:
    resume_migrations must not dial a device rebalance as a node address,
    and resume_device_rebalances must ignore slot-migration journals."""
    from redisson_tpu.server.migration import resume_migrations

    jd = str(tmp_path / "journal")
    from redisson_tpu.client.objects.hyperloglog import HyperLogLog

    HyperLogLog(engine, "mix0").add_all(["x"])
    slot = calc_slot(b"mix0")
    with pytest.raises(CoordinatorKilled):
        rebalance_devices(
            engine, {slot: 4}, journal_dir=jd, crash_after="PLANNED"
        )
    # a slot-migration journal in the SAME directory (unreachable node:
    # the wire resume path would fail loudly if it tried the rebalance)
    j = MigrationJournal.create(jd, "127.0.0.1:1", "127.0.0.1:2")
    j.append("PLANNED", source="127.0.0.1:1", target="127.0.0.1:2",
             slots=[slot], epoch=j.epoch, old_view=[], new_view=[])
    # both journals share one monotonic epoch sequence
    assert j.epoch > MigrationJournal.scan(jd)[0].epoch
    # the device-rebalance resume settles only its own journal
    results = resume_device_rebalances(engine, jd)
    assert [r["action"] for r in results] == ["completed"]
    assert engine.placement.device_id_for_slot(slot) == 4
    # the wire resume sees only the slot-migration journal; it fails on the
    # unreachable node (expected here) but never touches the rebalance
    wire = resume_migrations(jd)
    assert len(wire) == 1 and wire[0]["id"] == j.migration_id


def test_plan_frame_aborts_on_in_frame_multi():
    """MULTI arms transaction queueing mid-frame: every later command must
    append to the queue in frame order, which concurrent buckets cannot
    guarantee — the planner refuses the whole frame."""
    p = SlotPlacement()
    a, b = (n.encode() for n in _names_on_distinct_devices(p, 2))
    cmds = [
        [b"SET", a, b"1"],
        [b"MULTI"],
        [b"SET", b, b"2"],
        [b"EXEC"],
    ]
    assert p.plan_frame(cmds) is None
    assert p.plan_frame(cmds, single_device_ok=True) is None


def test_transaction_in_one_frame_on_sharded_server(sharded_server):
    """MULTI..EXEC pipelined in ONE frame against a device-sharded server
    queues and executes in order (the planner hands the frame to the
    sequential path)."""
    st = sharded_server
    with st.client() as conn:
        replies = conn.execute_many([
            ("SET", "tx:a", "1"),
            ("MULTI",),
            ("SET", "tx:a", "2"),
            ("SET", "tx:b", "3"),
            ("EXEC",),
            ("GET", "tx:a"),
            ("GET", "tx:b"),
        ])
        assert bytes(replies[0]) == b"OK"
        assert bytes(replies[1]) == b"OK"          # MULTI
        assert bytes(replies[2]) == b"QUEUED"
        assert bytes(replies[3]) == b"QUEUED"
        assert bytes(replies[5]) == b"2"
        assert bytes(replies[6]) == b"3"
