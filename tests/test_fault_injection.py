"""Deterministic fault-injection smoke tier (ISSUE 1 tentpole): one fast
test per fault type, each proving the fault flows through the REAL failure
path — retry machinery, pool discard, detector feeds — not around it.

The endurance tier (minutes of mixed workload across repeated cycles) is
``tests/test_soak.py`` (``-m slow``); these are its tier-1 contracts.
"""
import threading
import time

import pytest

from redisson_tpu.chaos.census import ResourceCensus
from redisson_tpu.chaos.faults import Fault, FaultPlane, FaultSchedule
from redisson_tpu.net.client import (
    CommandTimeoutError,
    ConnectionError_,
    NodeClient,
)
from redisson_tpu.net.detectors import (
    FailedCommandsDetector,
    FailedCommandsTimeoutDetector,
    FailedConnectionDetector,
)
from redisson_tpu.server.server import ServerThread
from redisson_tpu.utils.metrics import MetricsRegistry


@pytest.fixture(scope="module")
def server():
    with ServerThread(port=0) as st:
        yield st


def _client(server, **kw):
    kw.setdefault("ping_interval", 0)
    kw.setdefault("timeout", 2.0)
    kw.setdefault("retry_attempts", 2)
    kw.setdefault("retry_interval", 0.05)
    kw.setdefault("connect_timeout", 5.0)
    return NodeClient(f"127.0.0.1:{server.port}", **kw)


# -- schedule determinism -----------------------------------------------------

def test_schedule_is_seed_deterministic():
    a = FaultSchedule(42).add_random("drop", n=5, window=100)
    b = FaultSchedule(42).add_random("drop", n=5, window=100)
    assert [(f.kind, f.after) for f in a.faults] == [
        (f.kind, f.after) for f in b.faults
    ]
    c = FaultSchedule(43).add_random("drop", n=5, window=100)
    assert [f.after for f in a.faults] != [f.after for f in c.faults]


def test_unknown_fault_kind_rejected():
    with pytest.raises(ValueError):
        Fault("sigsegv")


def test_plane_counts_events_and_hits(server):
    sched = FaultSchedule(0)
    rule = sched.add("delay", port=server.port, after=1, count=2, delay_s=0.0)
    plane = sched.plane()
    nc = _client(server)
    try:
        with plane.active():
            for _ in range(4):
                nc.execute("PING")
        assert rule.hits == 2
        assert plane.injected == {"delay": 2}
        assert plane.events("send", server.port) >= 4
    finally:
        nc.close()


# -- one smoke per fault type -------------------------------------------------

def test_drop_feeds_command_failed_detector(server):
    det = FailedCommandsDetector(threshold=1, window_s=60.0)
    nc = _client(server, detector=det)
    sched = FaultSchedule(0)
    sched.add("drop", port=server.port, after=0, count=1)
    plane = sched.plane()
    try:
        with plane.active():
            assert nc.execute("PING") in (b"PONG", "PONG")  # retry recovers
        assert plane.injected == {"drop": 1}
        assert det.is_node_failed()  # the drop was COUNTED, not bypassed
    finally:
        nc.close()


def test_delay_injects_bounded_latency(server):
    nc = _client(server)
    sched = FaultSchedule(0)
    sched.add("delay", port=server.port, after=0, count=1, delay_s=0.3)
    plane = sched.plane()
    try:
        with plane.active():
            t0 = time.monotonic()
            assert nc.execute("PING") in (b"PONG", "PONG")
            assert time.monotonic() - t0 >= 0.3
    finally:
        nc.close()


def test_truncate_mid_reply_fails_loudly_then_recovers(server):
    det = FailedCommandsDetector(threshold=1, window_s=60.0)
    nc = _client(server, detector=det)
    sched = FaultSchedule(0)
    sched.add("truncate", port=server.port, after=0, count=1)
    plane = sched.plane()
    try:
        with plane.active():
            # partial frame then a dead socket -> discard + retry on a fresh
            # connection; the reply is never half-parsed into a wrong value
            assert nc.execute("ECHO", b"payload-123") == b"payload-123"
        assert plane.injected == {"truncate": 1}
        assert det.is_node_failed()
    finally:
        nc.close()


def test_refuse_connect_feeds_connection_detector(server):
    det = FailedConnectionDetector(threshold=1, window_s=60.0)
    nc = _client(server, detector=det, retry_attempts=1, pool_size=2, min_idle=0)
    sched = FaultSchedule(0)
    sched.add("refuse_connect", after=0, count=100)
    plane = sched.plane()
    try:
        with plane.active():
            with pytest.raises((ConnectionError_, OSError)):
                nc.execute("PING")
        assert plane.injected["refuse_connect"] >= 1
        assert det.is_node_failed()
        # chaos lifted: the same client reconnects and serves
        assert nc.execute("PING") in (b"PONG", "PONG")
    finally:
        nc.close()


def test_partition_in_times_out_and_feeds_timeout_detector(server):
    det = FailedCommandsTimeoutDetector(threshold=1, window_s=60.0)
    nc = _client(server, detector=det)
    sched = FaultSchedule(0)
    sched.add("partition_in", port=server.port, after=0, count=50)
    plane = sched.plane()
    try:
        with plane.active():
            with pytest.raises(CommandTimeoutError):
                nc.execute("PING", timeout=0.4, retry_attempts=0)
        assert plane.injected["partition_in"] >= 1
        assert det.is_node_failed()
        assert nc.execute("PING") in (b"PONG", "PONG")
    finally:
        nc.close()


def test_partition_out_times_out_without_transmitting(server):
    nc = _client(server)
    sched = FaultSchedule(0)
    sched.add("partition_out", port=server.port, after=0, count=1)
    plane = sched.plane()
    try:
        before = server.server.stats["commands"]
        with plane.active():
            with pytest.raises(CommandTimeoutError):
                nc.execute("PING", timeout=0.4, retry_attempts=0)
        # the frame never reached the server (one-way partition, outbound)
        assert server.server.stats["commands"] == before
        assert nc.execute("PING") in (b"PONG", "PONG")
    finally:
        nc.close()


def test_pause_node_is_hung_but_accepting(server):
    """SIGSTOP analog: connections stay open, replies stop — only the
    command-timeout detector class can catch this failure mode."""
    det = FailedCommandsTimeoutDetector(threshold=1, window_s=60.0)
    nc = _client(server, detector=det)
    try:
        server.server.pause()
        assert server.server.paused
        with pytest.raises(CommandTimeoutError):
            nc.execute("PING", timeout=0.5, retry_attempts=0)
        assert det.is_node_failed()
    finally:
        server.server.resume()
    assert nc.execute("PING") in (b"PONG", "PONG")
    nc.close()


def test_replication_stall_and_resume():
    from redisson_tpu.harness import _exec, free_port

    master = ServerThread(port=free_port()).start()
    replica = ServerThread(port=free_port()).start()
    try:
        with replica.client() as c:
            _exec(c, "REPLICAOF", master.server.host, master.server.port,
                  timeout=120.0)
        src = master.server.replication_source()
        from redisson_tpu.client.remote import RemoteRedisson

        r = RemoteRedisson(f"127.0.0.1:{master.server.port}", timeout=30.0)
        try:
            src.stall()
            r.get_bucket("stall:k").set(1)
            assert src.flush() == 0  # the stream ships NOTHING while stalled
            assert replica.server.engine.store.get_unguarded("stall:k") is None
            src.resume()
            assert src.flush() > 0
            assert replica.server.engine.store.get_unguarded("stall:k") is not None
        finally:
            r.shutdown()
    finally:
        replica.stop()
        master.stop()


def test_coordinator_probe_threads_exempt_by_default(server):
    """The failure detector's OWN probes are ground truth: a plane must not
    fault them by default (a chaos-faulted ping stream declares healthy
    masters dead — unplanned failover, lost async tail)."""
    sched = FaultSchedule(0)
    sched.add("drop", port=server.port, after=0, count=1000)
    plane = sched.plane()
    nc = _client(server, retry_attempts=0)
    result = {}

    def probe():
        result["reply"] = nc.execute("PING")

    try:
        with plane.active():
            t = threading.Thread(target=probe, name="rtpu-failover-0")
            t.start()
            t.join(timeout=10)
            assert result.get("reply") in (b"PONG", "PONG")
            assert plane.injected == {}  # nothing injected, nothing counted
            # a data-plane thread IS faulted by the same rule
            with pytest.raises((ConnectionError_, OSError)):
                nc.execute("PING")
        assert plane.injected == {"drop": 1}
    finally:
        nc.close()


# -- DCN-level (host-group) partitions ----------------------------------------

def test_dcn_partition_counts_on_group_stream():
    """A host-GROUP rule indexes the group's combined event stream: the
    faulted window covers the first N sends to EITHER node, regardless of
    how traffic interleaves — the DCN-uplink failure a per-port rule
    cannot express."""
    a = ServerThread(port=0).start()
    b = ServerThread(port=0).start()
    try:
        group = (a.port, b.port)
        sched = FaultSchedule(0)
        rule = sched.add_dcn_partition(group, direction="out", after=0, count=2)
        plane = sched.plane()
        nca = _client(a)
        ncb = _client(b)
        try:
            with plane.active():
                # first two sends into the group are swallowed — one per node
                with pytest.raises(CommandTimeoutError):
                    nca.execute("PING", timeout=0.4, retry_attempts=0)
                with pytest.raises(CommandTimeoutError):
                    ncb.execute("PING", timeout=0.4, retry_attempts=0)
                # window exhausted: BOTH nodes serve again
                assert nca.execute("PING") in (b"PONG", "PONG")
                assert ncb.execute("PING") in (b"PONG", "PONG")
            assert rule.hits == 2
            assert plane.injected == {"partition_out": 2}
        finally:
            nca.close()
            ncb.close()
    finally:
        a.stop()
        b.stop()


def test_dcn_partition_leaves_other_hosts_alone(server):
    """A group rule must not touch traffic to nodes OUTSIDE the group."""
    sched = FaultSchedule(0)
    sched.add_dcn_partition((server.port + 1, server.port + 2), after=0, count=50)
    plane = sched.plane()
    nc = _client(server)
    try:
        with plane.active():
            assert nc.execute("PING") in (b"PONG", "PONG")
        assert plane.injected == {}
    finally:
        nc.close()


def test_dcn_partition_validation():
    sched = FaultSchedule(0)
    with pytest.raises(ValueError, match="direction"):
        sched.add_dcn_partition((1, 2), direction="sideways")
    with pytest.raises(ValueError, match="mutually exclusive"):
        Fault("partition_out", port=1, ports=(1, 2))


# -- storage fault stream (checkpoint plane; depth in test_checkpoint.py) -----

def test_storage_faults_count_on_their_own_streams():
    from redisson_tpu.chaos.faults import FaultPlane

    sched = FaultSchedule(0)
    sched.add("enospc", after=1, count=1)
    sched.add("fsync_fail", after=0, count=1)
    plane = FaultPlane(sched)
    # event 0 on storage_write passes; event 1 raises
    assert plane.on_storage_write("/x", b"abcd") == b"abcd"
    with pytest.raises(OSError):
        plane.on_storage_write("/x", b"abcd")
    with pytest.raises(OSError):
        plane.on_storage_fsync("/x")
    assert plane.events("storage_write") == 2
    assert plane.events("storage_fsync") == 1
    assert plane.injected == {"enospc": 1, "fsync_fail": 1}


def test_torn_write_truncates_at_fraction_and_byte():
    from redisson_tpu.chaos.faults import FaultPlane

    sched = FaultSchedule(0)
    sched.add("torn_write", after=0, count=1, torn_frac=0.25)
    sched.add("torn_write", after=1, count=1, torn_at=3)
    plane = FaultPlane(sched)
    assert plane.on_storage_write("/x", b"x" * 100) == b"x" * 25
    assert plane.on_storage_write("/x", b"abcdef") == b"abc"
    assert plane.on_storage_write("/x", b"abcdef") == b"abcdef"  # window over


# -- RetryPolicy (net/retry.py) -----------------------------------------------

def test_retry_policy_backoff_is_seed_deterministic_and_bounded():
    from redisson_tpu.net.retry import RetryPolicy

    a = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=1.0, seed=7)
    b = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=1.0, seed=7)
    da = [a.backoff(i) for i in range(6)]
    db = [b.backoff(i) for i in range(6)]
    assert da == db  # same seed -> byte-identical sleep program
    for i, d in enumerate(da):
        assert 0.0 <= d <= 1.0 * 1.2 + 1e-9  # max_delay * (1 + jitter)
    c = RetryPolicy(max_attempts=5, base_delay=0.1, max_delay=1.0, seed=8)
    assert [c.backoff(i) for i in range(6)] != da


def test_retry_policy_deadline_propagates_into_sleep_and_timeouts():
    from redisson_tpu.net.retry import DeadlineExceeded, RetryPolicy

    clock = RetryPolicy(max_attempts=10, base_delay=5.0, deadline_s=0.05,
                        jitter=0.0).start()
    # per-attempt timeout is clamped to the remaining budget
    assert clock.attempt_timeout(30.0) <= 0.05
    clock.attempt = 1
    t0 = time.monotonic()
    try:
        clock.sleep()  # 5s backoff truncated to the ~0.05s budget
    except DeadlineExceeded:
        pass
    assert time.monotonic() - t0 < 1.0
    time.sleep(0.06)
    assert not clock.more_attempts()
    with pytest.raises(DeadlineExceeded):
        clock.sleep()


def test_node_client_retry_policy_rides_the_detectors(server):
    """The admin-plane satellite: a NodeClient on a RetryPolicy absorbs a
    drop via backoff AND still feeds the failure detector — control
    traffic rides the same machinery as data traffic."""
    from redisson_tpu.net.retry import RetryPolicy

    det = FailedCommandsDetector(threshold=1, window_s=60.0)
    nc = NodeClient(
        f"127.0.0.1:{server.port}", ping_interval=0, timeout=2.0,
        connect_timeout=5.0, detector=det,
        retry_policy=RetryPolicy(max_attempts=3, base_delay=0.02,
                                 max_delay=0.1, deadline_s=10.0),
    )
    sched = FaultSchedule(0)
    sched.add("drop", port=server.port, after=0, count=1)
    plane = sched.plane()
    try:
        with plane.active():
            assert nc.execute("PING") in (b"PONG", "PONG")  # retry recovers
        assert plane.injected == {"drop": 1}
        assert det.is_node_failed()  # the drop was COUNTED, not bypassed
        # an explicit per-call retry_attempts still overrides the policy
        sched2 = FaultSchedule(0)
        sched2.add("drop", port=server.port, after=0, count=10)
        with sched2.plane().active():
            with pytest.raises((ConnectionError_, OSError)):
                nc.execute("PING", retry_attempts=0)
    finally:
        nc.close()


def test_node_client_retry_policy_deadline_bounds_total_time(server):
    from redisson_tpu.net.retry import RetryPolicy

    nc = NodeClient(
        f"127.0.0.1:{server.port}", ping_interval=0, timeout=2.0,
        connect_timeout=5.0,
        retry_policy=RetryPolicy(max_attempts=50, base_delay=0.5,
                                 max_delay=2.0, deadline_s=0.8, jitter=0.0),
    )
    sched = FaultSchedule(0)
    sched.add("drop", port=server.port, after=0, count=1000)
    plane = sched.plane()
    try:
        with plane.active():
            t0 = time.monotonic()
            with pytest.raises((ConnectionError_, OSError)):
                nc.execute("PING")
            # 50 attempts x 0.5s+ backoff would be ~25s; the deadline
            # cuts the whole operation to ~its budget
            assert time.monotonic() - t0 < 5.0
    finally:
        nc.close()


# -- census ------------------------------------------------------------------

def test_census_snapshot_diff_and_gauges(server):
    census = ResourceCensus()
    census.track_server("srv", server.server)
    census.track_engine("srv.engine", server.server.engine)
    nc = _client(server)
    try:
        nc.execute("SET", "census:k", "v")
        before = census.snapshot()
        assert before["srv.engine.record_locks"] == 0
        assert before["srv.repl_staged_xfers"] == 0
        assert "srv.engine.keys" in before
        nc.execute("SET", "census:k2", "v")
        after = census.snapshot()
        moved = census.diff(before, after)
        assert "srv.engine.keys" in moved
        # the ignore pattern silences legitimate growth
        census.assert_flat(before, after, ignore=("*.keys", "*.wait_entries",
                                                  "*.connections"))
        # live gauges ride the ordinary MetricsRegistry -> Prometheus path
        reg = MetricsRegistry()
        census.register(reg)
        text = reg.prometheus_text()
        assert "census_srv_engine_record_locks" in text
    finally:
        nc.close()


def test_census_tracks_client_pools(server):
    census = ResourceCensus()
    nc = _client(server)

    class Facade:  # minimal RemoteRedisson shape: one .node
        node = nc

    try:
        census.track_client("cli", Facade())
        nc.execute("PING")
        snap = census.snapshot()
        assert snap["cli.node_clients"] == 1
        assert snap["cli.conn_in_use"] == 0  # released back at quiesce
        assert snap["cli.conn_idle"] >= 1
    finally:
        nc.close()


def test_census_assert_flat_raises_with_detail():
    census = ResourceCensus()
    with pytest.raises(AssertionError, match="x.locks: 0.0 -> 2.0"):
        census.assert_flat({"x.locks": 0.0}, {"x.locks": 2.0}, context="t")


# -- link retry profiles (ISSUE 16) --------------------------------------------

@pytest.fixture()
def _profile_reset():
    """Every profile test leaves the process exactly as found: unpinned,
    env untouched."""
    import os

    from redisson_tpu.net import retry

    saved = os.environ.pop("RTPU_RETRY_PROFILE", None)
    retry.set_retry_profile(None)
    yield
    if saved is None:
        os.environ.pop("RTPU_RETRY_PROFILE", None)
    else:
        os.environ["RTPU_RETRY_PROFILE"] = saved
    retry.set_retry_profile(None)


def test_lan_profile_is_the_historical_schedule(_profile_reset):
    """The behavioral-identity contract: the default profile's numbers ARE
    the policies the call sites hard-coded before profiles existed, so a
    single-host fleet (and every deterministic fault-schedule test) sees
    byte-identical retry behavior."""
    from redisson_tpu.net.retry import link_policy, replica_link_kwargs

    admin = link_policy("admin")
    assert (admin.max_attempts, admin.base_delay, admin.max_delay,
            admin.jitter, admin.deadline_s) == (4, 0.05, 1.0, 0.2, 30.0)
    rejoin = link_policy("rejoin")
    assert (rejoin.max_attempts, rejoin.base_delay, rejoin.max_delay,
            rejoin.jitter, rejoin.deadline_s) == (5, 0.1, 1.0, 0.2, 20.0)
    # replication links: the legacy single-shot discipline, no retry_policy
    assert replica_link_kwargs() == {"ping_interval": 0, "retry_attempts": 1}


def test_migration_admin_policy_rides_the_profile(_profile_reset):
    from redisson_tpu.net import retry
    from redisson_tpu.server.migration import _admin_retry_policy

    assert _admin_retry_policy().deadline_s == 30.0
    retry.set_retry_profile("wan")
    assert _admin_retry_policy().deadline_s == 120.0


def test_wan_profile_stretches_and_arms_replica_links(_profile_reset):
    from redisson_tpu.net import retry
    from redisson_tpu.net.retry import link_policy, replica_link_kwargs

    retry.set_retry_profile("wan")
    admin = link_policy("admin")
    assert admin.max_attempts == 8 and admin.deadline_s == 120.0
    kw = replica_link_kwargs()
    # still single-shot per call at the NodeClient layer, but the link now
    # carries a policy so WAN flaps back off instead of tearing down
    assert kw["retry_attempts"] == 1
    assert kw["retry_policy"].deadline_s == 60.0


def test_profile_resolution_env_pin_unknown(_profile_reset):
    import os

    from redisson_tpu.net import retry

    assert retry.current_profile() == "lan"          # default
    os.environ["RTPU_RETRY_PROFILE"] = "wan"
    assert retry.current_profile() == "wan"          # env engages
    retry.set_retry_profile("lan")
    assert retry.current_profile() == "lan"          # pin beats env
    retry.set_retry_profile(None)
    assert retry.current_profile() == "wan"          # unpin re-reads env
    os.environ["RTPU_RETRY_PROFILE"] = "interplanetary"
    assert retry.current_profile() == "lan"          # unknown -> lan, no boot
    with pytest.raises(ValueError):
        retry.set_retry_profile("interplanetary")    # explicit pin DOES fail
    from redisson_tpu.net.retry import link_policy

    assert link_policy("admin", deadline_s=5.0).deadline_s == 5.0  # override


def test_wan_profile_keeps_deadline_clamp_semantics(_profile_reset):
    """The clamp contract is profile-independent: a per-attempt timeout
    inside a nearly-exhausted operation budget waits the REMAINING budget,
    not its own default, and the sleep path still raises DeadlineExceeded
    at zero — wan only changes the numbers, never the semantics."""
    from redisson_tpu.net import retry
    from redisson_tpu.net.retry import DeadlineExceeded, link_policy

    retry.set_retry_profile("wan")
    clock = link_policy("admin", deadline_s=0.05).start()
    assert clock.attempt_timeout(30.0) <= 0.05       # clamped to the budget
    time.sleep(0.06)
    assert clock.attempt_timeout(30.0) == 0.0
    assert not clock.more_attempts()
    with pytest.raises(DeadlineExceeded):
        clock.sleep()


def test_supervisor_threads_retry_profile_to_server_cli(tmp_path, _profile_reset):
    from redisson_tpu.cluster import ClusterSupervisor
    from redisson_tpu.cluster.supervisor import NodeProc

    sup = ClusterSupervisor(masters=1, base_dir=str(tmp_path),
                            platform="cpu", retry_profile="wan")
    node = NodeProc("m0", "master", base_dir=str(tmp_path))
    cli = sup._server_cli(node, restore=False)
    i = cli.index("--retry-profile")
    assert cli[i + 1] == "wan"
