"""Async client API tests (the reference's async/reactive facade analog).

pytest-asyncio is not in the image; each test drives its own event loop via
asyncio.run — which also proves the client needs no special runner.
"""
import asyncio

import numpy as np
import pytest

from redisson_tpu.client.aio import AsyncRemoteRedisson
from redisson_tpu.net.resp import RespError
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def server():
    with ServerThread(port=0) as st:
        yield st


def test_async_basic_objects(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            m = client.get_map("aio-m")
            await m.put("k", 41)
            assert await m.get("k") == 41
            assert await m.size() == 1

            q = client.get_queue("aio-q")
            await q.offer("a")
            await q.offer("b")
            assert await q.poll() == "a"

            al = client.get_atomic_long("aio-counter")
            assert await al.increment_and_get() == 1
            assert await al.add_and_get(9) == 10

    asyncio.run(main())


def test_async_pipelining_single_connection(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            # many concurrent ops multiplex over ONE pipelined connection
            al = client.get_atomic_long("aio-pipe")
            results = await asyncio.gather(*(al.increment_and_get() for _ in range(50)))
            assert sorted(results) == list(range(1, 51))
            # raw pipeline: one write burst, ordered replies
            replies = await client.node.execute_pipeline(
                [("SET", f"aio-{i}", str(i)) for i in range(10)]
                + [("GET", f"aio-{i}") for i in range(10)]
            )
            assert [int(r) for r in replies[10:]] == list(range(10))

    asyncio.run(main())


def test_async_error_and_reconnect_surface(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            with pytest.raises(RespError):
                await client.execute("NOSUCHCMD")
            # still usable after an error reply
            b = client.get_bucket("aio-b")
            await b.set("v")
            assert await b.get() == "v"

    asyncio.run(main())


def test_async_pubsub(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            q = await client.subscribe("aio-chan")
            await asyncio.sleep(0.1)  # let the subscription register
            n = await client.execute("PUBLISH", "aio-chan", b"hello")
            assert n >= 1
            channel, payload = await asyncio.wait_for(q.get(), timeout=5)
            assert payload == b"hello"

    asyncio.run(main())


def test_async_lock_roundtrip(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            lock = client.get_lock("aio-lock")
            assert await lock.try_lock() is True
            # second client (distinct identity) cannot take it
            async with await AsyncRemoteRedisson.connect(server.address) as other:
                assert await other.get_lock("aio-lock").try_lock() is False
            await lock.unlock()

    asyncio.run(main())


def test_async_orphan_error_reply_does_not_kill_reader(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            conn = await client.node._connection()
            # a send()-fired command whose reply is a plain error frame: no
            # positional future exists — the reader must route it as an
            # orphan, not die on QueueEmpty
            conn.send("NOSUCHCMD")
            await conn.drain()
            await asyncio.sleep(0.2)
            assert not conn.closed
            assert await client.execute("PING") in (b"PONG", "PONG")

    asyncio.run(main())


def test_async_timeout_does_not_resend(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            al = client.get_atomic_long("aio-timeout-counter")
            await al.set(0)
            # hold the lock under another identity so try_lock(wait=1s)
            # genuinely blocks past the 0.05s client timeout
            await client.node.execute(
                "OBJCALL", "get_lock", "aio-slowlock", "try_lock",
                __import__("pickle").dumps(((), {})), "holder:9",
            )
            with pytest.raises(TimeoutError):
                await client.node.execute(
                    "OBJCALL", "get_lock", "aio-slowlock", "try_lock",
                    __import__("pickle").dumps(((1.0,), {})),
                    "h:1", timeout=0.05,
                )
            v1 = await al.increment_and_get()
            assert v1 == 1

    asyncio.run(main())


def test_async_factory_rejects_silent_codec(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            with pytest.raises(TypeError):
                client.get_bucket("b", object())

    asyncio.run(main())


def test_async_pubsub_multiplexed_and_unsubscribe(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            q1 = await client.subscribe("mux-1")
            q2 = await client.subscribe("mux-2")
            # both channels share ONE pubsub connection
            assert client._pubsub is not None
            await asyncio.sleep(0.1)
            await client.execute("PUBLISH", "mux-1", b"a")
            await client.execute("PUBLISH", "mux-2", b"b")
            assert (await asyncio.wait_for(q1.get(), 5))[1] == b"a"
            assert (await asyncio.wait_for(q2.get(), 5))[1] == b"b"
            await client.unsubscribe("mux-1")
            await asyncio.sleep(0.1)
            await client.execute("PUBLISH", "mux-1", b"gone")
            await client.execute("PUBLISH", "mux-2", b"still")
            assert (await asyncio.wait_for(q2.get(), 5))[1] == b"still"
            assert q1.empty(), "unsubscribed channel must stop delivering"

    asyncio.run(main())


def test_async_pubsub_reconnects_after_drop(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            q = await client.subscribe("reconn")
            await asyncio.sleep(0.1)
            # kill the pubsub socket out from under the client
            await client._pubsub.close()
            deadline = asyncio.get_running_loop().time() + 5
            # the done-callback re-opens and re-attaches the subscription
            while asyncio.get_running_loop().time() < deadline:
                await asyncio.sleep(0.2)
                if client._pubsub is not None and not client._pubsub.closed:
                    break
            await asyncio.sleep(0.2)
            await client.execute("PUBLISH", "reconn", b"back")
            assert (await asyncio.wait_for(q.get(), 5))[1] == b"back"

    asyncio.run(main())


def test_async_blocking_pop_does_not_stall_pipeline(server):
    """A parked BLPOP must ride a dedicated connection: concurrent commands
    on the shared multiplexed FIFO keep flowing while it waits."""

    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            parked = asyncio.create_task(
                client.execute("BLPOP", "aio:bq", 10, timeout=30.0)
            )
            await asyncio.sleep(0.2)  # BLPOP is now parked server-side
            # the shared pipeline must answer FAST despite the park
            t0 = asyncio.get_running_loop().time()
            await client.execute("SET", "aio:k", "v")
            got = await client.execute("GET", "aio:k")
            elapsed = asyncio.get_running_loop().time() - t0
            assert bytes(got) == b"v"
            assert elapsed < 2.0, f"pipeline stalled behind BLPOP ({elapsed:.1f}s)"
            # wake the parked pop and check its reply shape
            await client.execute("RPUSH", "aio:bq", "wake")
            key, val = await asyncio.wait_for(parked, 10.0)
            assert bytes(key) == b"aio:bq" and bytes(val) == b"wake"
            # timeout path returns nil without disturbing the client
            assert await client.execute("BLPOP", "aio:empty", 0.2, timeout=10.0) is None
            assert await client.execute("PING") in (b"PONG", "PONG")

    asyncio.run(main())


def test_async_xread_block_is_dedicated(server):
    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            parked = asyncio.create_task(
                client.execute("XREAD", "BLOCK", 10000, "STREAMS", "aio:st", "$",
                               timeout=30.0)
            )
            await asyncio.sleep(0.2)
            assert await client.execute("PING") in (b"PONG", "PONG")  # not stalled
            await client.execute("XADD", "aio:st", "*", "f", "v")
            out = await asyncio.wait_for(parked, 10.0)
            assert bytes(out[0][0]) == b"aio:st"

    asyncio.run(main())


def test_async_blocking_connection_reuse_and_close(server):
    """Clean blocking calls return their dedicated connection to the
    free-list; a timed-out one is discarded (its reply is still in
    flight); close() tears everything down."""

    async def main():
        client = await AsyncRemoteRedisson.connect(server.address)
        node = client.node
        # clean call: connection returns to the free-list and is reused
        await client.execute("RPUSH", "aio:rq", "a")
        await client.execute("BLPOP", "aio:rq", 5, timeout=30.0)
        assert len(node._dedicated_idle) == 1
        first = node._dedicated_idle[0]
        await client.execute("RPUSH", "aio:rq", "b")
        await client.execute("BLPOP", "aio:rq", 5, timeout=30.0)
        assert node._dedicated_idle and node._dedicated_idle[0] is first
        # client-side timeout: the pooled conn is consumed by the call and
        # discarded (its reply is still in flight — reuse would misalign
        # the FIFO), so the free-list ends empty
        with pytest.raises(TimeoutError):
            await client.execute("BLPOP", "aio:never", 10, timeout=0.3)
        assert first.closed
        assert not node._dedicated_idle
        # a clean call after the discard builds a FRESH pooled conn
        await client.execute("RPUSH", "aio:rq", "c")
        await client.execute("BLPOP", "aio:rq", 5, timeout=30.0)
        assert len(node._dedicated_idle) == 1
        assert node._dedicated_idle[0] is not first
        await client.aclose()
        assert not node._dedicated_idle and not node._dedicated_active

    asyncio.run(main())


def test_async_blocking_detects_bytes_command_names(server):
    """b'BLPOP' must route to a dedicated connection exactly like 'BLPOP'."""

    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            parked = asyncio.create_task(
                client.execute(b"BLPOP", "aio:bk", 10, timeout=30.0)
            )
            await asyncio.sleep(0.2)
            t0 = asyncio.get_running_loop().time()
            assert await client.execute("PING") in (b"PONG", "PONG")
            assert asyncio.get_running_loop().time() - t0 < 2.0
            await client.execute("RPUSH", "aio:bk", "w")
            _, v = await asyncio.wait_for(parked, 10.0)
            assert bytes(v) == b"w"

    asyncio.run(main())


def test_async_blocking_timeout_derives_from_block_arg(server):
    """BLPOP k 40 with no explicit client timeout must NOT be cut short by
    the 30s default — the wait derives from the command's own budget."""
    from redisson_tpu.client.aio import AsyncNodeClient

    assert AsyncNodeClient._block_budget(("BLPOP", "k", "40")) == 40.0
    assert AsyncNodeClient._block_budget(("BLPOP", "k", 0)) is None  # forever
    assert AsyncNodeClient._block_budget(
        ("XREAD", "BLOCK", "45000", "STREAMS", "k", "$")
    ) == 45.0
    assert AsyncNodeClient._block_budget((b"BRPOP", "k", "2.5")) == 2.5

    async def main():
        async with await AsyncRemoteRedisson.connect(server.address) as client:
            # error replies keep the dedicated connection reusable
            await client.execute("SET", "aio:str", "v")
            node = client.node
            with pytest.raises(RespError):
                await client.execute("BLPOP", "aio:str", 1)
            assert len(node._dedicated_idle) == 1  # FIFO aligned: reused

    asyncio.run(main())
