"""BloomFilter, BitSet, HyperLogLog, BinaryStream, RKeys behavioral depth
(RedissonBloomFilterTest 15 / BitSetTest 13 / HyperLogLogTest /
BinaryStreamTest / KeysTest) — VERDICT r3 #7, round-4 batch 8.
"""
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def remote_client():
    with ServerThread(port=0) as st:
        c = RemoteRedisson(st.address, timeout=60.0)
        yield c
        c.shutdown()


@pytest.fixture(scope="module")
def embedded_client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


@pytest.fixture(params=["embedded", "remote"])
def client(request, embedded_client, remote_client):
    return embedded_client if request.param == "embedded" else remote_client


def nm(tag):
    return f"skk-{tag}-{time.time_ns()}"


class TestBloomFilter:
    def test_init_reports_config(self, client):
        bf = client.get_bloom_filter(nm("cfg"))
        assert bf.try_init(10_000, 0.01) is True
        assert bf.try_init(99, 0.5) is False  # set-once
        assert bf.get_expected_insertions() == 10_000
        assert float(bf.get_false_probability()) == 0.01
        assert bf.get_size() > 0
        assert bf.get_hash_iterations() >= 1

    def test_add_contains_no_false_negatives(self, client):
        bf = client.get_bloom_filter(nm("fn"))
        bf.try_init(10_000, 0.01)
        keys = np.arange(2_000, dtype=np.int64) * 2654435761
        newly = bf.add_each(keys)
        assert newly.sum() >= 1_990  # probabilistic: ~all new
        assert bf.contains_each(keys).all()

    def test_false_positive_rate_bounded(self, client):
        bf = client.get_bloom_filter(nm("fp"))
        bf.try_init(10_000, 0.01)
        bf.add_each(np.arange(5_000, dtype=np.int64))
        absent = np.arange(1 << 40, (1 << 40) + 5_000, dtype=np.int64)
        fp = bf.contains_each(absent).mean()
        assert fp < 0.03  # target p=0.01 at half fill

    def test_count_estimate(self, client):
        bf = client.get_bloom_filter(nm("cnt"))
        bf.try_init(100_000, 0.01)
        bf.add_each(np.arange(10_000, dtype=np.int64))
        assert abs(bf.count() - 10_000) / 10_000 < 0.1

    def test_object_value_add(self, client):
        bf = client.get_bloom_filter(nm("obj"))
        bf.try_init(1_000, 0.01)
        assert bf.add("string-key") is True
        assert bf.contains("string-key") is True
        assert bf.contains("never-added") in (False, True)  # fp allowed
        assert bf.add("string-key") is False  # already present


class TestBitSet:
    def test_bit_ops(self, client):
        bs = client.get_bit_set(nm("ops"))
        assert bs.set(7) is False     # previous value
        assert bs.set(7) is True
        assert bs.get(7) is True and bs.get(8) is False
        assert bs.cardinality() == 1
        assert bs.length() == 8       # highest set bit + 1

    def test_batch_forms(self, client):
        bs = client.get_bit_set(nm("batch"))
        idx = np.array([1, 3, 5], np.int64)
        old = bs.set_each(idx)
        assert not np.asarray(old).any()
        got = bs.get_each(np.array([1, 2, 3, 4, 5], np.int64))
        assert list(np.asarray(got).astype(bool)) == [True, False, True, False, True]

    def test_logic_ops(self, client):
        a = client.get_bit_set(nm("la"))
        b = client.get_bit_set(nm("lb"))
        a.set_each(np.array([1, 2], np.int64))
        b.set_each(np.array([2, 3], np.int64))
        a.or_(b.name)
        assert a.cardinality() == 3
        a.and_(b.name)
        assert a.cardinality() == 2
        a.xor(b.name)
        assert a.cardinality() == 0

    def test_byte_array_roundtrip(self, embedded_client):
        bs = embedded_client.get_bit_set(nm("bytes"))
        bs.set(0)
        bs.set(9)
        blob = bs.to_byte_array()
        bs2 = embedded_client.get_bit_set(nm("bytes2"))
        bs2.from_byte_array(blob)
        assert bs2.get(0) and bs2.get(9) and bs2.cardinality() == 2


class TestHyperLogLog:
    def test_add_count(self, client):
        h = client.get_hyper_log_log(nm("cnt"))
        h.add_all(np.arange(10_000, dtype=np.int64))
        assert abs(h.count() - 10_000) / 10_000 < 0.05

    def test_merge_with(self, client):
        a = client.get_hyper_log_log(nm("ma"))
        b = client.get_hyper_log_log(nm("mb"))
        a.add_all(np.arange(0, 5_000, dtype=np.int64))
        b.add_all(np.arange(2_500, 7_500, dtype=np.int64))
        assert abs(a.count_with(b.name) - 7_500) / 7_500 < 0.05
        a.merge_with(b.name)
        assert abs(a.count() - 7_500) / 7_500 < 0.05
        assert abs(b.count() - 5_000) / 5_000 < 0.05  # src untouched

    def test_object_values(self, client):
        h = client.get_hyper_log_log(nm("objs"))
        for v in ("a", "b", "a", "c"):
            h.add(v)
        assert h.count() == 3


class TestBinaryStream:
    def test_write_read(self, client):
        b = client.get_binary_stream(nm("wr"))
        payload = b"\x00binary\xffdata"
        assert b.write(0, payload) == len(payload)  # SETRANGE-style
        assert b.get() == payload
        b.append(b"-more")
        assert b.get() == payload + b"-more"
        assert b.size() == len(payload) + 5
        assert b.read(1, 6) == b"binary"
        # a positional write past the end zero-fills the gap
        b2 = client.get_binary_stream(nm("wr2"))
        b2.write(3, b"x")
        assert b2.get() == b"\x00\x00\x00x"

    def test_set_replaces(self, client):
        b = client.get_binary_stream(nm("set"))
        b.set(b"old")
        b.set(b"new")
        assert b.get() == b"new"


class TestKeys:
    def test_keys_pattern_and_count(self, remote_client):
        ks = remote_client.get_keys()
        tag = nm("kp")
        for i in range(3):
            remote_client.get_bucket(f"{tag}:{i}").set(i)
        found = ks.get_keys(f"{tag}:*")
        assert len(found) == 3
        assert ks.count_exists(f"{tag}:0", f"{tag}:zz") == 1

    def test_delete_by_pattern(self, remote_client):
        ks = remote_client.get_keys()
        tag = nm("dp")
        for i in range(4):
            remote_client.get_bucket(f"{tag}:{i}").set(i)
        assert ks.delete_by_pattern(f"{tag}:*") == 4
        assert ks.get_keys(f"{tag}:*") == []

    def test_expire_via_keys(self, remote_client):
        ks = remote_client.get_keys()
        name = nm("exp")
        remote_client.get_bucket(name).set("v")
        assert ks.expire(name, 30.0) is True
        remain = ks.remain_time_to_live(name)
        assert remain is not None and 25.0 < remain <= 30.0

    def test_embedded_keys_surface(self, embedded_client):
        ks = embedded_client.get_keys()
        tag = nm("emb")
        embedded_client.get_bucket(f"{tag}:a").set(1)
        assert f"{tag}:a" in ks.get_keys(f"{tag}:*")
        assert ks.delete(f"{tag}:a", f"{tag}:zz") == 1
