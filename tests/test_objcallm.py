"""OBJCALLM: the batched object wire (one frame + one pickle for many ops)."""
import numpy as np
import pytest

from redisson_tpu.harness import ClusterRunner, free_port
from redisson_tpu.server.server import ServerThread


def test_objcallm_single_node():
    st = ServerThread(port=free_port()).start()
    try:
        from redisson_tpu.client.remote import RemoteRedisson

        c = RemoteRedisson(f"127.0.0.1:{st.server.port}", timeout=60.0)
        ops = []
        for i in range(50):
            ops.append(("get_map", "m1", "put", (f"k{i}", i), {}))
        ops.append(("get_map", "m1", "size", (), {}))
        ops.append(("get_set", "s1", "add", ("x",), {}))
        ops.append(("get_atomic_long", "al", "add_and_get", (7,), {}))
        ops.append(("get_map", "m1", "definitely_missing", (), {}))  # error row
        res = c.objcall_many(ops)
        assert res[50] == 50  # size after 50 puts
        assert res[51] is True
        assert res[52] == 7
        assert isinstance(res[53], Exception)
        assert c.get_map("m1").get("k7") == 7
        c.shutdown()
    finally:
        st.stop()


def test_objcallm_cluster_groups_per_shard():
    runner = ClusterRunner(masters=3).run()
    try:
        client = runner.client(scan_interval=0)
        ops = []
        for i in range(60):
            ops.append(("get_map", f"cm-{i}", "put", ("k", i), {}))
        for i in range(60):
            ops.append(("get_map", f"cm-{i}", "get", ("k",), {}))
        res = client.objcall_many(ops)
        assert res[:60] == [None] * 60  # put returns old value (None)
        assert res[60:] == list(range(60))
        # records spread over all three shards
        per = [len(m.server.server.engine.store) for m in runner.masters]
        assert all(p > 0 for p in per)
        client.shutdown()
    finally:
        runner.shutdown()


def test_objcallm_cluster_survives_stale_routing():
    """Per-op MOVED rows re-route instead of surfacing as errors."""
    from redisson_tpu.server.migration import migrate_slots
    from redisson_tpu.utils.crc16 import calc_slot

    runner = ClusterRunner(masters=2).run()
    try:
        client = runner.client(scan_interval=0)
        names = [f"st-{i}" for i in range(30)]
        client.objcall_many([("get_bucket", n, "set", (i,), {}) for i, n in enumerate(names)])
        lo0, hi0 = runner.slot_ranges[0]
        slots = sorted({
            calc_slot(n.encode()) for n in names
            if lo0 <= calc_slot(n.encode()) <= hi0
        })
        migrate_slots(runner.masters[0].address, runner.masters[1].address, slots)
        # client's view is stale: per-op MOVED rows must still resolve
        res = client.objcall_many([("get_bucket", n, "get", (), {}) for n in names])
        assert res == list(range(30))
        client.shutdown()
    finally:
        runner.shutdown()
