"""Journaled resumable slot migration (ISSUE 4 tentpole): write-ahead
journal mechanics, kill-the-coordinator-at-every-phase resume property,
fencing epochs, and the rollback-must-not-mask-the-original-error
satellite.

The acceptance property lives in ``test_kill_coordinator_at_every_phase``:
for EVERY journal phase, killing the coordinator right after that phase's
entry and calling ``resume_migrations()`` ends with all slots STABLE on
exactly one owner, the record readable at its exact value, and the journal
terminal.

ISSUE 13 extends the property to the RECEIVING side: ``ImportJournal``
mechanics, the target's boot-time batch replay, the double-kill matrix
(coordinator AND target dead at the same journal phase), and the
no-rollback-into-a-dead-target policy.
"""
import os

import pytest

from redisson_tpu.harness import ClusterRunner, _exec
from redisson_tpu.net.resp import RespError
from redisson_tpu.server import migration as mig
from redisson_tpu.server.migration import (
    CoordinatorKilled,
    migrate_slots,
    rearm_recovery,
    resume_migrations,
)
from redisson_tpu.server.migration_journal import ImportJournal, MigrationJournal
from redisson_tpu.utils.crc16 import calc_slot


# -- journal file mechanics ---------------------------------------------------

def test_journal_append_open_roundtrip(tmp_path):
    j = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    j.append("PLANNED", source="a:1", target="b:2", slots=[5], epoch=j.epoch,
             old_view=[[0, 10, "h", 1, "n"]], new_view=[[0, 10, "h", 2, "m"]])
    j.append("WINDOW_OPEN")
    j.append("DRAINING", moved=3, sweep=1, batch=3)
    j.append("DRAINING", moved=3, sweep=2, batch=0)
    back = MigrationJournal.open(j.path)
    assert [e["phase"] for e in back.entries] == [
        "PLANNED", "WINDOW_OPEN", "DRAINING", "DRAINING",
    ]
    assert back.phase == "DRAINING"
    assert back.latest("moved") == 3
    assert back.entry("PLANNED")["slots"] == [5]
    assert not back.is_terminal()
    back.append("STABLE", moved=3)
    assert MigrationJournal.open(j.path).is_terminal()


def test_journal_torn_tail_line_dropped(tmp_path):
    j = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    j.append("PLANNED", source="a:1", target="b:2", slots=[1], epoch=j.epoch,
             old_view=[], new_view=[])
    j.append("WINDOW_OPEN")
    # simulate a crash mid-append: the last line is half-written
    raw = open(j.path, "rb").read()
    with open(j.path, "wb") as f:
        f.write(raw[: len(raw) - 7])
    back = MigrationJournal.open(j.path)
    assert [e["phase"] for e in back.entries] == ["PLANNED"]
    # a corrupt line also invalidates everything after it (WAL prefix rule)
    with open(j.path, "wb") as f:
        f.write(raw.split(b"\n")[0] + b"XX\n" + raw.split(b"\n")[1] + b"\n")
    assert MigrationJournal.open(j.path).entries == []


def test_journal_epoch_allocation_is_monotonic(tmp_path):
    a = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    a.append("PLANNED", epoch=a.epoch, source="a:1", target="b:2", slots=[1],
             old_view=[], new_view=[])
    a.append("STABLE")
    b = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    assert b.epoch == a.epoch + 1
    # terminal journals still hold their epoch: a third allocation sees both
    b.append("PLANNED", epoch=b.epoch, source="a:1", target="b:2", slots=[1],
             old_view=[], new_view=[])
    c = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    assert c.epoch == b.epoch + 1


def test_journal_rejects_unknown_phase(tmp_path):
    j = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    with pytest.raises(ValueError, match="unknown journal phase"):
        j.append("EXPLODED")


def test_resume_on_empty_or_missing_dir(tmp_path):
    assert resume_migrations(str(tmp_path / "nope")) == []
    assert resume_migrations(str(tmp_path)) == []


def test_resume_terminalizes_torn_first_line_journal(tmp_path):
    """A crash mid-append of the very FIRST entry leaves a journal with
    zero intact lines: nothing ever ran, but resume must terminalize it so
    it stops reading as in-flight."""
    j = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    with open(j.path, "wb") as f:
        f.write(b'{"phase":"PLANNED"')  # torn: no CRC separator, no newline
    assert [x.migration_id for x in MigrationJournal.in_flight(str(tmp_path))]
    results = resume_migrations(str(tmp_path))
    assert [r["action"] for r in results] == ["rolled_back"]
    assert MigrationJournal.in_flight(str(tmp_path)) == []


# -- the kill-the-coordinator property ---------------------------------------

@pytest.fixture()
def cluster2():
    runner = ClusterRunner(masters=2).run()
    yield runner
    runner.shutdown()


def _owner_index(runner, slot: int) -> int:
    return next(
        i for i, (lo, hi) in enumerate(runner.slot_ranges) if lo <= slot <= hi
    )


def test_kill_coordinator_at_every_phase(cluster2, tmp_path):
    """ISSUE 4 acceptance: for every journal phase, kill after that phase,
    resume, and end with all slots STABLE on exactly one owner, zero acked
    loss, and the record's exact contents intact."""
    client = cluster2.client(scan_interval=0)
    jd = str(tmp_path / "journal")
    try:
        client.get_bucket("mig-key").set("payload")
        slot = calc_slot(b"mig-key")
        for phase, expect in [
            ("PLANNED", "rolled_back"),
            ("WINDOW_OPEN", "completed"),
            ("DRAINING:1", "completed"),
            ("VIEW_COMMITTED", "completed"),
        ]:
            owner = next(
                m for m in cluster2.masters
                if m.server.server.engine.store.exists("mig-key")
            )
            other = next(m for m in cluster2.masters if m is not owner)
            with pytest.raises(CoordinatorKilled):
                migrate_slots(owner.address, other.address, [slot],
                              journal_dir=jd, crash_after=phase)
            results = resume_migrations(jd)
            assert [r["action"] for r in results] == [expect], (phase, results)
            assert not MigrationJournal.in_flight(jd)
            # window fully closed on both ends — no slot left non-STABLE
            for node in cluster2.masters:
                srv = node.server.server
                assert not srv.migrating_slots, (phase, srv.migrating_slots)
                assert not srv.importing_slots, (phase, srv.importing_slots)
            # exactly one owner holds the record, value intact
            holders = [
                m for m in cluster2.masters
                if m.server.server.engine.store.exists("mig-key")
            ]
            assert len(holders) == 1, phase
            expected_holder = owner if expect == "rolled_back" else other
            assert holders[0] is expected_holder, phase
            client.refresh_topology()
            assert client.get_bucket("mig-key").get() == "payload", phase
    finally:
        client.shutdown()


def test_resume_is_idempotent(cluster2, tmp_path):
    """A crash DURING resume (simulated by resuming twice) converges: the
    second pass finds nothing in flight."""
    client = cluster2.client(scan_interval=0)
    jd = str(tmp_path / "journal")
    try:
        client.get_bucket("idem-key").set("v")
        slot = calc_slot(b"idem-key")
        si = _owner_index(cluster2, slot)
        with pytest.raises(CoordinatorKilled):
            migrate_slots(cluster2.masters[si].address,
                          cluster2.masters[1 - si].address, [slot],
                          journal_dir=jd, crash_after="WINDOW_OPEN")
        first = resume_migrations(jd)
        assert [r["action"] for r in first] == ["completed"]
        assert resume_migrations(jd) == []  # nothing left in flight
        client.refresh_topology()
        assert client.get_bucket("idem-key").get() == "v"
    finally:
        client.shutdown()


def test_journaled_migration_without_crash_records_stable(cluster2, tmp_path):
    client = cluster2.client(scan_interval=0)
    jd = str(tmp_path / "journal")
    try:
        client.get_bucket("jrn-key").set("v")
        slot = calc_slot(b"jrn-key")
        si = _owner_index(cluster2, slot)
        moved = migrate_slots(cluster2.masters[si].address,
                              cluster2.masters[1 - si].address, [slot],
                              journal_dir=jd)
        assert moved >= 1
        journals = MigrationJournal.scan(jd)
        assert len(journals) == 1
        assert journals[0].phase == "STABLE"
        phases = [e["phase"] for e in journals[0].entries]
        assert phases[0] == "PLANNED" and "WINDOW_OPEN" in phases
        assert "VIEW_COMMITTED" in phases and phases[-1] == "STABLE"
        assert resume_migrations(jd) == []
    finally:
        client.shutdown()


# -- fencing epochs -----------------------------------------------------------

def test_stale_epoch_rejected_idempotent_epoch_accepted(cluster2):
    node = cluster2.masters[0]
    peer = cluster2.masters[1]
    lo, _hi = cluster2.slot_ranges[0]
    with node.server.client() as c:
        _exec(c, "CLUSTER", "SETSLOT", lo, "MIGRATING", peer.address,
              "EPOCH", 5)
        # same epoch re-issue = the resume path: accepted
        _exec(c, "CLUSTER", "SETSLOT", lo, "MIGRATING", peer.address,
              "EPOCH", 5)
        # a STALE coordinator (lower epoch) is fenced out
        reply = c.execute("CLUSTER", "SETSLOT", lo, "STABLE", "EPOCH", 4)
        assert isinstance(reply, RespError)
        assert str(reply).startswith("STALEEPOCH")
        # MIGRATESLOTS is fenced by the same per-slot epoch
        reply = c.execute("CLUSTER", "MIGRATESLOTS", "EPOCH", 4, lo)
        assert isinstance(reply, RespError)
        assert str(reply).startswith("STALEEPOCH")
        # a NEWER epoch supersedes and closes the window
        _exec(c, "CLUSTER", "SETSLOT", lo, "STABLE", "EPOCH", 6)
    assert not node.server.server.migrating_slots
    # epoch-less legacy traffic stays unfenced (manual admin path)
    with node.server.client() as c:
        _exec(c, "CLUSTER", "SETSLOT", lo, "MIGRATING", peer.address)
        _exec(c, "CLUSTER", "SETSLOT", lo, "STABLE")


# -- rollback exception chaining (satellite) ----------------------------------

def test_rollback_failure_does_not_mask_original_error(cluster2, monkeypatch):
    """A `_rollback` that itself raises must surface the ORIGINAL failure
    to the caller, with the rollback failure chained onto it."""
    primary = RuntimeError("drain exploded")
    rb_err = RuntimeError("rollback also exploded")

    def boom_drain(self, moved=0):
        raise primary

    def boom_rollback(*a, **kw):
        raise rb_err

    monkeypatch.setattr(mig._MigrationRun, "_phase_drain", boom_drain)
    monkeypatch.setattr(mig, "_rollback", boom_rollback)
    slot = cluster2.slot_ranges[0][0]
    with pytest.raises(RuntimeError) as exc:
        migrate_slots(cluster2.masters[0].address,
                      cluster2.masters[1].address, [slot])
    assert exc.value is primary          # the FIRST failure reaches the caller
    assert exc.value.__cause__ is rb_err  # the rollback failure rides along


def test_rollback_success_reraises_original(cluster2, monkeypatch):
    primary = RuntimeError("drain exploded")

    def boom_drain(self, moved=0):
        raise primary

    monkeypatch.setattr(mig._MigrationRun, "_phase_drain", boom_drain)
    slot = cluster2.slot_ranges[0][0]
    with pytest.raises(RuntimeError) as exc:
        migrate_slots(cluster2.masters[0].address,
                      cluster2.masters[1].address, [slot])
    assert exc.value is primary
    # rollback really ran: no window left behind
    for node in cluster2.masters:
        srv = node.server.server
        assert not srv.migrating_slots and not srv.importing_slots


# -- journal GC (long-lived coordinators) -------------------------------------

def _terminal_journal(tmp_path, phase="STABLE"):
    j = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    j.append("PLANNED", source="a:1", target="b:2", slots=[1], epoch=j.epoch,
             old_view=[], new_view=[])
    j.append(phase)
    return j


def test_gc_removes_only_old_terminal_journals(tmp_path):
    old = [
        _terminal_journal(tmp_path, "STABLE" if i % 2 else "ROLLED_BACK")
        for i in range(6)
    ]
    inflight = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    inflight.append("PLANNED", source="a:1", target="b:2", slots=[2],
                    epoch=inflight.epoch, old_view=[], new_view=[])
    inflight.append("WINDOW_OPEN")
    newer = [_terminal_journal(tmp_path) for _ in range(2)]
    removed = MigrationJournal.gc(str(tmp_path), keep=3)
    # the oldest 5 terminal journals go; the newest 3 terminal stay
    assert sorted(removed) == sorted(j.path for j in old[:5])
    kept = MigrationJournal.scan(str(tmp_path))
    assert {j.path for j in kept} == {old[5].path, inflight.path,
                                      newer[0].path, newer[1].path}
    # the in-flight journal is NEVER touched, even with keep=1
    MigrationJournal.gc(str(tmp_path), keep=1)
    assert inflight.path in {j.path for j in MigrationJournal.scan(str(tmp_path))}
    # epoch allocation stays monotonic after pruning (the newest terminal
    # journal survives, so max-epoch never decreases)
    nxt = MigrationJournal.create(str(tmp_path), "a:1", "b:2")
    assert nxt.epoch > newer[1].epoch


def test_gc_rejects_keep_zero(tmp_path):
    _terminal_journal(tmp_path)
    with pytest.raises(ValueError, match="keep"):
        MigrationJournal.gc(str(tmp_path), keep=0)


def test_gc_empty_or_missing_dir(tmp_path):
    assert MigrationJournal.gc(str(tmp_path / "nope"), keep=4) == []
    assert MigrationJournal.gc(str(tmp_path), keep=4) == []


def test_resume_migrations_invokes_gc(tmp_path):
    for _ in range(5):
        _terminal_journal(tmp_path)
    assert resume_migrations(str(tmp_path), gc_keep=2) == []
    assert len(MigrationJournal.scan(str(tmp_path))) == 2
    # gc_keep=None keeps everything
    for _ in range(3):
        _terminal_journal(tmp_path)
    resume_migrations(str(tmp_path), gc_keep=None)
    assert len(MigrationJournal.scan(str(tmp_path))) == 5


# -- import-side journal (ISSUE 13 tentpole) ----------------------------------

def test_import_journal_roundtrip_and_suffix_isolation(tmp_path):
    """ImportJournal batches survive a reopen byte-for-byte, the two
    journal kinds never appear in each other's scans, and terminalization
    sticks."""
    jd = str(tmp_path)
    j = ImportJournal.open_for(jd, "127.0.0.1:7002", 3, source="127.0.0.1:7001")
    assert j.phase == "OPENED" and j.epoch == 3
    assert j.target == "127.0.0.1:7002" and j.source == "127.0.0.1:7001"
    j.append_batch(b"\x00binary\xffblob-1")
    j.append_batch(b"blob-2")
    back = ImportJournal.open(j.path)
    assert back.batch_blobs() == [b"\x00binary\xffblob-1", b"blob-2"]
    assert back.batch_count() == 2 and not back.is_terminal()
    # a coordinator journal in the same dir: the scans stay disjoint
    cj = _terminal_journal(tmp_path)
    assert {x.path for x in ImportJournal.scan(jd)} == {j.path}
    assert {x.path for x in MigrationJournal.scan(jd)} == {cj.path}
    back.append("STABLE", settled=True)
    assert ImportJournal.open(j.path).is_terminal()
    assert ImportJournal.in_flight(jd) == []
    # open_for on an existing journal does NOT re-OPEN it
    again = ImportJournal.open_for(jd, "127.0.0.1:7002", 3)
    assert [e["phase"] for e in again.entries].count("OPENED") == 1


def test_import_journal_rejects_coordinator_phases(tmp_path):
    j = ImportJournal.open_for(str(tmp_path), "a:1", 1)
    with pytest.raises(ValueError, match="unknown journal phase"):
        j.append("DRAINING")


def test_resume_terminalizes_torn_import_journal(tmp_path):
    """A crash mid-append of the OPENED line leaves an import journal with
    zero intact entries — no node can claim it (its target is unreadable)
    and no batch ever became durable, so resume_migrations settles it
    (else it reads in-flight forever and gc pins its coordinator
    journal)."""
    jd = str(tmp_path)
    path = ImportJournal.path_for(jd, "t:1", 5)
    with open(path, "wb") as f:
        f.write(b'{"phase":"OPENED"')  # torn: no CRC separator, no newline
    assert ImportJournal.in_flight(jd)
    assert resume_migrations(jd) == []
    assert ImportJournal.in_flight(jd) == []
    assert ImportJournal.open(path).phase == "ROLLED_BACK"


def test_gc_sweeps_terminal_import_journals_protects_inflight(tmp_path):
    """Satellite: gc prunes a target's TERMINAL import journals by the same
    keep policy, never an in-flight one — and a coordinator journal whose
    epoch still has an in-flight import journal is kept regardless of
    age (the target's boot replay needs it)."""
    jd = str(tmp_path)
    # epoch 1..6: terminal coordinator journals with terminal import mirrors
    for _ in range(6):
        cj = _terminal_journal(tmp_path)
        ij = ImportJournal.open_for(jd, "t:1", cj.epoch, source="s:1")
        ij.append_batch(b"x")
        ij.append("STABLE", settled=True)
    # epoch 7: TERMINAL coordinator journal but the import journal is still
    # in flight (target died before settling) — both files must survive gc
    cj7 = _terminal_journal(tmp_path)
    inflight = ImportJournal.open_for(jd, "t:1", cj7.epoch, source="s:1")
    inflight.append_batch(b"y")
    removed = MigrationJournal.gc(jd, keep=2)
    kept_coord = {j.path for j in MigrationJournal.scan(jd)}
    kept_imports = {j.path for j in ImportJournal.scan(jd)}
    assert cj7.path in kept_coord, "protected coordinator journal pruned"
    assert inflight.path in kept_imports, "in-flight import journal pruned"
    # keep=2 applies per kind: 2 terminal imports survive (plus in-flight),
    # and of the 6 unprotected terminal coordinator journals 2 survive
    assert len(kept_imports) == 3
    assert len(kept_coord) == 3  # cj7 + newest 2 unprotected
    assert removed and all(p.endswith((".journal", ".import")) for p in removed)
    # after the import journal terminalizes, the next sweep may prune both
    inflight.append("STABLE", settled=True)
    MigrationJournal.gc(jd, keep=1)
    assert len([j for j in ImportJournal.scan(jd) if j.is_terminal()]) == 1


@pytest.fixture()
def cluster2j(tmp_path):
    """2 masters + a shared journal dir on every node: imports journal."""
    jd = str(tmp_path / "journal")
    runner = ClusterRunner(masters=2, journal_dir=jd).run()
    yield runner, jd
    runner.shutdown()


def test_double_kill_matrix_in_process(cluster2j):
    """ISSUE 13 acceptance (in-process leg): at every journal phase, kill
    the coordinator AND the migration TARGET (fresh engine on the same
    port — its memory dies like a SIGKILL), replay the import journal at
    'boot' via rearm_recovery, resume — zero acked loss, exactly-one-owner,
    all slots STABLE, import journals terminal."""
    runner, jd = cluster2j
    client = runner.client(scan_interval=0)
    try:
        client.get_bucket("dk-key").set("payload")
        slot = calc_slot(b"dk-key")
        for phase, expect in [
            ("PLANNED", "rolled_back"),
            ("WINDOW_OPEN", "completed"),
            ("DRAINING:1", "completed"),
            ("VIEW_COMMITTED", "completed"),
        ]:
            owner = next(
                m for m in runner.masters
                if m.server.server.engine.store.exists("dk-key")
            )
            other = next(m for m in runner.masters if m is not owner)
            with pytest.raises(CoordinatorKilled):
                migrate_slots(owner.address, other.address, [slot],
                              journal_dir=jd, crash_after=phase)
            # the TARGET dies too: restart_node gives it a FRESH engine on
            # the same port — the drained records now exist nowhere but its
            # import journal
            runner.stop_node(other)
            runner.restart_node(other)
            rearm_recovery(other.server.server, jd)
            results = resume_migrations(jd)
            assert [r["action"] for r in results] == [expect], (phase, results)
            assert not MigrationJournal.in_flight(jd), phase
            assert not ImportJournal.in_flight(jd), phase
            holders = [
                m for m in runner.masters
                if m.server.server.engine.store.exists("dk-key")
            ]
            assert len(holders) == 1, phase
            assert holders[0] is (owner if expect == "rolled_back" else other)
            for node in runner.masters:
                srv = node.server.server
                assert not srv.migrating_slots and not srv.importing_slots
                assert srv.import_journal_rows() == [], phase
            client.refresh_topology()
            assert client.get_bucket("dk-key").get() == "payload", phase
    finally:
        client.shutdown()


def test_dead_target_leaves_journal_resumable_from_either_side(cluster2j):
    """Resume with the target still DOWN reports 'failed' and leaves the
    journal in flight; once the target is back (fresh engine + import
    replay) the next resume drives the pair to STABLE — 'from either
    side'."""
    runner, jd = cluster2j
    client = runner.client(scan_interval=0)
    try:
        client.get_bucket("dt-key").set("payload")
        slot = calc_slot(b"dt-key")
        owner = next(
            m for m in runner.masters
            if m.server.server.engine.store.exists("dt-key")
        )
        other = next(m for m in runner.masters if m is not owner)
        with pytest.raises(CoordinatorKilled):
            migrate_slots(owner.address, other.address, [slot],
                          journal_dir=jd, crash_after="DRAINING:1")
        runner.stop_node(other)  # the target is simply GONE
        results = resume_migrations(jd)
        assert [r["action"] for r in results] == ["failed"], results
        assert len(MigrationJournal.in_flight(jd)) == 1
        runner.restart_node(other)
        rearm_recovery(other.server.server, jd)
        results = resume_migrations(jd)
        assert [r["action"] for r in results] == ["completed"], results
        assert not MigrationJournal.in_flight(jd)
        client.refresh_topology()
        assert client.get_bucket("dt-key").get() == "payload"
    finally:
        client.shutdown()


def test_live_rollback_skipped_when_target_unreachable(cluster2j, monkeypatch):
    """The no-fork policy: a journaled migration whose drain fails with an
    UNREACHABLE target must NOT roll back (the target may hold journaled
    batches whose source copies are deleted) — the journal stays in flight
    for a forward resume.  A reachable target still rolls back."""
    runner, jd = cluster2j
    primary = RuntimeError("drain exploded")

    def boom_drain(self, moved=0):
        raise primary

    monkeypatch.setattr(mig._MigrationRun, "_phase_drain", boom_drain)
    slot = runner.slot_ranges[0][0]
    src, dst = runner.masters[0], runner.masters[1]
    # reachable target: the historical rollback runs and terminalizes
    with pytest.raises(RuntimeError):
        migrate_slots(src.address, dst.address, [slot], journal_dir=jd)
    assert not MigrationJournal.in_flight(jd)
    assert MigrationJournal.scan(jd)[-1].phase == "ROLLED_BACK"
    # unreachable target: no rollback — in flight, window still armed
    monkeypatch.setattr(
        mig._MigrationRun, "_target_reachable", lambda self: False
    )
    with pytest.raises(RuntimeError):
        migrate_slots(src.address, dst.address, [slot], journal_dir=jd)
    inflight = MigrationJournal.in_flight(jd)
    assert [j.phase for j in inflight] == ["WINDOW_OPEN"]
    assert slot in src.server.server.migrating_slots
    # forward resume converges once the 'dead' target answers again
    monkeypatch.undo()
    results = resume_migrations(jd)
    assert [r["action"] for r in results] == ["completed"], results
    assert not src.server.server.migrating_slots


def test_cluster_windows_reports_import_journal_rows(cluster2j):
    """Satellite: CLUSTER WINDOWS on the TARGET shows the in-flight import
    journal (epoch, phase, batches, source) mid-migration, and the rows
    disappear when the migration settles."""
    runner, jd = cluster2j
    client = runner.client(scan_interval=0)
    try:
        client.get_bucket("cw-key").set("v")
        slot = calc_slot(b"cw-key")
        owner = next(
            m for m in runner.masters
            if m.server.server.engine.store.exists("cw-key")
        )
        other = next(m for m in runner.masters if m is not owner)
        with pytest.raises(CoordinatorKilled):
            migrate_slots(owner.address, other.address, [slot],
                          journal_dir=jd, crash_after="DRAINING:1")
        with other.server.client() as c:
            rows = [r for r in c.execute("CLUSTER", "WINDOWS")
                    if bytes(r[0]) == b"IMPORTJOURNAL"]
        assert len(rows) == 1
        _tag, epoch, phase, batches, source = rows[0]
        assert int(epoch) == MigrationJournal.in_flight(jd)[0].epoch
        assert bytes(phase) == b"BATCH" and int(batches) >= 1
        assert bytes(source).decode() == owner.address
        resume_migrations(jd)
        for node in runner.masters:
            with node.server.client() as c:
                assert c.execute("CLUSTER", "WINDOWS") == [], node.address
    finally:
        client.shutdown()


def test_batched_drain_one_journal_fsync_per_batch(cluster2j):
    """Batch-coalesced drains (ISSUE 14 satellite): a journaled migration
    ships DRAIN_BATCH_RECORDS records per IMPORTRECORDS frame, so the
    target journals (= fsyncs) once per BATCH, not once per record — and
    the journal-before-ack contract still holds: every drained record is
    inside some journaled frame."""
    runner, jd = cluster2j
    client = runner.client(scan_interval=0)
    try:
        tag = "{bdrain}"
        n = 10
        for i in range(n):
            client.get_bucket(f"{tag}:r{i}").set(f"v{i}")
        slot = calc_slot(tag.encode())
        owner = next(
            m for m in runner.masters
            if m.server.server.engine.store.exists(f"{tag}:r0")
        )
        other = next(m for m in runner.masters if m is not owner)
        for m in runner.masters:
            m.server.server.DRAIN_BATCH_RECORDS = 4
        migrate_slots(owner.address, other.address, [slot], journal_dir=jd)
        # every record landed on the target (zero loss through the batches)
        for i in range(n):
            assert other.server.server.engine.store.exists(f"{tag}:r{i}")
            assert not owner.server.server.engine.store.exists(f"{tag}:r{i}")
        # the target's import journal holds ceil(10/4) = 3 batches: one
        # fsync per FRAME, not per record
        journals = [j for j in ImportJournal.scan(jd) if j.batch_count() > 0]
        assert len(journals) == 1, [j.path for j in journals]
        assert journals[0].batch_count() == 3, journals[0].batch_count()
        client.refresh_topology()
        assert client.get_bucket(f"{tag}:r7").get() == "v7"
    finally:
        client.shutdown()


def test_batched_drain_reships_nothing_on_empty_followup_sweep(cluster2j):
    """The drain loop's convergence contract survives batching: the second
    MIGRATESLOTS sweep finds nothing and ships no frame."""
    runner, jd = cluster2j
    client = runner.client(scan_interval=0)
    try:
        client.get_bucket("{bd2}:x").set("v")
        slot = calc_slot(b"{bd2}")
        owner = next(
            m for m in runner.masters
            if m.server.server.engine.store.exists("{bd2}:x")
        )
        other = next(m for m in runner.masters if m is not owner)
        migrate_slots(owner.address, other.address, [slot], journal_dir=jd)
        journals = [j for j in ImportJournal.scan(jd) if j.batch_count() > 0]
        assert len(journals) == 1 and journals[0].batch_count() == 1
    finally:
        client.shutdown()
