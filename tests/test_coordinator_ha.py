"""Coordinator HA: N FailoverCoordinators, leadership via FencedLock with
fencing tokens on view writes (VERDICT r2 #7; reference: the sentinel layer
tolerating sentinel death, connection/SentinelConnectionManager.java:210-430)."""
import time

import pytest

from redisson_tpu.harness import ClusterRunner
from redisson_tpu.net.resp import RespError
from redisson_tpu.server.monitor import HAFailoverCoordinator
from redisson_tpu.utils.crc16 import calc_slot


def _lock_name_in_range(lo: int, hi: int) -> str:
    """A {hashtag}'d leader-lock name pinned to [lo, hi] so leadership
    survives the OTHER master's death."""
    for i in range(10_000):
        name = f"{{lk{i}}}leader"
        if lo <= calc_slot(f"lk{i}".encode()) <= hi:
            return name
    raise AssertionError("no hashtag found for range")


@pytest.fixture()
def grid():
    runner = ClusterRunner(masters=2, replicas_per_master=1).run()
    yield runner
    runner.shutdown()


def _wait(cond, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if cond():
            return
        time.sleep(0.1)
    raise AssertionError(msg)


def test_single_leader_among_standbys(grid):
    lo1, hi1 = grid.slot_ranges[1]
    lock_name = _lock_name_in_range(lo1, hi1)
    coords = [
        HAFailoverCoordinator(
            grid.view_tuples(), grid.seeds(), check_interval=0.2, lease=2.0,
            lock_name=lock_name,
        ).start()
        for _ in range(3)
    ]
    try:
        _wait(
            lambda: sum(c.is_leader.is_set() for c in coords) == 1,
            20, "expected exactly one leader",
        )
        time.sleep(1.0)
        assert sum(c.is_leader.is_set() for c in coords) == 1
    finally:
        for c in coords:
            c.stop()


def test_killed_leader_mid_failover_standby_converges(grid):
    """THE chaos criterion: kill master0, then kill the ACTIVE coordinator
    before/while it handles the failover; the standby must take over and
    still converge the cluster."""
    lo1, hi1 = grid.slot_ranges[1]
    lock_name = _lock_name_in_range(lo1, hi1)
    a = HAFailoverCoordinator(
        grid.view_tuples(), grid.seeds(), check_interval=0.2, lease=1.5,
        lock_name=lock_name,
    ).start()
    b = HAFailoverCoordinator(
        grid.view_tuples(), grid.seeds(), check_interval=0.2, lease=1.5,
        lock_name=lock_name,
    ).start()
    client = grid.client(scan_interval=1.0)
    try:
        _wait(lambda: a.is_leader.is_set() or b.is_leader.is_set(), 20, "no leader")
        leader, standby = (a, b) if a.is_leader.is_set() else (b, a)
        # seed a key owned by master0 so we can prove serving resumes
        lo0, hi0 = grid.slot_ranges[0]
        key = next(
            f"ha-{i}" for i in range(10_000)
            if lo0 <= calc_slot(f"ha-{i}".encode()) <= hi0
        )
        client.get_bucket(key).set("before")
        client.sync_replication([key])  # deterministic: replica has the write
        # kill master0 and IMMEDIATELY crash the leader (no unlock): the
        # failover is at best half-done when the leader dies
        grid.stop_master(0)
        leader.kill()
        # standby must acquire after lease lapse and drive the promotion
        _wait(lambda: standby.is_leader.is_set(), 30, "standby never took over")
        _wait(lambda: len(standby.failovers) >= 1, 30, "standby never failed over")
        # the cluster converged: the old master0 range is served again
        def served():
            try:
                client.refresh_topology()
                return client.get_bucket(key).get() == "before"
            except Exception:  # noqa: BLE001
                return False

        _wait(served, 30, "slot range never recovered under the new leader")
        # and writes land on the promoted master
        client.get_bucket(key).set("after")
        assert client.get_bucket(key).get() == "after"
    finally:
        client.shutdown()
        a.kill() if a._thread and a._thread.is_alive() else None
        b.stop()


def test_stale_leader_view_write_fenced(grid):
    """A view write stamped with an OLD fencing token is rejected — the
    paused ex-leader cannot clobber its successor's topology."""
    node = grid.masters[0]
    flat = []
    for lo, hi, h, p, nid in grid.view_tuples():
        flat += [lo, hi, h, p, nid]
    with node.server.client() as c:
        # successor installed a view at token 7
        assert c.execute("CLUSTER", "SETVIEW", "TOKEN", 7, *flat) in (b"OK", "+OK", "OK")
        # stale ex-leader at token 3: rejected
        reply = c.execute("CLUSTER", "SETVIEW", "TOKEN", 3, *flat)
        assert isinstance(reply, RespError) and "STALEVIEW" in str(reply)
        # equal/higher tokens pass (idempotent re-push)
        assert c.execute("CLUSTER", "SETVIEW", "TOKEN", 7, *flat) in (b"OK", "+OK", "OK")
        assert c.execute("CLUSTER", "SETVIEW", "TOKEN", 9, *flat) in (b"OK", "+OK", "OK")
