"""Chaos: concurrent load across a master kill + automatic failover.

Parity target: ``org/redisson/RedissonFailoverTest.java:47-152`` — a stream
of writes continues across ``master.stop()`` with a bounded error budget —
and the BaseConcurrentTest multi-writer fan-outs (SURVEY.md §4.3).
"""
import threading
import time

import pytest

from redisson_tpu.harness import ClusterRunner, _exec
from redisson_tpu.server.monitor import FailoverCoordinator
from redisson_tpu.utils.crc16 import calc_slot


def test_writes_survive_master_kill_with_auto_failover():
    runner = ClusterRunner(masters=2, replicas_per_master=1).run()
    coord = None
    client = None
    try:
        client = runner.client(scan_interval=0.5)
        coord = FailoverCoordinator(runner.view_tuples(), check_interval=0.1).start()
        time.sleep(0.4)  # coordinator learns replica sets

        # every key rides one hashtag so the whole stream targets the master
        # we are about to kill (the worst case)
        tag = "ha"
        slot = calc_slot(tag.encode())
        mi = next(i for i, (lo, hi) in enumerate(runner.slot_ranges) if lo <= slot <= hi)

        acked = []
        errors = []
        stop = threading.Event()

        def writer(wid: int):
            i = 0
            while not stop.is_set():
                key = f"w{wid}-{i}{{{tag}}}"
                try:
                    client.get_bucket(key).set(i)
                    acked.append(key)
                except Exception as e:  # noqa: BLE001 — budgeted
                    errors.append(repr(e))
                i += 1
                time.sleep(0.005)

        threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
        for t in threads:
            t.start()
        time.sleep(1.0)
        # snapshot the acked set, replicate it, then kill the master —
        # every snapshot key was acked before the flush scan, so the flush
        # ships a superset of the snapshot
        pre_kill_acked = list(acked)
        with runner.masters[mi].server.client() as c:
            _exec(c, "REPLFLUSH")
        runner.stop_master(mi)

        # writers keep running through the failover window
        deadline = time.time() + 20
        while time.time() < deadline and not coord.failovers:
            time.sleep(0.2)
        assert coord.failovers, "no automatic failover happened"
        time.sleep(1.5)  # let clients re-route and writes resume
        resumed_marker = len(acked)
        time.sleep(1.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)

        assert len(acked) > resumed_marker, "writes never resumed after failover"
        # bounded error budget: the outage window is ~seconds of a ~5s run;
        # every error must be a connectivity/redirect artifact, not data loss
        assert len(errors) < len(acked), f"error budget blown: {len(errors)} vs {len(acked)}"

        # acked-and-replicated writes survive the failover
        client.refresh_topology()
        sample = pre_kill_acked[:: max(1, len(pre_kill_acked) // 50)]
        for key in sample:
            assert client.get_bucket(key).get() is not None, f"lost acked+flushed {key}"
    finally:
        if coord is not None:
            coord.stop()
        if client is not None:
            client.shutdown()
        runner.shutdown()


def test_concurrent_multi_writer_objects():
    """BaseConcurrentTest analog: many threads, shared objects, no lost ops."""
    runner = ClusterRunner(masters=3).run()
    client = None
    try:
        client = runner.client(scan_interval=0)
        counter = client.get_atomic_long("cc-counter")
        m = client.get_map("cc-map")
        errs = []

        def worker(wid):
            try:
                for i in range(50):
                    counter.increment_and_get()
                    m.put(f"{wid}-{i}", i)
            except Exception as e:  # noqa: BLE001
                errs.append(repr(e))

        threads = [threading.Thread(target=worker, args=(w,)) for w in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        assert counter.get() == 8 * 50
        assert m.size() == 8 * 50
    finally:
        if client is not None:
            client.shutdown()
        runner.shutdown()


def test_blocking_consumer_survives_failover():
    """A BLPOP consumer parked on the dying master reconnects and keeps
    consuming after the replica is promoted (the ElementsSubscribe +
    isBlockingCommand resilience story, end to end)."""
    runner = ClusterRunner(masters=2, replicas_per_master=1).run()
    coord = None
    client = None
    try:
        client = runner.client(scan_interval=0.5)
        coord = FailoverCoordinator(runner.view_tuples(), check_interval=0.1).start()
        time.sleep(0.4)

        tag = "bq"
        slot = calc_slot(tag.encode())
        mi = next(i for i, (lo, hi) in enumerate(runner.slot_ranges) if lo <= slot <= hi)
        qname = f"jobs{{{tag}}}"

        consumed = []
        stop = threading.Event()

        def consumer():
            while not stop.is_set():
                try:
                    got = client.execute("BLPOP", qname, 1)
                    if got is not None:
                        consumed.append(bytes(got[1]))
                except Exception:  # noqa: BLE001 — outage window: retry
                    time.sleep(0.1)

        t = threading.Thread(target=consumer)
        t.start()
        # feed a few jobs, prove consumption, then kill the master mid-stream
        for i in range(5):
            client.execute("RPUSH", qname, f"pre-{i}")
        deadline = time.time() + 10
        while time.time() < deadline and len(consumed) < 5:
            time.sleep(0.05)
        assert len(consumed) == 5, consumed

        runner.stop_master(mi)
        deadline = time.time() + 20
        while time.time() < deadline and not coord.failovers:
            time.sleep(0.2)
        assert coord.failovers, "no automatic failover happened"
        time.sleep(1.5)
        client.refresh_topology()

        # jobs pushed AFTER promotion reach the parked consumer
        produced = []
        deadline = time.time() + 15
        i = 0
        while time.time() < deadline and len(consumed) < 8:
            try:
                client.execute("RPUSH", qname, f"post-{i}")
                produced.append(f"post-{i}".encode())
                i += 1
            except Exception:  # noqa: BLE001 — routing may still settle
                pass
            time.sleep(0.2)
        stop.set()
        t.join(10)
        assert not t.is_alive()
        post = [c for c in consumed if c.startswith(b"post-")]
        assert post, f"consumer never resumed after failover: {consumed}"
        assert set(post) <= set(produced), "consumed a job that was never acked"
    finally:
        if coord is not None:
            coord.stop()
        if client is not None:
            client.shutdown()
        runner.shutdown()
