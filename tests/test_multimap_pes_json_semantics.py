"""Multimap, PermitExpirableSemaphore, FairLock, JsonBucket behavioral depth
(RedissonListMultimapTest 20 / SetMultimapTest 28 /
PermitExpirableSemaphoreTest 26 / FairLockTest 25 / JsonBucketTest 20) —
VERDICT r3 #7, round-4 batch 7.
"""
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def remote_client():
    with ServerThread(port=0) as st:
        c = RemoteRedisson(st.address, timeout=60.0)
        yield c
        c.shutdown()


@pytest.fixture(scope="module")
def embedded_client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


@pytest.fixture(params=["embedded", "remote"])
def client(request, embedded_client, remote_client):
    return embedded_client if request.param == "embedded" else remote_client


def nm(tag):
    return f"mpj-{tag}-{time.time_ns()}"


class TestListMultimap:
    def test_put_preserves_duplicates_and_order(self, client):
        mm = client.get_list_multimap(nm("dup"))
        mm.put("k", "a")
        mm.put("k", "b")
        mm.put("k", "a")
        assert mm.get_all("k") == ["a", "b", "a"]
        assert mm.size() == 3
        assert mm.key_size() == 1

    def test_remove_single_occurrence(self, client):
        mm = client.get_list_multimap(nm("rm"))
        mm.put("k", "a")
        mm.put("k", "a")
        assert mm.remove("k", "a") is True
        assert mm.get_all("k") == ["a"]

    def test_remove_all_returns_values(self, client):
        mm = client.get_list_multimap(nm("rma"))
        mm.put("k", "a")
        mm.put("k", "b")
        assert mm.remove_all("k") == ["a", "b"]
        assert mm.get_all("k") == []
        assert mm.key_size() == 0

    def test_fast_remove_and_contains(self, client):
        mm = client.get_list_multimap(nm("fr"))
        mm.put_all("k", ["a", "b"])
        mm.put("k2", "c")
        assert mm.contains_key("k") and not mm.contains_key("zz")
        assert mm.contains_entry("k", "a") and not mm.contains_entry("k", "zz")
        assert mm.fast_remove("k", "zz") == 1
        assert mm.key_size() == 1

    def test_entries_and_keysets(self, client):
        mm = client.get_list_multimap(nm("ent"))
        mm.put("k1", "a")
        mm.put("k2", "b")
        assert sorted(mm.read_all_key_set()) == ["k1", "k2"]
        assert sorted(mm.entries()) == [("k1", "a"), ("k2", "b")]


class TestSetMultimap:
    def test_put_dedupes(self, client):
        mm = client.get_set_multimap(nm("dd"))
        assert mm.put("k", "a") is True
        assert mm.put("k", "a") is False  # already in the value set
        assert mm.get_all("k") == ["a"]

    def test_independent_keys(self, client):
        mm = client.get_set_multimap(nm("ind"))
        mm.put("k1", "x")
        mm.put("k2", "x")
        mm.remove("k1", "x")
        assert mm.get_all("k1") == []
        assert mm.get_all("k2") == ["x"]

    def test_cache_per_key_ttl(self, client):
        mmc = client.get_set_multimap_cache(nm("ttl"))
        mmc.put("hot", "v1")
        mmc.put("cold", "v2")
        assert mmc.expire_key("cold", 0.15) is True
        assert mmc.expire_key("absent", 1.0) is False
        time.sleep(0.3)
        assert mmc.get_all("cold") == []
        assert mmc.get_all("hot") == ["v1"]


class TestPermitExpirableSemaphore:
    def test_acquire_returns_permit_id(self, client):
        s = client.get_permit_expirable_semaphore(nm("pid"))
        assert s.try_set_permits(2) is True
        assert s.try_set_permits(5) is False  # set-once
        p1 = s.try_acquire()
        p2 = s.try_acquire()
        assert p1 and p2 and p1 != p2
        assert s.try_acquire() is None  # exhausted
        assert s.available_permits() == 0

    def test_release_by_id(self, client):
        s = client.get_permit_expirable_semaphore(nm("rel"))
        s.try_set_permits(1)
        pid = s.try_acquire()
        assert s.release(pid) is True
        assert s.release(pid) is False  # double release
        assert s.release("bogus") is False
        assert s.available_permits() == 1

    def test_lease_expiry_returns_permit(self, client):
        s = client.get_permit_expirable_semaphore(nm("lease"))
        s.try_set_permits(1)
        pid = s.try_acquire(lease_time=0.15)
        assert pid is not None
        assert s.available_permits() == 0
        time.sleep(0.3)
        assert s.available_permits() == 1  # lease reaped
        assert s.release(pid) is False     # expired permit cannot release

    def test_update_lease_time(self, client):
        s = client.get_permit_expirable_semaphore(nm("upd"))
        s.try_set_permits(1)
        pid = s.try_acquire(lease_time=0.15)
        assert s.update_lease_time(pid, 30.0) is True
        time.sleep(0.3)
        assert s.available_permits() == 0  # extended lease still held
        assert s.update_lease_time("bogus", 1.0) is False

    def test_blocked_acquire_wakes_on_release(self, embedded_client):
        s = embedded_client.get_permit_expirable_semaphore(nm("wake"))
        s.try_set_permits(1)
        held = s.try_acquire()
        got = []

        def waiter():
            got.append(s.try_acquire(wait_time=10.0))

        th = threading.Thread(target=waiter, daemon=True)
        th.start()
        time.sleep(0.1)
        assert not got
        s.release(held)
        th.join(5.0)
        assert got and got[0] is not None


class TestFairLock:
    def test_fifo_grant_order(self, embedded_client):
        """Waiters acquire in arrival order (the fair-queue contract)."""
        lk = embedded_client.get_fair_lock(nm("fifo"))
        lk.lock()
        order = []
        threads = []

        def waiter(tag, delay):
            time.sleep(delay)
            lk.lock()
            order.append(tag)
            time.sleep(0.05)
            lk.unlock()

        for i, d in enumerate((0.05, 0.15, 0.25)):
            th = threading.Thread(target=waiter, args=(i, d), daemon=True)
            th.start()
            threads.append(th)
        time.sleep(0.5)  # all three queued behind the holder
        lk.unlock()
        for th in threads:
            th.join(timeout=10.0)
        assert order == [0, 1, 2]

    def test_try_lock_fails_behind_queue(self, embedded_client):
        lk = embedded_client.get_fair_lock(nm("behind"))
        lk.lock()
        got = []
        th = threading.Thread(target=lambda: got.append(lk.try_lock()))
        th.start(); th.join(5.0)
        assert got == [False]
        lk.unlock()


class TestJsonBucket:
    def test_set_get_paths(self, client):
        jb = client.get_json_bucket(nm("jp"))
        jb.set("$", {"user": {"name": "ann", "tags": ["a", "b"], "age": 30}})
        assert jb.get("$.user.name") == "ann"
        assert jb.get("$.user.tags") == ["a", "b"]
        assert jb.get("$") == {"user": {"name": "ann", "tags": ["a", "b"], "age": 30}}

    def test_set_subpath(self, client):
        jb = client.get_json_bucket(nm("sub"))
        jb.set("$", {"a": {"b": 1}})
        jb.set("$.a.b", 2)
        assert jb.get("$.a.b") == 2

    def test_num_incr(self, client):
        jb = client.get_json_bucket(nm("incr"))
        jb.set("$", {"n": 10})
        assert jb.increment_and_get("$.n", 5) == 15
        assert jb.get("$.n") == 15

    def test_array_ops(self, client):
        jb = client.get_json_bucket(nm("arr"))
        jb.set("$", {"xs": [1, 2]})
        assert jb.array_append("$.xs", 3) == 3  # new length
        assert jb.get("$.xs") == [1, 2, 3]
        assert jb.array_index_of("$.xs", 2) == 1
        assert jb.array_pop("$.xs") == 3
        assert jb.array_size("$.xs") == 2

    def test_toggle_and_clear(self, client):
        jb = client.get_json_bucket(nm("tc"))
        jb.set("$", {"flag": True, "n": 5})
        assert jb.toggle("$.flag") is False
        assert jb.clear("$.n") == 1
        assert jb.get("$.n") == 0

    def test_object_introspection(self, client):
        jb = client.get_json_bucket(nm("obj"))
        jb.set("$", {"a": 1, "b": {"c": 2}})
        assert sorted(jb.object_keys("$")) == ["a", "b"]
        assert jb.object_size("$") == 2
        assert jb.type("$.a") in ("integer", "number", "int")


class TestInterfaceDiffTail:
    """Round-4 API-diff tail: AtomicLong.getAndDelete,
    RMultimap.replaceValues."""

    def test_atomic_long_get_and_delete(self, client):
        al = client.get_atomic_long(nm("gad"))
        al.set(42)
        assert al.get_and_delete() == 42
        assert al.get() == 0          # record gone: fresh zero
        assert al.get_and_delete() == 0  # absent: zero, no error

    def test_multimap_replace_values(self, client):
        mm = client.get_list_multimap(nm("repl"))
        mm.put_all("k", ["a", "b"])
        assert mm.replace_values("k", ["x", "y", "z"]) == ["a", "b"]
        assert mm.get_all("k") == ["x", "y", "z"]
        assert mm.replace_values("k", []) == ["x", "y", "z"]
        assert mm.get_all("k") == []
        assert mm.replace_values("fresh", ["n"]) == []
        assert mm.get_all("fresh") == ["n"]

    def test_set_multimap_replace_values_dedupes(self, client):
        mm = client.get_set_multimap(nm("repls"))
        mm.put("k", "old")
        old = mm.replace_values("k", ["v", "v", "w"])
        assert old == ["old"]
        assert sorted(mm.get_all("k")) == ["v", "w"]
