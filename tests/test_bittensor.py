import jax.numpy as jnp
import numpy as np

from redisson_tpu.ops import bittensor as bt


def test_set_get_roundtrip():
    bits = bt.make(10_000)
    idx = jnp.asarray([0, 5, 9999, 1234], jnp.int32)
    bits = bt.set_bits(bits, idx, 1)
    got = np.asarray(bt.get_bits(bits, idx))
    assert got.tolist() == [1, 1, 1, 1]
    other = np.asarray(bt.get_bits(bits, jnp.asarray([1, 6, 9998], jnp.int32)))
    assert other.tolist() == [0, 0, 0]


def test_clear_bit():
    bits = bt.make(100)
    bits = bt.set_bits(bits, jnp.asarray([7], jnp.int32), 1)
    bits = bt.set_bits(bits, jnp.asarray([7], jnp.int32), 0)
    assert int(bt.get_bits(bits, jnp.asarray([7], jnp.int32))[0]) == 0


def test_duplicate_indices_ok():
    bits = bt.make(64)
    idx = jnp.asarray([3, 3, 3, 3], jnp.int32)
    bits = bt.set_bits(bits, idx, 1)
    assert int(bt.popcount(bits, 64)) == 1


def test_set_and_report_newness():
    bits = bt.make(1 << 16)
    rows = jnp.asarray([[1, 2, 3], [10, 20, 30]], jnp.int32)
    bits, newly = bt.set_and_report(bits, rows)
    assert np.asarray(newly).tolist() == [True, True]
    bits, newly = bt.set_and_report(bits, rows)
    assert np.asarray(newly).tolist() == [False, False]
    mixed = jnp.asarray([[1, 2, 99]], jnp.int32)  # one fresh bit -> new
    _, newly = bt.set_and_report(bits, mixed)
    assert np.asarray(newly).tolist() == [True]


def test_contains():
    bits = bt.make(1 << 12)
    bits = bt.set_bits(bits, jnp.asarray([5, 6, 7], jnp.int32), 1)
    q = jnp.asarray([[5, 6, 7], [5, 6, 8]], jnp.int32)
    assert np.asarray(bt.contains(bits, q)).tolist() == [True, False]


def test_popcount_and_bitops():
    a = bt.make(2048)
    b = bt.make(2048)
    a = bt.set_bits(a, jnp.arange(0, 100, dtype=jnp.int32), 1)
    b = bt.set_bits(b, jnp.arange(50, 150, dtype=jnp.int32), 1)
    assert int(bt.popcount(a, 2048)) == 100
    assert int(bt.popcount(bt.bit_and(a, b), 2048)) == 50
    assert int(bt.popcount(bt.bit_or(a, b), 2048)) == 150
    assert int(bt.popcount(bt.bit_xor(a, b), 2048)) == 100
    assert int(bt.popcount(bt.bit_not(a, 2048), 2048)) == 2048 - 100


def test_bitpos_and_length():
    bits = bt.make(4096)
    assert int(bt.bitpos(bits, 1, 4096)) == -1
    assert int(bt.bitpos(bits, 0, 4096)) == 0
    bits = bt.set_bits(bits, jnp.asarray([100, 200], jnp.int32), 1)
    assert int(bt.bitpos(bits, 1, 4096)) == 100
    assert int(bt.length_hint(bits)) == 201


def test_out_of_range_dropped():
    bits = bt.make(100)
    bits = bt.set_bits(bits, jnp.asarray([10_000_000], jnp.int32), 1)
    assert int(bt.popcount(bits, bits.shape[0])) == 0
    got = bt.get_bits(bits, jnp.asarray([10_000_000], jnp.int32))
    assert int(got[0]) == 0


def test_pack_roundtrip():
    bits = bt.make(1000)
    idx = jnp.asarray([0, 1, 7, 8, 63, 999], jnp.int32)
    bits = bt.set_bits(bits, idx, 1)
    packed = bt.to_packed(np.asarray(bits), 1000)
    assert len(packed) == 125
    restored = bt.from_packed(packed, 1000)
    np.testing.assert_array_equal(restored[:1000], np.asarray(bits)[:1000])
