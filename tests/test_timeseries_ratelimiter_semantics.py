"""TimeSeries, RateLimiter, RingBuffer, DelayedQueue, TransferQueue, adder
behavioral depth (RedissonTimeSeriesTest / RateLimiterTest /
RingBufferTest / DelayedQueueTest / TransferQueueTest / LongAdderTest) —
VERDICT r3 #7, round-4 batch 6.
"""
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def remote_client():
    with ServerThread(port=0) as st:
        c = RemoteRedisson(st.address, timeout=60.0)
        yield c
        c.shutdown()


@pytest.fixture(scope="module")
def embedded_client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


@pytest.fixture(params=["embedded", "remote"])
def client(request, embedded_client, remote_client):
    return embedded_client if request.param == "embedded" else remote_client


def nm(tag):
    return f"trx-{tag}-{time.time_ns()}"


class TestTimeSeries:
    def seeded(self, client, tag):
        ts = client.get_time_series(nm(tag))
        for t in (1.0, 2.0, 3.0, 4.0):
            ts.add(t, f"v{int(t)}")
        return ts

    def test_add_get_size(self, client):
        ts = self.seeded(client, "ag")
        assert ts.size() == 4
        assert ts.get(2.0) == "v2"
        assert ts.get(9.9) is None

    def test_overwrite_same_timestamp(self, client):
        ts = client.get_time_series(nm("ow"))
        ts.add(1.0, "a")
        ts.add(1.0, "b")
        assert ts.size() == 1
        assert ts.get(1.0) == "b"

    def test_first_last(self, client):
        ts = self.seeded(client, "fl")
        # RTimeSeries.first(count)/last(count) return LISTS, newest-first for last
        assert ts.first() == ["v1"] and ts.last() == ["v4"]
        assert ts.first(2) == ["v1", "v2"]
        assert ts.last(2) == ["v4", "v3"]
        assert ts.first_timestamp() == 1.0
        assert ts.last_timestamp() == 4.0

    def test_range(self, client):
        ts = self.seeded(client, "rng")
        got = ts.range(2.0, 3.0)
        assert [v for _t, v in got] == ["v2", "v3"]
        rev = ts.range_reversed(2.0, 4.0)
        assert [v for _t, v in rev] == ["v4", "v3", "v2"]

    def test_remove_and_remove_range(self, client):
        ts = self.seeded(client, "rm")
        assert ts.remove(2.0) is True
        assert ts.remove(2.0) is False
        assert ts.remove_range(3.0, 4.0) == 2
        assert ts.size() == 1

    def test_poll_ends(self, client):
        ts = self.seeded(client, "poll")
        assert ts.poll_first() == ["v1"]
        assert ts.poll_last() == ["v4"]
        assert ts.size() == 2

    def test_add_all(self, client):
        ts = client.get_time_series(nm("aa"))
        ts.add_all({10.0: "x", 20.0: "y"})
        assert ts.size() == 2
        assert ts.last() == ["y"]


class TestRateLimiter:
    def test_rate_enforced_within_window(self, client):
        rl = client.get_rate_limiter(nm("rate"))
        assert rl.try_set_rate("OVERALL", 3, 1.0) is True
        assert rl.try_set_rate("OVERALL", 99, 1.0) is False  # set-once
        assert all(rl.try_acquire() for _ in range(3))
        assert rl.try_acquire() is False  # window exhausted

    def test_window_refills(self, client):
        rl = client.get_rate_limiter(nm("refill"))
        rl.try_set_rate("OVERALL", 2, 0.2)
        assert rl.try_acquire() and rl.try_acquire()
        assert not rl.try_acquire()
        time.sleep(0.3)
        assert rl.try_acquire() is True

    def test_acquire_multiple_permits(self, client):
        rl = client.get_rate_limiter(nm("multi"))
        rl.try_set_rate("OVERALL", 5, 1.0)
        assert rl.try_acquire(3) is True
        assert rl.try_acquire(3) is False  # only 2 left
        assert rl.try_acquire(2) is True

    def test_set_rate_overrides(self, client):
        rl = client.get_rate_limiter(nm("ovr"))
        rl.try_set_rate("OVERALL", 1, 30.0)
        assert rl.try_acquire() and not rl.try_acquire()
        rl.set_rate("OVERALL", 10, 30.0)  # forced reset (RRateLimiter.setRate)
        assert rl.try_acquire() is True

    def test_get_config(self, client):
        rl = client.get_rate_limiter(nm("cfg"))
        rl.try_set_rate("OVERALL", 7, 2.0)
        cfg = rl.get_config()
        assert cfg["rate"] == 7 and cfg["interval"] == 2.0


class TestRingBuffer:
    def test_overwrites_oldest_when_full(self, client):
        rb = client.get_ring_buffer(nm("rb"))
        assert rb.try_set_capacity(3) is True
        for i in range(5):
            rb.offer(i)
        assert rb.read_all() == [2, 3, 4]  # oldest two overwritten
        assert rb.size() == 3
        assert rb.capacity() == 3
        assert rb.remaining_capacity() == 0

    def test_set_capacity_shrink_keeps_newest(self, client):
        rb = client.get_ring_buffer(nm("shrink"))
        rb.try_set_capacity(4)
        for i in range(4):
            rb.offer(i)
        rb.set_capacity(2)
        assert rb.read_all() == [2, 3]

    def test_capacity_validation(self, client):
        rb = client.get_ring_buffer(nm("val"))
        with pytest.raises(ValueError):
            rb.try_set_capacity(0)


class TestDelayedQueue:
    def test_elements_appear_after_delay(self, embedded_client):
        dest = embedded_client.get_blocking_queue(nm("dq-dest"))
        dq = embedded_client.get_delayed_queue(dest)
        dq.offer("later", delay=0.2)
        dq.offer("now", delay=0.0)
        deadline = time.time() + 5.0
        got = []
        while time.time() < deadline and len(got) < 2:
            v = dest.poll()
            if v is not None:
                got.append(v)
            time.sleep(0.02)
        assert got == ["now", "later"]  # delay order, not offer order

    def test_pending_visible_in_delayed_queue(self, embedded_client):
        dest = embedded_client.get_blocking_queue(nm("dq2-dest"))
        dq = embedded_client.get_delayed_queue(dest)
        dq.offer("pending", delay=30.0)
        assert dest.poll() is None  # not yet transferred
        assert dq.size() >= 1       # still parked in the delay zset


class TestTransferQueue:
    def test_transfer_waits_for_consumer(self, embedded_client):
        tq = embedded_client.get_transfer_queue(nm("tq"))
        done = threading.Event()

        def producer():
            tq.transfer("item")  # blocks until taken
            done.set()

        th = threading.Thread(target=producer, daemon=True)
        th.start()
        time.sleep(0.1)
        assert not done.is_set()
        assert tq.take() == "item"
        assert done.wait(5.0)

    def test_try_transfer_without_consumer(self, embedded_client):
        tq = embedded_client.get_transfer_queue(nm("tq2"))
        assert tq.try_transfer("nobody") is False
        assert tq.size() == 0  # rejected transfer leaves nothing behind


class TestAdders:
    def test_long_adder_sum(self, embedded_client):
        a = embedded_client.get_long_adder(nm("la"))
        for _ in range(5):
            a.increment()
        a.add(10)
        a.decrement()
        assert a.sum() == 14
        a.reset()
        assert a.sum() == 0

    def test_double_adder(self, embedded_client):
        a = embedded_client.get_double_adder(nm("da"))
        a.add(1.5)
        a.add(2.25)
        assert a.sum() == 3.75
