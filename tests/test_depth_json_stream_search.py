"""Depth additions (VERDICT r2 #9): JSON path ops, stream trim strategies /
pending summary / consumer admin, search aggregation sort+paging.
Reference: RedissonJsonBucket.java, RedissonStream.java:1-1441,
RedissonSearch.java."""
import pytest

import redisson_tpu


@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


# -- JsonBucket ---------------------------------------------------------------


def test_json_clear_toggle_strappend(client):
    j = client.get_json_bucket("jd:doc")
    j.set("$", {"flag": True, "n": 7, "items": [1, 2], "meta": {"a": 1}, "s": "ab"})
    assert j.toggle("flag") is False
    assert j.toggle("flag") is True
    assert j.clear("items") == 1 and j.get("items") == []
    assert j.clear("n") == 1 and j.get("n") == 0
    assert j.clear("s") == 0  # strings aren't cleared (Redis semantics)
    assert j.string_append("s", "cd") == 4
    assert j.get("s") == "abcd"


def test_json_array_ops(client):
    j = client.get_json_bucket("jd:arr")
    j.set("$", {"a": [1, 2, 3, 4, 5]})
    assert j.array_insert("a", 1, 99) == 6
    assert j.get("a") == [1, 99, 2, 3, 4, 5]
    assert j.array_pop("a", 1) == 99
    assert j.array_pop("a") == 5
    assert j.array_trim("a", 1, 2) == 2
    assert j.get("a") == [2, 3]
    assert j.array_index_of("a", 3) == 1
    assert j.array_index_of("a", 42) == -1


def test_json_object_ops_and_merge(client):
    j = client.get_json_bucket("jd:obj")
    j.set("$", {"user": {"name": "kim", "age": 30, "tags": ["x"]}})
    assert sorted(j.object_keys("user")) == ["age", "name", "tags"]
    assert j.object_size("user") == 3
    # RFC 7386 merge-patch: None deletes, dicts merge, scalars replace
    j.merge("user", {"age": 31, "name": None, "city": "oslo"})
    assert j.get("user") == {"age": 31, "tags": ["x"], "city": "oslo"}


# -- Stream -------------------------------------------------------------------


def test_stream_trim_min_id_and_last_id(client):
    s = client.get_stream("sd:trim")
    ids = [s.add({"i": i}) for i in range(10)]
    assert s.last_id() == ids[-1]
    dropped = s.trim_by_min_id(ids[4])
    assert dropped == 4
    assert s.size() == 6
    assert list(s.range())[0] == ids[4]


def test_stream_pending_summary_and_delconsumer(client):
    s = client.get_stream("sd:pel")
    for i in range(6):
        s.add({"i": i})
    s.create_group("g", from_id="0")
    s.read_group("g", "alice", count=2)
    s.read_group("g", "bob", count=4)
    summary = s.pending_summary("g")
    assert summary["total"] == 6
    assert summary["consumers"] == {"alice": 2, "bob": 4}
    assert summary["min_id"] is not None and summary["max_id"] is not None
    # DELCONSUMER discards bob's pending entries
    assert s.remove_consumer("g", "bob") == 4
    assert s.pending_summary("g")["total"] == 2
    assert "bob" not in s.list_consumers("g")


def test_stream_setid_replays_history(client):
    s = client.get_stream("sd:setid")
    ids = [s.add({"i": i}) for i in range(4)]
    s.create_group("g", from_id="$")  # nothing new to deliver
    assert s.read_group("g", "c1", count=10) == {}
    s.set_group_id("g", "0")  # rewind: everything re-delivers
    got = s.read_group("g", "c1", count=10)
    assert list(got) == ids


# -- Search aggregation -------------------------------------------------------


def test_search_aggregate_sort_and_paging(client):
    search = client.get_search()
    search.create_index("agg:idx", {"team": "tag", "score": "numeric"})
    for i in range(12):
        search.add_document(
            "agg:idx", f"d{i}", {"team": f"t{i % 3}", "score": float(i)}
        )
    rows = search.aggregate(
        "agg:idx",
        group_by="team",
        reducers={"n": ("count", None), "total": ("sum", "score")},
        sort_by="total",
        descending=True,
    )
    totals = [r["total"] for r in rows]
    assert totals == sorted(totals, reverse=True)
    assert {r["team"] for r in rows} == {"t0", "t1", "t2"}
    # paging
    page = search.aggregate(
        "agg:idx", group_by="team", reducers={"n": ("count", None)},
        sort_by="n", offset=1, limit=1,
    )
    assert len(page) == 1


def test_json_array_trim_negative_indexes(client):
    j = client.get_json_bucket("jd:negtrim")
    j.set("$", {"a": [0, 1, 2, 3, 4]})
    assert j.array_trim("a", 0, -1) == 5  # keep everything (Redis idiom)
    assert j.get("a") == [0, 1, 2, 3, 4]
    assert j.array_trim("a", -3, -2) == 2
    assert j.get("a") == [2, 3]
    assert j.array_trim("a", 5, 9) == 0
    assert j.get("a") == []


def test_search_aggregate_mixed_type_sort(client):
    search = client.get_search()
    search.create_index("mix:idx", {"label": "tag", "v": "numeric"})
    search.add_document("mix:idx", "a", {"label": 42, "v": 1.0})
    search.add_document("mix:idx", "b", {"label": "42x", "v": 2.0})
    rows = search.aggregate(
        "mix:idx", group_by="label", reducers={"n": ("count", None)},
        sort_by="label",
    )
    assert len(rows) == 2  # no TypeError on int-vs-str


def test_role_breadcrumb_distinguishes_promoted_from_restarted(client):
    """Coordinator-HA discovery: only a master that NAMES the dead master it
    was promoted from is adopted (ROLE 4th element breadcrumb)."""
    from redisson_tpu.harness import ClusterRunner
    from redisson_tpu.net.client import NodeClient

    runner = ClusterRunner(masters=1, replicas_per_master=1).run()
    try:
        master = runner.masters[0]
        replica = runner.replicas[0]
        c = NodeClient(replica.address, ping_interval=0)
        role = c.execute("ROLE", timeout=5.0)
        assert bytes(role[0]) == b"slave"
        c.execute("REPLICAOF", "NO", "ONE", timeout=10.0)
        role = c.execute("ROLE", timeout=5.0)
        assert bytes(role[0]) == b"master"
        assert bytes(role[3]).decode() == master.address  # breadcrumb
        c.close()
        # a never-replica master has NO breadcrumb
        cm = NodeClient(master.address, ping_interval=0)
        role = cm.execute("ROLE", timeout=5.0)
        assert bytes(role[3]) == b""
        cm.close()
    finally:
        runner.shutdown()


def test_json_array_insert_pop_index_negative_semantics(client):
    """Reviewer repros: negative indexes are normalized ONCE (contiguous
    insert), pops clamp to the ends, index_of returns absolute positions."""
    j = client.get_json_bucket("jd:negops")
    j.set("$", {"a": [1, 2, 3]})
    assert j.array_insert("a", -1, "x", "y") == 5
    assert j.get("a") == [1, 2, "x", "y", 3]
    assert j.array_pop("a", 50) == 3      # out of range: clamps to last
    assert j.array_pop("a", -50) == 1     # clamps to first
    assert j.get("a") == [2, "x", "y"]
    j.set("$", {"b": [1, 2, 3]})
    assert j.array_index_of("b", 3, start=-2) == 2  # absolute, found
    assert j.array_index_of("b", 1, start=-2) == -1
    assert j.array_index_of("b", 2, start=0, stop=-1) == 1


def test_read_method_classification_for_new_surface():
    """New read-only methods must classify as reads (replica routing)."""
    from redisson_tpu.net.commands import objcall_is_write

    for m in ("pending_summary", "object_keys", "object_size",
              "array_index_of", "array_size", "string_size", "type",
              "list_groups", "list_consumers", "last_id"):
        assert not objcall_is_write(m), m
    for m in ("array_insert", "array_pop", "array_trim", "merge", "toggle",
              "clear", "string_append", "trim_by_min_id", "remove_consumer",
              "set_group_id"):
        assert objcall_is_write(m), m
