"""Regression tests closing the round-5 advisor findings (ADVICE.md):
LZ4 frame endianness, the two geo fixes, and REPLPUSHSEG staging eviction.
(The replication delta-validation finding is covered in
``test_replication_delta.py::test_shape_divergence_raises_and_full_ships``.)
"""
import pickle
import threading

import pytest

import redisson_tpu
from redisson_tpu.client.codec import JsonCodec, Lz4Codec, StringCodec
from redisson_tpu.client.objects.geo import Geo, GeoSearchArgs
from redisson_tpu.harness import _exec, free_port
from redisson_tpu.net.resp import RespError
from redisson_tpu.server import replication
from redisson_tpu.server.server import ServerThread
from redisson_tpu.utils import lz4block


# -- Lz4Codec frame endianness (ADVICE r5 medium) -----------------------------

def test_lz4_frame_length_header_is_big_endian():
    """LZ4Codec.java writes the uncompressed length with Netty
    ByteBuf.writeInt — big-endian.  Byte-level wire vector: a 10-byte
    literals-only payload frames as 00 00 00 0A | A0 | payload."""
    c = Lz4Codec(StringCodec())
    frame = c.encode("0123456789")
    assert frame[:4] == b"\x00\x00\x00\x0a"          # length, network order
    assert frame[4] == 0xA0                          # token: 10 literals
    assert frame[5:] == b"0123456789"
    assert c.decode(frame) == "0123456789"


def test_lz4_frame_decodes_reference_written_value():
    """A frame assembled EXACTLY the way the reference writes it (writeInt
    big-endian + LZ4 block) must decode."""
    raw = StringCodec().encode("wire-compat " * 40)
    reference_frame = len(raw).to_bytes(4, "big") + lz4block.compress(raw)
    assert Lz4Codec(StringCodec()).decode(reference_frame) == "wire-compat " * 40


def test_lz4_decodes_legacy_little_endian_frames():
    """At-rest compat: values written before the endianness fix carried the
    length little-endian; decode retries LE when the BE size check fails
    (exactly one byte order satisfies the decompressor)."""
    raw = StringCodec().encode("legacy payload " * 30)
    legacy_frame = len(raw).to_bytes(4, "little") + lz4block.compress(raw)
    assert Lz4Codec(StringCodec()).decode(legacy_frame) == "legacy payload " * 30


def test_lz4_roundtrip_still_holds_for_structures():
    c = Lz4Codec(JsonCodec())
    v = {"k": list(range(64)), "s": "y" * 300}
    assert c.decode(c.encode(v)) == v


# -- geo fixes (ADVICE r5 low x2) ---------------------------------------------

@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


def _seed_geo(client, name="advice:geo"):
    g = client.get_geo(name)
    g.add(13.361389, 38.115556, "Palermo")
    g.add(15.087269, 37.502669, "Catania")
    g.add(2.349014, 48.864716, "Paris")
    return g


def test_search_with_position_accepts_keywords_again(client):
    g = _seed_geo(client, "advice:geo:kw")
    positional = g.search_with_position(15, 37, 200, "km")
    keyword = g.search_with_position(lon=15, lat=37, radius=200, unit="km")
    assert keyword == positional
    assert set(keyword) == {"Palermo", "Catania"}
    # mixed positional + keyword tail works too
    mixed = g.search_with_position(15, 37, radius=200, unit="km")
    assert mixed == positional
    with pytest.raises(TypeError, match="radius"):
        g.search_with_position(15, 37)


def test_store_search_to_skips_concurrently_removed_members(client, monkeypatch):
    g = _seed_geo(client, "advice:geo:race")
    orig = Geo._eval_args

    def eval_then_lose_member(self, args):
        pairs = orig(self, args)
        # simulate a concurrent removal landing between evaluation and the
        # locked copy: Catania vanishes from the source
        rec = self._engine.store.get(self._name)
        rec.host.pop(self._e("Catania"), None)
        return pairs

    monkeypatch.setattr(Geo, "_eval_args", eval_then_lose_member)
    args = GeoSearchArgs.from_coords(15, 37).radius(200, "km")
    # old code: KeyError mid-copy after dest was already cleared
    stored = g.store_search_to("advice:geo:dest", args)
    assert stored == 1  # Palermo survived; Catania skipped, not raised
    dest = client.get_geo("advice:geo:dest")
    assert dest.read_all() == ["Palermo"]


# -- REPLPUSHSEG staging eviction (ADVICE r5 low) -----------------------------

def _seg_frames(xfer_id, nsegs=2):
    """A valid empty replication payload split into `nsegs` chunks."""
    blob = pickle.dumps({"format": 1, "records": []}, protocol=4)
    per = -(-len(blob) // nsegs)
    return [
        ("REPLPUSHSEG", xfer_id, i, nsegs, blob[i * per:(i + 1) * per])
        for i in range(nsegs)
    ]


def _as_replica(st):
    """Replication pushes only land on replicas (a master rejects them as
    stale, ISSUE 17) — arm the staging target's role directly."""
    st.server.role = "replica"
    return st


def test_concurrent_transfers_beyond_old_cap_all_complete():
    """Six interleaved in-progress transfers (the old insertion-order cap
    of 4 dropped the first two) must ALL reassemble and apply."""
    st = _as_replica(ServerThread(port=free_port()).start())
    try:
        with st.client() as c:
            heads, tails = [], []
            for i in range(6):
                h, t = _seg_frames(f"xfer-{i}")
                heads.append(h)
                tails.append(t)
            for h in heads:          # stage seq 0 of every transfer first
                assert _exec(c, *h) == b"OK" or True
            for t in tails:          # then complete them all
                assert _exec(c, *t) == 0  # empty payload applies 0 records
        assert not st.server._repl_xfers  # staging fully drained
    finally:
        st.stop()


def test_stale_transfer_evicted_fresh_transfer_kept():
    st = _as_replica(ServerThread(port=free_port()).start())
    try:
        with st.client() as c:
            h_stale, t_stale = _seg_frames("xfer-stale")
            h_fresh, t_fresh = _seg_frames("xfer-fresh")
            _exec(c, *h_stale)
            _exec(c, *h_fresh)
            # age ONLY the stale transfer past the staleness window
            from redisson_tpu.server.verbs.admin import REPL_XFER_STALE_S

            with st.server._repl_xfers_lock:
                st.server._repl_xfers["xfer-stale"][1] -= REPL_XFER_STALE_S + 1
            # a new transfer staging triggers the staleness sweep
            h_new, t_new = _seg_frames("xfer-new")
            _exec(c, *h_new)
            # stale one is gone; its continuation fails loudly
            with pytest.raises(RespError, match="unknown replication transfer"):
                _exec(c, *t_stale)
            # the fresh in-progress transfer was NOT spuriously dropped
            assert _exec(c, *t_fresh) == 0
            assert _exec(c, *t_new) == 0
    finally:
        st.stop()


def test_transfer_staging_is_thread_safe_under_parallel_pushes():
    """Concurrent REPLPUSHSEG streams from several sources (replication
    racing IMPORTRECORDS-scale reshards) reassemble without corruption."""
    st = _as_replica(ServerThread(port=free_port()).start())
    errs = []

    def push(i):
        try:
            with st.client() as c:
                for frame in _seg_frames(f"par-{i}", nsegs=4):
                    _exec(c, *frame)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    try:
        threads = [threading.Thread(target=push, args=(i,)) for i in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=30)
        assert not errs, errs[:3]
        assert not st.server._repl_xfers
    finally:
        st.stop()
