"""Transaction suite across EVERY facade (VERDICT r3 #1-#2).

Mirrors the reference's per-object transactional test classes
(transaction/RedissonTransactionalBucketTest, ...MapTest, ...SetTest, etc.)
plus: the embedded semantics re-run verbatim against a live server and a
2-master cluster, a concurrent conflict-abort test, MULTI/EXEC/WATCH wire
compatibility, and TransactionOptions behavior.
"""
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.harness import ClusterRunner
from redisson_tpu.net.resp import RespError
from redisson_tpu.server.server import ServerThread
from redisson_tpu.services.transactions import (
    TransactionException,
    TransactionOptions,
)


@pytest.fixture(scope="module")
def server():
    with ServerThread(port=0) as st:
        yield st


@pytest.fixture(scope="module")
def remote(server):
    client = RemoteRedisson(server.address, timeout=60.0)
    yield client
    client.shutdown()


@pytest.fixture(scope="module")
def remote2(server):
    client = RemoteRedisson(server.address, timeout=60.0)
    yield client
    client.shutdown()


@pytest.fixture(scope="module")
def cluster_pair():
    runner = ClusterRunner(masters=2).run()
    c1 = runner.client(scan_interval=0)
    c2 = runner.client(scan_interval=0)
    yield c1, c2
    c1.shutdown()
    c2.shutdown()
    runner.shutdown()


@pytest.fixture()
def embedded():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


# -- the embedded semantics, verbatim, against each facade --------------------
# (the VERDICT "done" bar: embedded transaction tests pass against a server
# and a 2-master cluster)


def _drive_commit_applies(c, observer, tag):
    tx = c.create_transaction()
    tx.get_bucket(f"{tag}b").set("v1")
    tx.get_map(f"{tag}m").put("k", 1)
    assert observer.get_bucket(f"{tag}b").get() is None  # no dirty read
    tx.commit()
    assert tx.state == "committed"
    assert observer.get_bucket(f"{tag}b").get() == "v1"
    assert observer.get_map(f"{tag}m").get("k") == 1


def _drive_read_your_writes(c, observer, tag):
    tx = c.create_transaction()
    m = tx.get_map(f"{tag}rw")
    m.put("k", 42)
    assert m.get("k") == 42
    m.remove("k")
    assert m.get("k") is None
    tx.rollback()
    assert observer.get_map(f"{tag}rw").get("k") is None


def _drive_optimistic_conflict(c, observer, tag):
    observer.get_bucket(f"{tag}cf").set("orig")
    tx = c.create_transaction()
    tb = tx.get_bucket(f"{tag}cf")
    assert tb.get() == "orig"  # records the version precondition
    observer.get_bucket(f"{tag}cf").set("concurrent!")
    tb.set("mine")
    with pytest.raises(TransactionException, match="changed concurrently"):
        tx.commit()
    assert tx.state == "rolled_back"
    assert observer.get_bucket(f"{tag}cf").get() == "concurrent!"


def _drive_rollback_then_reuse_fails(c, tag):
    tx = c.create_transaction()
    tx.get_bucket(f"{tag}ru").set("x")
    tx.rollback()
    with pytest.raises(TransactionException):
        tx.commit()


def _drive_all(c, observer, tag):
    _drive_commit_applies(c, observer, tag)
    _drive_read_your_writes(c, observer, tag)
    _drive_optimistic_conflict(c, observer, tag)
    _drive_rollback_then_reuse_fails(c, tag)


class TestFacadeMatrix:
    def test_embedded(self, embedded):
        _drive_all(embedded, embedded, "e-")

    def test_remote(self, remote, remote2):
        _drive_all(remote, remote2, "r-")

    def test_cluster(self, cluster_pair):
        c1, c2 = cluster_pair
        _drive_all(c1, c2, "c-")

    def test_cluster_cross_shard_atomicity(self, cluster_pair):
        """A conflict on ANY shard aborts with nothing applied on any other
        shard (the check-phase of the grouped commit)."""
        c1, c2 = cluster_pair
        groups = c1.tx_groups([f"xs{i}" for i in range(40)])
        assert len(groups) == 2
        (_, an), (_, bn) = groups.items()
        na, nb = an[0], bn[0]
        c2.get_bucket(na).set("A")
        c2.get_map(nb).put("k", "B")
        tx = c1.create_transaction()
        assert tx.get_bucket(na).get() == "A"
        c2.get_bucket(na).set("A2")  # conflict on shard A
        tx.get_bucket(na).set("mine")
        tx.get_map(nb).put("k", "TORN?")  # would land on shard B
        with pytest.raises(TransactionException):
            tx.commit()
        assert c2.get_bucket(na).get() == "A2"
        assert c2.get_map(nb).get("k") == "B"  # shard B untouched


# -- concurrent conflict-abort (VERDICT #1 "done" criterion) ------------------


class TestConcurrency:
    def test_concurrent_increment_no_lost_updates(self, remote, remote2):
        wins, aborts = [], []

        def contend(cli, tag, rounds=15):
            for _ in range(rounds):
                tx = cli.create_transaction()
                m = tx.get_map("ctr")
                cur = m.get("n") or 0
                m.put("n", cur + 1)
                try:
                    tx.commit()
                    wins.append(tag)
                except TransactionException:
                    aborts.append(tag)

        t1 = threading.Thread(target=contend, args=(remote, "a"))
        t2 = threading.Thread(target=contend, args=(remote2, "b"))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert remote.get_map("ctr").get("n") == len(wins)
        assert len(wins) >= 1

    def test_blind_writes_never_conflict(self, remote, remote2):
        """Transactions that only WRITE (no reads) carry no version
        preconditions and must both land.  (`put` reads to return the prior
        value per the RMap contract; `fast_put` is the blind form.)"""
        tx1 = remote.create_transaction()
        tx2 = remote2.create_transaction()
        tx1.get_map("bw").fast_put("a", 1)
        tx2.get_map("bw").fast_put("b", 2)
        tx1.commit()
        tx2.commit()
        assert remote.get_map("bw").get_all(["a", "b"]) == {"a": 1, "b": 2}


# -- view breadth (transaction/RedissonTransaction.java:84-196) ---------------


class TestViews:
    def test_bucket_conditionals(self, remote, remote2):
        tx = remote.create_transaction()
        b = tx.get_bucket("vb")
        assert b.try_set("first") is True
        assert b.try_set("second") is False  # sees its own write
        assert b.compare_and_set("first", "updated") is True
        assert b.compare_and_set("nope", "x") is False
        assert b.get_and_set("final") == "updated"
        tx.commit()
        assert remote2.get_bucket("vb").get() == "final"

    def test_bucket_try_set_conflict_when_raced(self, remote, remote2):
        tx = remote.create_transaction()
        assert tx.get_bucket("vb2").try_set("mine") is True  # probed absent
        remote2.get_bucket("vb2").set("theirs")  # racer creates it
        with pytest.raises(TransactionException):
            tx.commit()
        assert remote2.get_bucket("vb2").get() == "theirs"

    def test_buckets_view(self, remote, remote2):
        tx = remote.create_transaction()
        bs = tx.get_buckets()
        assert bs.try_set({"bk1": 1, "bk2": 2}) is True
        tx.commit()
        assert remote2.get_buckets().get("bk1", "bk2") == {"bk1": 1, "bk2": 2}
        # MSETNX contract: any existing key -> False, nothing written
        tx = remote.create_transaction()
        assert tx.get_buckets().try_set({"bk2": 99, "bk3": 3}) is False
        tx.rollback()
        assert remote2.get_bucket("bk3").get() is None

    def test_remote_buckets_surface(self, remote, remote2):
        """The non-transactional RBuckets facade over the wire."""
        bs = remote.get_buckets()
        bs.set({"rb1": "x", "rb2": "y"})
        assert remote2.get_buckets().get("rb1", "rb2", "rb-absent") == {
            "rb1": "x", "rb2": "y",
        }
        assert bs.try_set({"rb1": "clash", "rb9": "z"}) is False
        assert remote2.get_bucket("rb9").get() is None
        assert bs.try_set({"rb9": "z"}) is True

    def test_map_surface(self, remote, remote2):
        tx = remote.create_transaction()
        m = tx.get_map("vm")
        assert m.put("k", "v1") is None
        assert m.put("k", "v2") == "v1"  # previous from the overlay
        assert m.put_if_absent("k", "nope") == "v2"
        assert m.put_if_absent("k2", "yes") is None
        assert m.replace("k", "v3") == "v2"
        assert m.replace("absent", "x") is None
        assert m.replace_if_equals("k", "v3", "v4") is True
        assert m.replace_if_equals("k", "wrong", "x") is False
        assert m.remove_if_equals("k2", "yes") is True
        assert m.contains_key("k2") is False
        m.put_all({"a": 1, "b": 2})
        assert m.get_all(["a", "b", "k"]) == {"a": 1, "b": 2, "k": "v4"}
        tx.commit()
        assert remote2.get_map("vm").get("k") == "v4"
        assert remote2.get_map("vm").get("k2") is None
        assert remote2.get_map("vm").get("a") == 1

    def test_map_cache_ttl(self, remote, remote2):
        tx = remote.create_transaction()
        mc = tx.get_map_cache("vmc")
        mc.put_with_ttl("t", "short", ttl=0.15)
        mc.fast_put("p", "perm")
        tx.commit()
        assert remote2.get_map_cache("vmc").get("t") == "short"
        time.sleep(0.25)
        assert remote2.get_map_cache("vmc").get("t") is None
        assert remote2.get_map_cache("vmc").get("p") == "perm"

    def test_set_and_set_cache(self, remote, remote2):
        tx = remote.create_transaction()
        s = tx.get_set("vs")
        s.add("a")
        assert s.contains("a") is True
        s.remove("a")
        assert s.contains("a") is False
        s.add("keep")
        sc = tx.get_set_cache("vsc")
        sc.add("ttl-ed", ttl=0.15)
        sc.add("perm")
        tx.commit()
        assert remote2.get_set("vs").contains("keep")
        assert not remote2.get_set("vs").contains("a")
        assert remote2.get_set_cache("vsc").contains("ttl-ed")
        time.sleep(0.25)
        assert not remote2.get_set_cache("vsc").contains("ttl-ed")
        assert remote2.get_set_cache("vsc").contains("perm")

    def test_local_cached_map_handshake(self, remote, remote2):
        """The commit disable/enable handshake: a peer's near cache must not
        serve stale values after the commit."""
        lcm1 = remote.get_local_cached_map("vlcm")
        lcm2 = remote2.get_local_cached_map("vlcm")
        lcm1.put("a", 1)
        assert lcm2.get("a") == 1  # now cached in lcm2's near cache
        tx = remote.create_transaction()
        view = tx.get_local_cached_map(lcm1)
        assert view.get("a") == 1
        view.put("a", 2)
        tx.commit()
        deadline = time.time() + 5.0
        while time.time() < deadline and lcm2.get("a") != 2:
            time.sleep(0.05)
        assert lcm2.get("a") == 2
        assert lcm1.get("a") == 2

    def test_embedded_view_breadth(self, embedded):
        """Same 7-view surface embedded (the original facade keeps parity)."""
        tx = embedded.create_transaction()
        assert tx.get_bucket("eb").try_set("v")
        tx.get_buckets().set({"eb2": 2})
        tx.get_map("em").put("k", 1)
        tx.get_map_cache("emc").put_with_ttl("t", "v", ttl=30)
        tx.get_set("es").add("m")
        tx.get_set_cache("esc").add("m", ttl=30)
        lcm = embedded.get_local_cached_map("elcm")
        tx.get_local_cached_map(lcm).put("k", "v")
        tx.commit()
        assert embedded.get_bucket("eb").get() == "v"
        assert embedded.get_bucket("eb2").get() == 2
        assert embedded.get_map("em").get("k") == 1
        assert embedded.get_map_cache("emc").get("t") == "v"
        assert embedded.get_set("es").contains("m")
        assert embedded.get_set_cache("esc").contains("m")
        assert lcm.get("k") == "v"


# -- TransactionOptions (api/TransactionOptions.java) -------------------------


class TestOptions:
    def test_timeout_discards(self, remote):
        tx = remote.create_transaction(options=TransactionOptions(timeout=0.05))
        time.sleep(0.1)
        with pytest.raises(TransactionException, match="timed out"):
            tx.get_bucket("tb").set("late")
        assert tx.state == "timed_out"

    def test_timeout_kwarg_back_compat(self, embedded):
        tx = embedded.create_transaction(timeout=0.05)
        time.sleep(0.1)
        with pytest.raises(TransactionException, match="timed out"):
            tx.get_bucket("tb").set("late")

    def test_defaults(self):
        o = TransactionOptions.defaults()
        assert o.timeout == 5.0
        assert o.response_timeout == 3.0
        assert o.retry_attempts == 3
        assert o.sync_slaves == 0


# -- MULTI/EXEC/WATCH wire compatibility --------------------------------------


class TestWireMultiExec:
    def test_multi_exec_applies(self, remote):
        c = remote.node
        assert c.execute("MULTI") in (b"OK", "OK")
        assert c.execute("SET", "wx", "1") in (b"QUEUED", "QUEUED")
        assert c.execute("LPUSH", "wl", "a") in (b"QUEUED", "QUEUED")
        out = c.execute("EXEC")
        assert out[0] in (b"OK", "OK") and out[1] == 1
        assert c.execute("GET", "wx") == b"1"

    def test_exec_without_multi(self, remote):
        with pytest.raises(RespError, match="EXEC without MULTI"):
            remote.node.execute("EXEC")
        with pytest.raises(RespError, match="DISCARD without MULTI"):
            remote.node.execute("DISCARD")

    def test_nested_multi(self, remote):
        c = remote.node
        c.execute("MULTI")
        with pytest.raises(RespError, match="nested"):
            c.execute("MULTI")
        c.execute("DISCARD")

    def test_watch_aborts_exec(self, remote, remote2):
        c = remote.node
        c.execute("SET", "ww", "0")
        c.execute("WATCH", "ww")
        remote2.node.execute("SET", "ww", "99")  # concurrent write
        c.execute("MULTI")
        c.execute("SET", "ww", "mine")
        assert c.execute("EXEC") is None  # nil = aborted
        assert c.execute("GET", "ww") == b"99"

    def test_watch_clean_exec_passes(self, remote):
        c = remote.node
        c.execute("SET", "wc", "0")
        c.execute("WATCH", "wc")
        c.execute("MULTI")
        c.execute("SET", "wc", "new")
        assert c.execute("EXEC") is not None
        assert c.execute("GET", "wc") == b"new"

    def test_unwatch(self, remote, remote2):
        c = remote.node
        c.execute("SET", "wu", "0")
        c.execute("WATCH", "wu")
        remote2.node.execute("SET", "wu", "99")
        c.execute("UNWATCH")
        c.execute("MULTI")
        c.execute("SET", "wu", "mine")
        assert c.execute("EXEC") is not None  # watch was dropped
        assert c.execute("GET", "wu") == b"mine"

    def test_watch_inside_multi_forbidden(self, remote):
        c = remote.node
        c.execute("MULTI")
        with pytest.raises(RespError, match="WATCH inside MULTI"):
            c.execute("WATCH", "x")
        c.execute("DISCARD")

    def test_execabort_on_unknown_command(self, remote):
        c = remote.node
        c.execute("MULTI")
        with pytest.raises(RespError, match="unknown command"):
            c.execute("NOSUCHCMD")
        with pytest.raises(RespError, match="EXECABORT"):
            c.execute("EXEC")

    def test_per_command_errors_as_values(self, remote):
        c = remote.node
        c.execute("MULTI")
        c.execute("SET", "we", "x")
        c.execute("LPUSH", "we", "y")  # WRONGTYPE at exec time
        out = c.execute("EXEC")
        assert out[0] in (b"OK", "OK")
        assert isinstance(out[1], RespError)

    def test_blocking_degrades_inside_exec(self, remote):
        c = remote.node
        c.execute("MULTI")
        c.execute("BLPOP", "noq", "5")
        t0 = time.time()
        out = c.execute("EXEC")
        assert time.time() - t0 < 2.0  # no 5s park
        assert out[0] is None

    def test_watch_on_absent_key_sees_creation(self, remote, remote2):
        c = remote.node
        c.execute("WATCH", "wabsent")
        remote2.node.execute("SET", "wabsent", "created")
        c.execute("MULTI")
        c.execute("SET", "wabsent", "mine")
        assert c.execute("EXEC") is None

    def test_reset_clears_tx_state(self, remote):
        c = remote.node
        c.execute("MULTI")
        c.execute("SET", "wr", "x")
        assert c.execute("RESET") in (b"RESET", "RESET")
        with pytest.raises(RespError, match="EXEC without MULTI"):
            c.execute("EXEC")


class TestCommitPlan:
    """The shared sync/async commit planner (review fix): retry never
    re-sends applied frames, partial commits classify loudly."""

    def _plan(self):
        from redisson_tpu.services.transactions import CommitPlan

        versions = {"a": 1, "b": 2}
        ops = [("get_map", "a", "fast_put", ("k", 1), {}),
               ("get_map", "c", "fast_put", ("k", 2), {})]
        return CommitPlan(versions, ops, ["a", "c"], ["a", "b", "c"])

    def test_frames_split_versions_and_ops(self):
        plan = self._plan()
        frames = plan.frames({"n1": ["a", "b"], "n2": ["c"]})
        by_key = {f[0]: f for f in frames}
        assert by_key["n1"][2] == {"a": 1, "b": 2}
        assert [op[1] for op in by_key["n1"][3]] == ["a"]
        assert by_key["n2"][2] == {} and [op[1] for op in by_key["n2"][3]] == ["c"]

    def test_remaining_excludes_done(self):
        plan = self._plan()
        plan.done.update(["a", "b"])
        assert plan.remaining() == ["c"]
        # retried grouping only covers the un-applied names
        frames = plan.frames({"n2": plan.remaining()})
        assert len(frames) == 1 and frames[0][1] == ["c"]

    def test_check_phase_only_multi_frame_and_clean(self):
        plan = self._plan()
        two = plan.frames({"n1": ["a", "b"], "n2": ["c"]})
        one = plan.frames({"n1": ["a", "b", "c"]})
        assert plan.needs_check_phase(two) is True
        assert plan.needs_check_phase(one) is False
        plan.done.add("a")
        assert plan.needs_check_phase(two) is False  # post-partial: no lying

    def test_classify(self):
        plan = self._plan()
        assert plan.classify("TXCONFLICT object 'a' changed", 0, 3) == "conflict"
        plan.done.add("a")
        assert plan.classify("TXCONFLICT object 'b' changed", 0, 3) == "partial"
        assert plan.classify("MOVED 12 n2", 0, 3) == "retry"
        assert plan.classify("MOVED 12 n2", 2, 3) == "raise"  # attempts spent
        assert plan.classify("ERR boom", 0, 3) == "raise"
        err = plan.partial_error("TXCONFLICT object 'b' changed")
        assert "PARTIALLY COMMITTED" in str(err)
