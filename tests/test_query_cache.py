"""Content-addressed staged-query cache (core/kernels.py): hot-set serving
loops skip the pack + h2d upload; correctness is exact because reuse is
keyed on the operand BYTES, not object identity (VERDICT r4 next-step #1)."""
import numpy as np
import pytest

import redisson_tpu
from redisson_tpu.core import kernels as K


@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


def test_digest_is_content_addressed():
    a = np.arange(10_000, dtype=np.int64)
    b = a.copy()
    assert K.query_digest(a) == K.query_digest(b)  # same bytes, new object
    b[0] += 1
    assert K.query_digest(a) != K.query_digest(b)  # mutation changes the key
    assert K.query_digest(a) != K.query_digest(a.astype(np.int32))  # dtype
    assert K.query_digest(a, extra=b"x") != K.query_digest(a, extra=b"y")


def test_cache_lru_and_size_cap():
    K._QCACHE.clear()
    for i in range(K._QCACHE_SLOTS + 3):
        K.query_cache_put(b"d%d" % i, np.zeros(8, np.uint32))
    assert len(K._QCACHE) == K._QCACHE_SLOTS
    assert K.query_cache_get(b"d0") is None  # evicted
    assert K.query_cache_get(b"d%d" % (K._QCACHE_SLOTS + 2)) is not None
    # oversized buffers are never pinned
    K.query_cache_put(b"big", np.zeros(K._QCACHE_MAX_BYTES + 1, np.uint8))
    assert K.query_cache_get(b"big") is None


def test_bloom_array_hot_flush_reuses_buffer(client):
    arr = client.get_bloom_filter_array("qc:bank")
    assert arr.try_init(tenants=16, expected_insertions=100_000,
                        false_probability=0.01)
    rng = np.random.default_rng(0)
    keys = rng.integers(0, 1 << 60, 8192).astype(np.int64)
    t = (np.arange(8192) % 16).astype(np.int32)
    arr.add_each(t, keys)
    K._QCACHE.clear()
    f1 = arr.contains(t, keys)
    assert len(K._QCACHE) == 1  # staged buffer cached
    # a new array object with IDENTICAL content hits the cache
    f2 = arr.contains(t.copy(), keys.copy())
    assert len(K._QCACHE) == 1
    np.testing.assert_array_equal(f1, f2)
    assert f1.all()
    # mutated content misses (correctness over reuse)
    keys2 = keys.copy()
    keys2[0] = 12345
    f3 = arr.contains(t, keys2)
    assert len(K._QCACHE) == 2
    assert f3[1:].all()


def test_mutation_between_flushes_is_never_served_stale(client):
    """The exact hazard identity caching would have: mutate the caller's
    array in place between two flushes."""
    bf = client.get_bloom_filter("qc:single")
    assert bf.try_init(100_000, 0.01)
    keys = np.arange(8192, dtype=np.int64)
    bf.add_each(keys)
    assert bf.contains_each(keys).all()
    keys += 50_000_000  # in-place mutation: absent keys now
    found = bf.contains_each(keys)
    assert found.mean() < 0.05  # would be 1.0 if the stale buffer served


def test_small_flushes_bypass_cache(client):
    K._QCACHE.clear()
    bf = client.get_bloom_filter("qc:small")
    assert bf.try_init(10_000, 0.01)
    bf.add_each(np.arange(100, dtype=np.int64))
    bf.contains_each(np.arange(100, dtype=np.int64))
    assert len(K._QCACHE) == 0  # under the 4096-key threshold
