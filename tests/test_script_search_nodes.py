"""Script/Function services, Search service, Nodes admin API.

Parity seams: RedissonScript (EVAL/EVALSHA + NOSCRIPT fallback,
CommandAsyncService.java:400-512), RedissonFuction (FUNCTION LOAD/FCALL),
RedissonSearch (FT.CREATE/SEARCH/AGGREGATE), redisnode/* (PING/INFO/TIME).
"""
import threading
import time

import numpy as np
import pytest

from redisson_tpu.client.redisson import RedissonTpu
from redisson_tpu.services.script import NoScriptError, sha1_of
from redisson_tpu.services.search import (
    And,
    Eq,
    FieldType,
    Ge,
    Gt,
    In,
    Le,
    Lt,
    Or,
    Range,
    Text,
)


@pytest.fixture()
def client():
    c = RedissonTpu.create()
    yield c
    c.shutdown()


# -- scripts -----------------------------------------------------------------


def _transfer(ctx, keys, args):
    """Move `amount` between two atomic longs iff funds suffice."""
    src, dst = ctx.get_atomic_long(keys[0]), ctx.get_atomic_long(keys[1])
    amount = args[0]
    if src.get() < amount:
        return False
    src.add_and_get(-amount)
    dst.add_and_get(amount)
    return True


def test_eval_atomic_transfer(client):
    client.get_atomic_long("acct:a").set(100)
    client.get_atomic_long("acct:b").set(0)
    s = client.get_script()
    assert s.eval(_transfer, ["acct:a", "acct:b"], [30]) is True
    assert client.get_atomic_long("acct:a").get() == 70
    assert client.get_atomic_long("acct:b").get() == 30
    assert s.eval(_transfer, ["acct:a", "acct:b"], [1000]) is False


def test_script_load_and_eval_sha(client):
    s = client.get_script()
    sha = s.script_load(_transfer)
    assert sha == sha1_of(_transfer)
    assert s.script_exists(sha) == [True]
    assert s.script_exists("0" * 40) == [False]
    client.get_atomic_long("acct:x").set(5)
    client.get_atomic_long("acct:y").set(0)
    assert s.eval_sha(sha, ["acct:x", "acct:y"], [5]) is True
    with pytest.raises(NoScriptError):
        s.eval_sha("f" * 40)
    s.script_flush()
    assert s.script_exists(sha) == [False]


def test_eval_with_cache_noscript_fallback(client):
    """EVAL→EVALSHA: first call loads, second hits the cache."""
    s = client.get_script()
    sha = sha1_of(_transfer)
    assert s.script_exists(sha) == [False]
    client.get_atomic_long("acct:m").set(10)
    client.get_atomic_long("acct:n").set(0)
    assert s.eval_with_cache(_transfer, ["acct:m", "acct:n"], [10]) is True
    assert s.script_exists(sha) == [True]  # loaded by the fallback


def test_script_cache_shared_across_handles(client):
    sha = client.get_script().script_load(_transfer)
    assert client.get_script().script_exists(sha) == [True]


def test_script_atomicity_under_contention(client):
    """Concurrent transfers must conserve the total (Lua-equivalent)."""
    client.get_atomic_long("bank:a").set(1000)
    client.get_atomic_long("bank:b").set(1000)
    s = client.get_script()

    def worker(src, dst):
        for _ in range(100):
            s.eval(_transfer, [src, dst], [1])

    ts = [
        threading.Thread(target=worker, args=("bank:a", "bank:b")),
        threading.Thread(target=worker, args=("bank:b", "bank:a")),
        threading.Thread(target=worker, args=("bank:a", "bank:b")),
    ]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    total = client.get_atomic_long("bank:a").get() + client.get_atomic_long("bank:b").get()
    assert total == 2000


def test_function_library(client):
    f = client.get_function()
    f.load("mylib", {"bump": lambda ctx, keys, args: ctx.get_atomic_long(keys[0]).add_and_get(args[0])})
    assert f.call("bump", ["fn:c"], [7]) == 7
    assert f.call("bump", ["fn:c"], [3]) == 10
    assert "mylib" in f.list()
    with pytest.raises(ValueError):
        f.load("mylib", {})
    f.load("mylib", {"noop": lambda ctx, keys, args: None}, replace=True)
    with pytest.raises(KeyError):
        f.call("bump")  # replaced away
    assert f.unload("mylib") is True
    assert f.unload("mylib") is False


# -- search ------------------------------------------------------------------


SCHEMA = {
    "title": FieldType.TEXT,
    "category": FieldType.TAG,
    "price": FieldType.NUMERIC,
    "stock": FieldType.NUMERIC,
}


def _products(search):
    search.create_index("idx:prod", SCHEMA, prefixes=["prod:"])
    docs = [
        ("p1", {"title": "red widget deluxe", "category": "widgets", "price": 9.5, "stock": 3}),
        ("p2", {"title": "blue widget", "category": "widgets", "price": 12.0, "stock": 0}),
        ("p3", {"title": "green gadget", "category": "gadgets", "price": 7.25, "stock": 10}),
        ("p4", {"title": "red gadget pro", "category": "gadgets", "price": 30.0, "stock": 2}),
        ("p5", {"title": "widget refill pack", "category": "parts", "price": 2.0, "stock": 99}),
    ]
    for d, f in docs:
        search.add_document("idx:prod", d, f)
    return docs


def test_search_text_and(client):
    s = client.get_search()
    _products(s)
    r = s.search("idx:prod", Text("title", "red widget"))
    assert [d for d, _ in r.docs] == ["p1"]
    r = s.search("idx:prod", Text("title", "widget"))
    assert {d for d, _ in r.docs} == {"p1", "p2", "p5"}


def test_search_tag_and_numeric_range(client):
    s = client.get_search()
    _products(s)
    r = s.search("idx:prod", And([Eq("category", "gadgets"), Lt("price", 20)]))
    assert [d for d, _ in r.docs] == ["p3"]
    r = s.search("idx:prod", Range("price", 7, 12))
    assert {d for d, _ in r.docs} == {"p1", "p2", "p3"}
    r = s.search("idx:prod", Gt("stock", 0))
    assert {d for d, _ in r.docs} == {"p1", "p3", "p4", "p5"}


def test_search_or_in_conditions(client):
    s = client.get_search()
    _products(s)
    r = s.search("idx:prod", Or([Eq("category", "parts"), Ge("price", 30)]))
    assert {d for d, _ in r.docs} == {"p4", "p5"}
    r = s.search("idx:prod", In("category", ["widgets", "parts"]))
    assert {d for d, _ in r.docs} == {"p1", "p2", "p5"}


def test_search_sort_and_paging(client):
    s = client.get_search()
    _products(s)
    r = s.search("idx:prod", sort_by="price", limit=2)
    assert [d for d, _ in r.docs] == ["p5", "p3"]
    assert r.total == 5
    r2 = s.search("idx:prod", sort_by="price", offset=2, limit=2)
    assert [d for d, _ in r2.docs] == ["p1", "p2"]
    r3 = s.search("idx:prod", sort_by="price", descending=True, limit=1)
    assert [d for d, _ in r3.docs] == ["p4"]


def test_search_update_and_remove_document(client):
    s = client.get_search()
    _products(s)
    s.add_document("idx:prod", "p2", {"title": "blue widget v2", "category": "widgets", "price": 11.0, "stock": 5})
    r = s.search("idx:prod", Text("title", "v2"))
    assert [d for d, _ in r.docs] == ["p2"]
    assert s.search("idx:prod", Eq("price", 12.0)).total == 0
    assert s.remove_document("idx:prod", "p2") is True
    assert s.search("idx:prod", Text("title", "widget")).total == 2
    assert s.remove_document("idx:prod", "p2") is False


def test_search_aggregate(client):
    s = client.get_search()
    _products(s)
    rows = s.aggregate(
        "idx:prod",
        group_by="category",
        reducers={"n": ("count", None), "avg_price": ("avg", "price"), "max_price": ("max", "price")},
    )
    by_cat = {r["category"]: r for r in rows}
    assert by_cat["widgets"]["n"] == 2
    assert by_cat["widgets"]["avg_price"] == pytest.approx(10.75)
    assert by_cat["gadgets"]["max_price"] == 30.0
    total = s.aggregate("idx:prod", reducers={"sum_stock": ("sum", "stock")})
    assert total[0]["sum_stock"] == 114


def test_search_sync_from_maps(client):
    s = client.get_search()
    s.create_index("idx:users", {"name": FieldType.TEXT, "age": FieldType.NUMERIC}, prefixes=["users:"])
    m = client.get_map("users:eu")
    m.put("u1", {"name": "ada lovelace", "age": 36})
    m.put("u2", {"name": "alan turing", "age": 41})
    client.get_map("other:na").put("u3", {"name": "nope", "age": 99})
    n = s.sync("idx:users")
    assert n == 2
    assert s.search("idx:users", Text("name", "ada")).total == 1
    assert s.search("idx:users", Gt("age", 40)).total == 1
    # unchanged map -> version-diffed scan skips it
    assert s.sync("idx:users") == 0
    m.put("u4", {"name": "grace hopper", "age": 46})
    assert s.sync("idx:users") >= 1


def test_search_index_lifecycle(client):
    s = client.get_search()
    assert s.create("idx:a", {"x": FieldType.NUMERIC}) is True
    with pytest.raises(ValueError):
        s.create_index("idx:a", {})
    assert "idx:a" in s.index_names()
    info = s.info("idx:a")
    assert info["num_docs"] == 0 and info["schema"] == {"x": FieldType.NUMERIC}
    assert s.drop_index("idx:a") is True
    with pytest.raises(KeyError):
        s.search("idx:a")


def test_search_scales_vectorized(client):
    """Numeric filtering is one device op over all docs — sanity at 20k."""
    s = client.get_search()
    s.create_index("idx:big", {"v": FieldType.NUMERIC})
    rng = np.random.default_rng(0)
    vals = rng.uniform(0, 100, 20_000)
    for i, v in enumerate(vals):
        s.add_document("idx:big", f"d{i}", {"v": float(v)})
    r = s.search("idx:big", Range("v", 10, 20), limit=30_000)
    expected = int(((vals >= 10) & (vals <= 20)).sum())
    assert r.total == expected


# -- nodes -------------------------------------------------------------------


def test_embedded_nodes_group(client):
    ng = client.get_nodes_group()
    assert len(ng) >= 1
    assert ng.ping_all()
    node = ng.nodes()[0]
    info = node.info()
    assert info["keys"] >= 0 and "platform" in info
    assert ng.node(node.id) is node
    assert ng.node("nope:999") is None
    assert node.time() > 0


def test_remote_nodes_group(client):
    from redisson_tpu.client.nodes import NodesGroup
    from redisson_tpu.net.client import NodeClient
    from redisson_tpu.server.server import ServerThread

    with ServerThread(engine=client.engine, port=0) as st:
        nc = NodeClient(st.address)
        ng = NodesGroup.remote(nc)
        assert ng.ping_all()
        n = ng.nodes()[0]
        assert n.time() > 0
        info = n.info()
        assert isinstance(info, dict) and info
        mem = n.memory()
        assert isinstance(mem, dict)
        nc.close()


# -- review regressions ------------------------------------------------------


def test_engine_service_singleton_thread_safe(client):
    import threading

    results = []
    barrier = threading.Barrier(8)

    def grab():
        barrier.wait()
        results.append(client.get_script())

    ts = [threading.Thread(target=grab) for _ in range(8)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert all(r is results[0] for r in results)


def test_jcache_replace_preserves_ttl(client):
    from redisson_tpu.client.jcache import CacheConfig, ExpiryPolicy

    cm = client.get_cache_manager()
    cache = cm.create_cache("crepl", CacheConfig(expiry=ExpiryPolicy.created(0.12)))
    cache.put("k", 1)
    time.sleep(0.05)
    assert cache.replace("k", 2) is True      # must NOT wipe or re-arm the TTL
    assert cache.get_and_replace("k", 3) == 2
    time.sleep(0.1)                           # ~0.15s since creation
    assert cache.get("k") is None
    assert cache.replace("missing", 1) is False


def test_jcache_statistics_disabled(client):
    from redisson_tpu.client.jcache import CacheConfig

    cm = client.get_cache_manager()
    cache = cm.create_cache("cnostat", CacheConfig(statistics_enabled=False))
    cache.put("a", 1)
    cache.get("a")
    cache.remove("a")
    st = cache.statistics
    assert st.puts == 0 and st.hits == 0 and st.removals == 0


def test_jcache_remove_all_counts(client):
    cm = client.get_cache_manager()
    cache = cm.create_cache("crm")
    cache.put_all({"a": 1, "b": 2, "c": 3})
    cache.remove_all(["a", "b"])
    assert cache.statistics.removals == 2
    cache.remove_all()
    assert cache.statistics.removals == 3


def test_eviction_task_dropped_when_record_deleted(client):
    client.engine.eviction.min_delay = 0.02
    client.engine.eviction.start_delay = 0.02
    mc = client.get_map_cache("drop:mc")
    mc.put("k", "v")
    ev = client.engine.eviction
    assert "drop:mc" in ev._tasks
    # let at least one sweep observe the record existing — only a record that
    # has been seen alive is dropped on deletion (never-created names persist)
    first = ev.sweeps
    deadline = time.time() + 5
    while ev.sweeps < first + 2 and time.time() < deadline:
        time.sleep(0.02)
    client.engine.store.delete("drop:mc")
    deadline = time.time() + 5
    while "drop:mc" in client.engine.eviction._tasks and time.time() < deadline:
        time.sleep(0.02)
    assert "drop:mc" not in client.engine.eviction._tasks


def test_localcache_no_double_broadcast_on_fast_put_if_absent(client):
    msgs = []
    client.engine.pubsub.subscribe("redisson_local_cache:lc:dup", lambda c, m: msgs.append(m))
    m = client.get_local_cached_map("lc:dup")
    assert m.fast_put_if_absent("k", 1) is True
    assert len(msgs) == 1
