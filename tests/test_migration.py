"""Live slot migration: MIGRATING/IMPORTING window, ASK redirects, rebalance
under load with zero lost acked writes (VERDICT round-1 next-step #2;
reference: cluster/ClusterConnectionManager.java:358-450 checkSlotsMigration
+ command/RedisExecutor.java ASK handling)."""
import threading
import time

import pytest

from redisson_tpu.harness import ClusterRunner, _exec
from redisson_tpu.net.resp import RespError
from redisson_tpu.server.migration import migrate_slots
from redisson_tpu.utils.crc16 import calc_slot


@pytest.fixture()
def cluster2():
    runner = ClusterRunner(masters=2).run()
    yield runner
    runner.shutdown()


def _owner_index(runner, slot: int) -> int:
    return next(
        i for i, (lo, hi) in enumerate(runner.slot_ranges) if lo <= slot <= hi
    )


def test_migrate_slot_moves_records_and_view(cluster2):
    client = cluster2.client(scan_interval=0)
    try:
        client.get_bucket("mig-key").set("payload")
        slot = calc_slot(b"mig-key")
        si = _owner_index(cluster2, slot)
        ti = 1 - si
        source = cluster2.masters[si]
        target = cluster2.masters[ti]
        moved = migrate_slots(source.address, target.address, [slot])
        assert moved >= 1
        # record physically moved
        assert not source.server.server.engine.store.exists("mig-key")
        assert target.server.server.engine.store.exists("mig-key")
        # window closed on both sides
        assert not source.server.server.migrating_slots
        assert not target.server.server.importing_slots
        # client converges via MOVED/refresh and still reads the value
        client.refresh_topology()
        assert client.get_bucket("mig-key").get() == "payload"
        # writes land on the new owner
        client.get_bucket("mig-key").set("v2")
        assert target.server.server.engine.store.get("mig-key").host is not None
    finally:
        client.shutdown()


def test_ask_redirect_during_window(cluster2):
    client = cluster2.client(scan_interval=0)
    try:
        client.get_bucket("ask-key").set("here")
        slot = calc_slot(b"ask-key")
        si = _owner_index(cluster2, slot)
        source = cluster2.masters[si]
        target = cluster2.masters[1 - si]
        # open the window by hand and drain the one record
        with target.server.client() as c:
            _exec(c, "CLUSTER", "SETSLOT", slot, "IMPORTING", source.address)
        with source.server.client() as c:
            _exec(c, "CLUSTER", "SETSLOT", slot, "MIGRATING", target.address)
            assert _exec(c, "CLUSTER", "MIGRATESLOT", slot) == 1
            # moved-away key: raw source connection now gets ASK
            reply = c.execute("GET", "ask-key")
            assert isinstance(reply, RespError) and str(reply).startswith("ASK ")
            # creating a NEW record in the migrating slot is barred too
            # ({ask-key} hashtag pins it to the same slot)
            reply = c.execute("SET", "{ask-key}fresh", "x")
            assert isinstance(reply, RespError) and str(reply).startswith("ASK ")
        # the cluster client follows ASK transparently, no topology change
        assert client.get_bucket("ask-key").get() == "here"
        client.get_bucket("{ask-key}fresh").set("made-on-target")
        assert target.server.server.engine.store.exists("{ask-key}fresh")
        # ASKING is one-shot: un-asked command on target still MOVED
        with target.server.client() as c:
            reply = c.execute("GET", "ask-key")
            assert isinstance(reply, RespError) and str(reply).startswith("MOVED ")
        # close the window; the orchestrator path would SETVIEW + NODE
        with source.server.client() as c:
            _exec(c, "CLUSTER", "SETSLOT", slot, "STABLE")
        with target.server.client() as c:
            _exec(c, "CLUSTER", "SETSLOT", slot, "STABLE")
    finally:
        client.shutdown()


def test_rebalance_under_load_zero_lost_acked_writes(cluster2):
    """The chaos criterion: migrate a busy slot range mid-load; every write
    the client saw acknowledged must be readable afterwards."""
    client = cluster2.client(scan_interval=0)
    stop = threading.Event()
    acked: dict = {}
    errors: list = []

    # all keys share slot range of master 0 via distinct names across many
    # slots in [lo0, hi0]; we migrate the busiest sub-range while writing
    lo0, hi0 = cluster2.slot_ranges[0]
    keys = [f"load-{i}" for i in range(400)]
    keys = [k for k in keys if lo0 <= calc_slot(k.encode()) <= hi0][:120]
    assert len(keys) >= 50

    def writer(worker: int):
        n = 0
        while not stop.is_set():
            k = keys[(n * 7 + worker) % len(keys)]
            try:
                v = client.execute("INCR", k)
                acked[k] = max(acked.get(k, 0), int(v))
            except Exception as e:  # noqa: BLE001 — unacked; not counted
                errors.append(e)
            n += 1

    threads = [threading.Thread(target=writer, args=(w,)) for w in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.3)  # build up traffic before the reshard
    slots = sorted({calc_slot(k.encode()) for k in keys})
    moved = migrate_slots(
        cluster2.masters[0].address, cluster2.masters[1].address, slots
    )
    time.sleep(0.3)  # keep writing after the flip
    stop.set()
    for t in threads:
        t.join(timeout=10)
    assert moved >= len(keys) * 0.5  # most keys physically moved mid-load
    client.refresh_topology()
    lost = []
    for k, highest in acked.items():
        cur = client.execute("GET", k)
        cur = int(cur) if cur is not None else 0
        if cur < highest:
            lost.append((k, highest, cur))
    assert not lost, f"lost acked writes: {lost[:10]}"
    # and the records really live on the target now
    tgt_engine = cluster2.masters[1].server.server.engine
    assert sum(1 for k in acked if tgt_engine.store.exists(k)) == len(acked)
    client.shutdown()


def test_rebalance_under_load_deletes_do_not_resurrect(cluster2):
    """Chaos audit for DELETES: a DEL acked during a slot drain must stay
    deleted after the slot finalizes (advisor r2 high finding — a delete
    landing between the snapshot leaving and the drain's re-check used to
    resurrect from the migrated copy).  Each key has exactly ONE writer
    thread issuing SET/DEL, so the last acked op per key is deterministic."""
    client = cluster2.client(scan_interval=0)
    stop = threading.Event()
    last_acked: dict = {}  # key -> ("set", value) | ("del",)
    errors: list = []

    lo0, hi0 = cluster2.slot_ranges[0]
    keys = [f"dchaos-{i}" for i in range(600)]
    keys = [k for k in keys if lo0 <= calc_slot(k.encode()) <= hi0][:80]
    assert len(keys) >= 40

    def writer(worker: int, nworkers: int):
        mine = keys[worker::nworkers]
        n = 0
        while not stop.is_set():
            k = mine[n % len(mine)]
            try:
                if n % 3 == 2:
                    client.execute("DEL", k)
                    last_acked[k] = ("del",)
                else:
                    v = f"v{worker}-{n}"
                    client.execute("SET", k, v)
                    last_acked[k] = ("set", v)
            except Exception as e:  # noqa: BLE001 — unacked; not counted
                errors.append(e)
            n += 1

    nworkers = 4
    threads = [
        threading.Thread(target=writer, args=(w, nworkers)) for w in range(nworkers)
    ]
    for t in threads:
        t.start()
    time.sleep(0.3)
    slots = sorted({calc_slot(k.encode()) for k in keys})
    migrate_slots(cluster2.masters[0].address, cluster2.masters[1].address, slots)
    time.sleep(0.3)
    stop.set()
    for t in threads:
        t.join(timeout=10)
    client.refresh_topology()
    wrong = []
    for k, op in last_acked.items():
        cur = client.execute("GET", k)
        cur = bytes(cur).decode() if cur is not None else None
        if op[0] == "del" and cur is not None:
            wrong.append((k, "resurrected", cur))
        elif op[0] == "set" and cur != op[1]:
            wrong.append((k, f"expected {op[1]}", cur))
    assert not wrong, f"post-drain state diverged: {wrong[:10]}"
    client.shutdown()


def test_migration_with_cluster_pipeline(cluster2):
    """execute_many rows hitting a migration window re-route via ASK."""
    client = cluster2.client(scan_interval=0)
    try:
        names = [f"pipe-{i}" for i in range(40)]
        client.execute_many([("SET", n, str(i)) for i, n in enumerate(names)])
        lo0, hi0 = cluster2.slot_ranges[0]
        mine = [n for n in names if lo0 <= calc_slot(n.encode()) <= hi0]
        slots = sorted({calc_slot(n.encode()) for n in mine})
        migrate_slots(
            cluster2.masters[0].address, cluster2.masters[1].address, slots
        )
        # stale client pipelines still resolve every row (MOVED/ASK fallback)
        replies = client.execute_many([("GET", n) for n in names])
        assert [int(r) for r in replies] == list(range(40))
    finally:
        client.shutdown()


def test_tryagain_for_mixed_multikey_and_absent_guard(cluster2):
    """Multi-key ops spanning a half-drained window get TRYAGAIN (neither
    node holds every key); absent-key touches (GET/DEL) get ASK even when
    racing past the pre-dispatch check (store-level absent guard)."""
    client = cluster2.client(scan_interval=0)
    try:
        a, b = "{mix}a", "{mix}b"
        client.get_bucket(a).set("1")
        client.get_bucket(b).set("2")
        slot = calc_slot(b"mix")
        si = _owner_index(cluster2, slot)
        source = cluster2.masters[si]
        target = cluster2.masters[1 - si]
        with target.server.client() as c:
            _exec(c, "CLUSTER", "SETSLOT", slot, "IMPORTING", source.address)
        with source.server.client() as c:
            _exec(c, "CLUSTER", "SETSLOT", slot, "MIGRATING", target.address)
            # drain exactly ONE of the two records -> mixed window
            assert _exec(c, "CLUSTER", "MIGRATESLOT", slot, 1) == 1
            reply = c.execute("RENAME", a, b)
            assert isinstance(reply, RespError) and str(reply).startswith("TRYAGAIN")
            # single absent key: ASK straight from the store guard
            movedname = a if not source.server.server.engine.store.peek(a) else b
            assert isinstance(c.execute("GET", movedname), RespError)
            assert str(c.execute("DEL", movedname)).startswith("ASK ")
        # finish the drain; close the window via the orchestrator path
        moved = migrate_slots(source.address, target.address, [slot])
        assert moved >= 1
        client.refresh_topology()
        assert client.get_bucket(a).get() == "1"
        assert client.get_bucket(b).get() == "2"
    finally:
        client.shutdown()


def test_transactions_interleave_migration_no_torn_commits(cluster2):
    """VERDICT r3 #10: transactions + slot migration must interleave safely —
    every commit that reported success is fully visible afterward, every
    conflict-abort left nothing, and the TXEXEC whole-frame routing precheck
    keeps mid-migration commits atomic (bounced frames apply nothing and the
    client retries after a topology refresh)."""
    from redisson_tpu.services.transactions import TransactionException

    client = cluster2.client(scan_interval=0)
    committed: list = []
    aborted: list = []
    stop = threading.Event()

    def tx_writer(tag: str):
        i = 0
        while not stop.is_set():
            i += 1
            name = f"txm-{tag}-{i % 7}"
            try:
                tx = client.create_transaction()
                m = tx.get_map(name)
                cur = m.get("n") or 0
                m.put("n", cur + 1)
                m.fast_put(f"w{i}", tag)
                tx.commit()
                committed.append((name, cur + 1, f"w{i}"))
            except TransactionException:
                aborted.append(name)
            except RespError:
                # transient routing exhaustion mid-window: acceptable, but
                # must NOT have half-applied (audited below via version sums)
                aborted.append(name)

    threads = [threading.Thread(target=tx_writer, args=(t,)) for t in ("a", "b")]
    for th in threads:
        th.start()
    try:
        time.sleep(0.3)
        # bounce a band of slots back and forth while transactions run
        slots = sorted({calc_slot(f"txm-a-{j}".encode()) for j in range(7)}
                       | {calc_slot(f"txm-b-{j}".encode()) for j in range(7)})
        for _round in range(3):
            for slot in slots:
                si = _owner_index(cluster2, slot)
                src = cluster2.masters[si]
                dst = cluster2.masters[1 - si]
                try:
                    migrate_slots(src.address, dst.address, [slot])
                except Exception:
                    pass  # a busy window can refuse; writers keep going
                # keep the harness's notion of ownership fresh
                lo, hi = cluster2.slot_ranges[si]
            time.sleep(0.1)
    finally:
        stop.set()
        for th in threads:
            th.join(timeout=30.0)
    client.refresh_topology()
    # audit: every committed marker key is present (no torn commits)
    for name, _n, wkey in committed[-200:]:
        assert client.get_map(name).get(wkey) is not None, (name, wkey)
    assert len(committed) > 0
    client.shutdown()


def test_conditional_expiry_across_migration(cluster2):
    """EXPIRE NX/XX/GT/LT state must survive a slot move: the TTL travels
    with the migrated record and the conditional forms keep honoring it on
    the new owner."""
    client = cluster2.client(scan_interval=0)
    try:
        b = client.get_bucket("cem-key")
        b.set("v")
        assert b.expire_if_not_set(30.0) is True  # NX on fresh record
        slot = calc_slot(b"cem-key")
        si = _owner_index(cluster2, slot)
        moved = migrate_slots(
            cluster2.masters[si].address,
            cluster2.masters[1 - si].address,
            [slot],
        )
        assert moved >= 1
        client.refresh_topology()
        # TTL survived the move
        remain = b.remain_time_to_live()
        assert remain is not None and 20.0 < remain <= 30.0
        # conditional forms still see the carried TTL on the NEW owner
        assert b.expire_if_not_set(10.0) is False       # NX: TTL present
        assert b.expire_if_greater(60.0) is True        # GT: 60 > ~30
        assert b.expire_if_greater(5.0) is False
        assert b.expire_if_less(10.0) is True           # LT: 10 < 60
        remain = b.remain_time_to_live()
        assert remain is not None and remain <= 10.0
        assert b.get() == "v"
    finally:
        client.shutdown()
