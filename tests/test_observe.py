"""Tracing plane tests (ISSUE 12): per-frame stage spans, TRACE/SLOWLOG/
LATENCY parity verbs, fleet scrape, and the two cost/safety contracts —

  * DISARMED guard sites allocate NOTHING (the chaos-hook zero-cost
    discipline, extended to every trace site by line discovery across
    server/server.py, core/ioplane.py, server/registry.py);
  * ARMED replies are BYTE-IDENTICAL to disarmed, including under the
    3-frames-in-flight overlapped-readback shape (the tracer observes
    waits and work, it never reorders either).
"""
import threading
import time

import numpy as np
import pytest

from redisson_tpu.net.client import Connection
from redisson_tpu.observe import trace as obs
from redisson_tpu.server.server import ServerThread


@pytest.fixture(autouse=True)
def _restore_tracing():
    """Every test leaves the process tracer exactly as it found it (ring
    drained): a leaked armed tracer would silently tax every later test."""
    prev = obs.tracing_enabled()
    yield
    obs.set_tracing(prev)
    obs.TRACER.reset()
    obs.TRACER.slowlog_reset()
    obs.TRACER.latency_reset()
    obs.TRACER.slowlog_slower_than_us = 10_000


def _conn(st, timeout=60.0):
    return Connection(st.server.host, st.server.port, timeout=timeout)


# -- zero-alloc disarmed guards (discovery across every instrumented file) ----


def _trace_guard_lines(mod):
    """Line numbers of every tracing guard in `mod` — the exact sites the
    zero-cost contract covers.  Guards are written in one of three shapes
    (enforced here by discovery, like the fault-plane test): a read of the
    process-global ``_tracer``, a ``trace is not None`` branch on the
    threaded-through frame trace, or the lane occupancy's ``_tcur`` slot."""
    path = mod.__file__
    tokens = ("_tracer", "trace is not None", "_tcur", "done_tr is not None",
              "cur is not None", "current_trace()")
    lines = []
    with open(path) as fh:
        for no, line in enumerate(fh, 1):
            if "def " in line or "import" in line:
                continue
            if any(tok in line for tok in tokens):
                lines.append(no)
    return path, sorted(set(lines))


def test_trace_disarmed_guard_sites_allocate_nothing():
    """With tracing disarmed, a full wire workload crossing every
    instrumented chokepoint (parse, qos, dispatch, coalesced run, grouped
    readback, reply writer) must not allocate ANYTHING attributable to the
    discovered guard lines — the same allocator-level contract the
    fault-plane hooks carry (tests/test_perf_smoke.py)."""
    import tracemalloc

    import redisson_tpu.core.ioplane as ioplane_mod
    import redisson_tpu.server.registry as registry_mod
    import redisson_tpu.server.server as server_mod

    assert not obs.tracing_enabled(), "tracing leaked armed from another test"
    guards = {}
    for mod, floor in ((server_mod, 8), (ioplane_mod, 2), (registry_mod, 1)):
        path, lines = _trace_guard_lines(mod)
        assert len(lines) >= floor, (
            f"{path}: found only {len(lines)} trace guards — discovery "
            "tokens drifted from the instrumentation idiom"
        )
        guards[path] = set(lines)

    with ServerThread(port=0, workers=2) as st:
        conn = _conn(st)
        try:
            blob = np.ascontiguousarray(
                np.arange(128, dtype=np.int64) * 2654435761, "<i8"
            ).tobytes()
            assert conn.execute("BF.RESERVE", "za:bf", 0.01, 10_000) in (
                b"OK", "OK",
            )
            frame = [
                ("SET", "za:k", b"v"),
                ("BF.MADD64", "za:bf", blob),
                ("BF.MADD64", "za:bf", blob),   # coalescible run
                ("BF.MEXISTS64", "za:bf", blob),  # grouped readback
                ("PING",),
            ]
            conn.execute_many(frame, timeout=60.0)  # warm every lazy path
            tracemalloc.start(1)
            try:
                for _ in range(60):
                    conn.execute_many(frame, timeout=60.0)
                snap = tracemalloc.take_snapshot()
            finally:
                tracemalloc.stop()
        finally:
            conn.close()
    offenders = [
        (tb.filename, tb.lineno, stat.size)
        for stat in snap.statistics("lineno")
        for tb in [stat.traceback[0]]
        if tb.filename in guards and tb.lineno in guards[tb.filename]
        and stat.size > 0
    ]
    assert not offenders, (
        f"trace guard lines allocated with tracing DISARMED: {offenders}"
    )


# -- armed/disarmed byte-identity under overlapped readbacks -------------------


def _inflight_replies(traced: bool):
    """10 mixed frames, at most 3 in flight (the overlapped-readback shape
    the dispatch-ahead bound allows), replies drained in FIFO order."""
    prev = obs.set_tracing(traced)
    try:
        with ServerThread(port=0, workers=4) as st:
            conn = _conn(st, timeout=120.0)
            try:
                assert conn.execute("BF.RESERVE", "bi:bf", 0.01, 50_000) in (
                    b"OK", "OK",
                )
                out = []
                inflight = []
                for f in range(10):
                    keys = (
                        np.arange(400, dtype=np.int64) + f * 1000
                    ) * 2654435761
                    blob = np.ascontiguousarray(keys, "<i8").tobytes()
                    cmds = [
                        ("ECHO", f"f{f}".encode()),
                        ("BF.MADD64", "bi:bf", blob),
                        ("BF.MEXISTS64", "bi:bf", blob),
                        ("INCR", "bi:ctr"),
                    ]
                    inflight.append(conn.execute_many_lazy(cmds))
                    if len(inflight) > 3:  # 3 frames in flight
                        out.extend(inflight.pop(0).get(timeout=120.0))
                for h in inflight:
                    out.extend(h.get(timeout=120.0))
                return out
            finally:
                conn.close()
    finally:
        obs.set_tracing(prev)
        obs.TRACER.reset()
        obs.TRACER.slowlog_reset()


def test_armed_replies_byte_identical_with_three_frames_in_flight():
    a = _inflight_replies(traced=True)
    b = _inflight_replies(traced=False)
    assert len(a) == len(b)
    for i, (x, y) in enumerate(zip(a, b)):
        assert x == y, f"reply {i} diverged between tracing armed/disarmed"


# -- bounded ring + census drain ----------------------------------------------


def test_trace_ring_bounded_and_census_drains():
    from redisson_tpu.chaos.census import ResourceCensus

    obs.set_tracing(True)
    obs.TRACER.reset()
    with ServerThread(port=0) as st:
        census = ResourceCensus()
        census.track_server("srv", st.server)
        snap = census.snapshot()
        assert "srv.trace_ring_entries" in snap
        assert "srv.trace_inflight" in snap
        conn = _conn(st)
        try:
            assert conn.execute(
                "CONFIG", "SET", "trace-ring-capacity", "16"
            ) in (b"OK", "OK")
            # sustained load far past the ring capacity
            for _ in range(20):
                conn.execute_many([("PING",)] * 5, timeout=30.0)
            deadline = time.time() + 5
            while time.time() < deadline:
                c = st.server.tracer.census()
                if c["trace_inflight"] == 0:
                    break
                time.sleep(0.02)
            c = st.server.tracer.census()
            assert 0 < c["trace_ring_entries"] <= 16, c
            assert c["trace_inflight"] == 0, (
                "begun frames did not close their books at quiesce"
            )
            # metrics gauges carry the same rows
            mets = st.server.metrics.snapshot()
            assert 0 < mets["trace_ring_entries"] <= 16
            assert mets["trace_inflight"] == 0
            assert conn.execute("TRACE", "RESET") in (b"OK", "OK")
            # the RESET frame is itself traced and finishes AFTER the reset
            # applied — at most that one entry may remain
            time.sleep(0.1)
            assert st.server.tracer.census()["trace_ring_entries"] <= 1
        finally:
            conn.close()


# -- the acceptance waterfall: qos wait vs readback, separately attributed ----


def test_trace_get_waterfall_attributes_qos_wait_and_readback():
    """Hostile config2q-style mix, traced end to end: over the wire,
    TRACE GET must show a bulk frame whose `qos` span carries the bulk-gate
    wait and an interactive frame whose `readback` span carries the D2H —
    the two attributions that were previously indistinguishable."""
    obs.set_tracing(True)
    obs.TRACER.reset()
    blob = np.ascontiguousarray(
        np.arange(20_000, dtype=np.int64) * 2654435761, "<i8"
    ).tobytes()
    probe = np.ascontiguousarray(
        np.arange(64, dtype=np.int64) * 40503, "<i8"
    ).tobytes()
    with ServerThread(port=0, workers=4) as st:
        assert st.server.scheduler.armed
        admin = _conn(st)
        try:
            assert admin.execute("CONFIG", "SET", "qos-bulk-slots", "1") in (
                b"OK", "OK",
            )
            for i in range(2):
                admin.execute("BF.RESERVE", f"wf:bulk{i}{{hog}}", 0.01, 40_000)
            admin.execute("BF.RESERVE", "wf:int{ta}", 0.01, 10_000)
            admin.execute("BF.MADD64", "wf:int{ta}", probe)
        finally:
            admin.close()
        stop = threading.Event()
        errors = []

        def hog(j):
            try:
                c = _conn(st, timeout=120.0)
                try:
                    c.execute("CLIENT", "QOS", "CLASS", "bulk", "TENANT", "hog")
                    frame = [
                        ("BF.MADD64", f"wf:bulk{i}{{hog}}", blob)
                        for i in range(2)
                    ]
                    while not stop.is_set():
                        c.execute_many(frame, timeout=120.0)
                finally:
                    c.close()
            except Exception as e:  # noqa: BLE001
                if not stop.is_set():
                    errors.append(e)

        def interactive():
            try:
                c = _conn(st, timeout=120.0)
                try:
                    c.execute(
                        "CLIENT", "QOS", "CLASS", "interactive", "TENANT", "ta"
                    )
                    while not stop.is_set():
                        c.execute("BF.MEXISTS64", "wf:int{ta}", probe,
                                  timeout=120.0)
                finally:
                    c.close()
            except Exception as e:  # noqa: BLE001
                if not stop.is_set():
                    errors.append(e)

        threads = [
            threading.Thread(target=hog, args=(j,), daemon=True)
            for j in range(3)
        ] + [threading.Thread(target=interactive, daemon=True)]
        for t in threads:
            t.start()
        time.sleep(2.5)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors

        wire = _conn(st)
        try:
            entries = wire.execute("TRACE", "GET", "200", timeout=30.0)
        finally:
            wire.close()
    assert entries, "trace ring empty after a traced run"

    def spans_of(entry):
        return {
            bytes(s[0]).decode(): s for s in entry[7]
        }

    bulk_qos_waits = [
        spans_of(e)["qos"][2]
        for e in entries
        if bytes(e[5]) == b"bulk" and "qos" in spans_of(e)
    ]
    interactive_readbacks = [
        spans_of(e)["readback"][2]
        for e in entries
        if bytes(e[5]) == b"interactive" and "readback" in spans_of(e)
    ]
    # with bulk-slots=1 and 3 hog connections, somebody's frame sat behind
    # the admission gate for at least a millisecond
    assert bulk_qos_waits and max(bulk_qos_waits) > 1_000, bulk_qos_waits
    assert interactive_readbacks, (
        "no interactive frame recorded a readback span"
    )
    # the two attributions are on DIFFERENT frames: an interactive frame's
    # qos span (when present) is admission work, not the gate wait
    int_qos = [
        spans_of(e)["qos"][2]
        for e in entries
        if bytes(e[5]) == b"interactive" and "qos" in spans_of(e)
    ]
    assert int_qos and max(int_qos) < max(bulk_qos_waits), (
        "interactive frames waited on the bulk gate — attribution is wrong"
    )
    # coalesced bulk runs recorded ONE kernel span with member children
    kernel_entries = [
        e for e in entries
        if bytes(e[5]) == b"bulk" and "kernel" in spans_of(e)
    ]
    if kernel_entries:
        e = kernel_entries[0]
        members = [s for s in e[7] if bytes(s[0]) == b"kernel.member"]
        kernels = [s for s in e[7] if bytes(s[0]) == b"kernel"]
        assert len(kernels) >= 1 and len(members) >= 2


# -- SLOWLOG parity verbs ------------------------------------------------------


def test_slowlog_get_reset_len_with_threshold():
    obs.set_tracing(True)
    with ServerThread(port=0) as st:
        conn = _conn(st)
        try:
            # impossible threshold: nothing logs
            assert conn.execute(
                "CONFIG", "SET", "slowlog-log-slower-than", "-1"
            ) in (b"OK", "OK")
            st.server.tracer.slowlog_reset()
            conn.execute("PING")
            conn.execute("SET", "sl:k", b"v")
            time.sleep(0.1)
            assert conn.execute("SLOWLOG", "LEN") == 0
            # log-everything threshold
            conn.execute("CONFIG", "SET", "slowlog-log-slower-than", "0")
            conn.execute("SET", "sl:k2", b"v2")
            conn.execute("GET", "sl:k2")
            deadline = time.time() + 5
            while time.time() < deadline and conn.execute("SLOWLOG", "LEN") < 2:
                time.sleep(0.02)
            n = conn.execute("SLOWLOG", "LEN")
            assert n >= 2, n
            entries = conn.execute("SLOWLOG", "GET", "2")
            assert len(entries) == 2
            sid, ts, dur_us, cmd, stages = entries[0]
            assert sid > 0 and ts > 0 and dur_us >= 0
            # per-stage breakdown instead of Redis's flat duration
            stage_names = {bytes(s[0]) for s in stages}
            assert b"dispatch" in stage_names and b"reply" in stage_names
            # newest-first ordering (Redis parity)
            assert entries[0][0] > entries[1][0]
            assert conn.execute("SLOWLOG", "RESET") in (b"OK", "OK")
            # the RESET verb's own frame may re-log (threshold 0): raise it
            conn.execute(
                "CONFIG", "SET", "slowlog-log-slower-than", "10000000"
            )
            st.server.tracer.slowlog_reset()
            assert conn.execute("SLOWLOG", "LEN") == 0
        finally:
            conn.close()


# -- INFO commandstats + LATENCY ----------------------------------------------


def test_info_commandstats_section():
    with ServerThread(port=0) as st:
        conn = _conn(st)
        try:
            conn.execute("SET", "cs:k", b"v")
            conn.execute("GET", "cs:k")
            conn.execute("PING")
            text = bytes(conn.execute("INFO", "commandstats")).decode()
            assert text.startswith("# Commandstats")
            assert "cmdstat_set:calls=" in text
            assert "usec_per_call=" in text
            # plain INFO keeps its historical sections, commandstats-free
            plain = bytes(conn.execute("INFO")).decode()
            assert "cmdstat_" not in plain and "# Server" in plain
            # INFO all appends the section
            everything = bytes(conn.execute("INFO", "all")).decode()
            assert "# Server" in everything and "cmdstat_get:" in everything
        finally:
            conn.close()


def test_latency_history_and_reset_over_stage_histograms():
    obs.set_tracing(True)
    obs.TRACER.latency_reset()
    with ServerThread(port=0) as st:
        conn = _conn(st)
        try:
            for _ in range(5):
                conn.execute("PING")
            deadline = time.time() + 5
            while time.time() < deadline:
                if conn.execute("LATENCY", "HISTORY", "total"):
                    break
                time.sleep(0.02)
            hist = conn.execute("LATENCY", "HISTORY", "total")
            assert hist, "no total-latency samples after traced traffic"
            ts, ms = hist[-1]  # (unix ts, ms) — the Redis LATENCY contract
            assert ts > 0 and ms >= 1
            assert conn.execute("LATENCY", "HISTORY", "dispatch")
            latest = conn.execute("LATENCY", "LATEST")
            events = {bytes(row[0]) for row in latest}
            assert b"total" in events and b"dispatch" in events
            # disarm first: the RESET frame itself would otherwise re-seed
            # the event it just cleared when its reply span closes
            obs.set_tracing(False)
            time.sleep(0.05)
            n = conn.execute("LATENCY", "RESET", "total")
            assert n == 1
            assert conn.execute("LATENCY", "HISTORY", "total") == []
            # stage histograms also feed the MetricsRegistry exposition
            text = bytes(conn.execute("METRICS")).decode()
            assert "rtpu_stage_dispatch_count" in text
            assert "rtpu_stage_total_p99_seconds" in text
        finally:
            conn.close()


# -- exported gauges (the satellite bugfix) -----------------------------------


def test_dropped_pushes_and_shed_counters_in_prometheus_exposition():
    with ServerThread(port=0) as st:
        conn = _conn(st)
        try:
            text = bytes(conn.execute("METRICS")).decode()
        finally:
            conn.close()
    # dropped_pushes was census-only before ISSUE 12; the QoS cumulative
    # shed counters ride the same default registry
    assert "rtpu_dropped_pushes " in text
    assert "rtpu_qos_shed_ops " in text
    assert "rtpu_qos_shed_frames " in text
    assert "rtpu_trace_ring_entries " in text


# -- fleet-wide scrape ---------------------------------------------------------


def test_merge_prometheus_texts_labels_every_line():
    from redisson_tpu.utils.metrics import merge_prometheus_texts

    merged = merge_prometheus_texts({
        "h1:1": "rtpu_keys 3.0\nrtpu_up 1\n",
        "h2:2": 'rtpu_keys 5.0\nrtpu_lat{q="p99"} 0.2\n# comment\n',
    })
    lines = merged.strip().splitlines()
    assert 'rtpu_keys{node="h1:1"} 3.0' in lines
    assert 'rtpu_keys{node="h2:2"} 5.0' in lines
    # an existing label set keeps its labels, node appended
    assert 'rtpu_lat{q="p99",node="h2:2"} 0.2' in lines
    assert not any(line.startswith("#") for line in lines)


def test_metrics_cluster_aggregates_the_fleet():
    """The wire half of the one-pane-of-glass: METRICS CLUSTER on one node
    scrapes every master in its view and returns one labeled exposition."""
    with ServerThread(port=0) as a, ServerThread(port=0) as b:
        from redisson_tpu.utils.crc16 import calc_slot

        view = [
            ("0", "8191", a.server.host, str(a.server.port),
             a.server.node_id),
            ("8192", "16383", b.server.host, str(b.server.port),
             b.server.node_id),
        ]
        flat = [x for row in view for x in row]
        # a key whose slot the SECOND node owns
        key = next(
            f"mc:{i}" for i in range(500)
            if calc_slot(f"mc:{i}".encode()) >= 8192
        )
        ca = _conn(a)
        cb = _conn(b)
        try:
            assert ca.execute("CLUSTER", "SETVIEW", *flat) in (b"OK", "OK")
            assert cb.execute("CLUSTER", "SETVIEW", *flat) in (b"OK", "OK")
            assert cb.execute("SET", key, b"v") in (b"OK", "OK")
            text = bytes(ca.execute("METRICS", "CLUSTER")).decode()
        finally:
            ca.close()
            cb.close()
    la = f'node="{a.server.host}:{a.server.port}"'
    lb = f'node="{b.server.host}:{b.server.port}"'
    assert la in text and lb in text
    assert f"rtpu_keys{{{lb}}} 1.0" in text


def test_supervisor_scrape_merges_live_nodes():
    """ClusterSupervisor.scrape() — driven against in-process listeners
    (the supervisor half shares merge_prometheus_texts with the METRICS
    CLUSTER verb; real-process supervision is covered by
    tests/test_cluster_proc.py).  A dead node contributes nothing."""
    from redisson_tpu.cluster.supervisor import ClusterSupervisor

    class FakeNode:
        def __init__(self, host, port, up=True):
            self.host, self.port, self._up = host, port, up

        @property
        def address(self):
            return f"{self.host}:{self.port}"

        def alive(self):
            return self._up

    with ServerThread(port=0) as a, ServerThread(port=0) as b:
        sup = ClusterSupervisor(masters=1)  # construction only, never started
        sup.masters = [
            FakeNode(a.server.host, a.server.port),
            FakeNode(b.server.host, b.server.port),
            FakeNode("127.0.0.1", 1, up=False),  # dead: skipped silently
        ]
        text = sup.scrape()
    assert f'node="{a.server.host}:{a.server.port}"' in text
    assert f'node="{b.server.host}:{b.server.port}"' in text
    assert 'node="127.0.0.1:1"' not in text
    assert "rtpu_keys{" in text


# -- perf gate: armed-overhead row --------------------------------------------


def test_perf_gate_obs_overhead_row():
    import importlib.util
    import os

    spec = importlib.util.spec_from_file_location(
        "perf_gate",
        os.path.join(os.path.dirname(__file__), "..", "tools", "perf_gate.py"),
    )
    pg = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(pg)

    base = {"metric": "x", "value": 1000.0, "details": {}}

    def doc(ratio):
        return {
            "metric": "x", "value": 1000.0,
            "details": {"obs_armed_overhead_ratio": ratio},
        }

    # absent everywhere: n/a row, passes (first sight becomes the baseline)
    rows, ok = pg.compare(base, base, 0.05)
    assert ok
    # healthy ratio passes even vs an n/a baseline
    rows, ok = pg.compare(base, doc(0.995), 0.05)
    assert ok, rows
    # the 3% armed-overhead floor binds from first sight
    rows, ok = pg.compare(base, doc(0.90), 0.05)
    assert not ok
    assert any(
        "armed tracing" in r[0] and r[4] == "FAIL" for r in rows
    ), rows
