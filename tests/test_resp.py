"""RESP framing tests: native tokenizer vs pure-Python fallback parity.

Covers the marker set the reference decoder handles
(CommandDecoder.java:58-270: `_ , + - : $ = % * > ~ #`), incremental feeds,
and batched CRC16 slot calc parity with utils/crc16.py.
"""
import pytest

from redisson_tpu.net import _native, resp
from redisson_tpu.net.resp import (
    Push,
    RespError,
    RespParser,
    calc_slots,
    encode_command,
    encode_reply,
)
from redisson_tpu.utils.crc16 import calc_slot

HAS_NATIVE = _native.load() is not None

PARSERS = [False] + ([True] if HAS_NATIVE else [])


def mk(use_native):
    return RespParser(use_native=use_native)


@pytest.mark.parametrize("native", PARSERS)
def test_scalars(native):
    p = mk(native)
    data = b"+OK\r\n:42\r\n:-7\r\n$5\r\nhello\r\n$-1\r\n$0\r\n\r\n#t\r\n#f\r\n,3.5\r\n,inf\r\n_\r\n"
    vals = p.feed(data)
    assert vals == [b"OK", 42, -7, b"hello", None, b"", True, False, 3.5, float("inf"), None]
    assert p.pending_bytes == 0


@pytest.mark.parametrize("native", PARSERS)
def test_nested_aggregates(native):
    p = mk(native)
    data = b"*3\r\n:1\r\n*2\r\n$1\r\na\r\n$1\r\nb\r\n*-1\r\n"
    (v,) = p.feed(data)
    assert v == [1, [b"a", b"b"], None]


@pytest.mark.parametrize("native", PARSERS)
def test_resp3_map_set_push(native):
    p = mk(native)
    data = b"%2\r\n$1\r\nk\r\n:1\r\n$1\r\nj\r\n:2\r\n~2\r\n:1\r\n:2\r\n>2\r\n$7\r\nmessage\r\n$2\r\nhi\r\n"
    m, s, push = p.feed(data)
    assert m == {b"k": 1, b"j": 2}
    assert s == {1, 2}
    assert isinstance(push, Push) and push == [b"message", b"hi"]


@pytest.mark.parametrize("native", PARSERS)
def test_error_reply(native):
    p = mk(native)
    (e,) = p.feed(b"-ERR unknown command\r\n")
    assert isinstance(e, RespError)
    assert e.code == "ERR"


@pytest.mark.parametrize("native", PARSERS)
def test_incremental_byte_by_byte(native):
    p = mk(native)
    data = encode_command("SET", "key", "value") + b":1\r\n"
    got = []
    for i in range(len(data)):
        got.extend(p.feed(data[i : i + 1]))
    assert got == [[b"SET", b"key", b"value"], 1]


@pytest.mark.parametrize("native", PARSERS)
def test_incomplete_bulk_not_consumed(native):
    p = mk(native)
    assert p.feed(b"$13\r\nhalf") == []
    assert p.pending_bytes == len(b"$13\r\nhalf")
    assert p.feed(b"-and-done\r\n") == [b"half-and-done"]


@pytest.mark.parametrize("native", PARSERS)
def test_malformed_raises(native):
    p = mk(native)
    with pytest.raises(resp.ProtocolError):
        p.feed(b"!bogus\r\n")


@pytest.mark.parametrize("native", PARSERS)
def test_pipeline_many(native):
    p = mk(native)
    frame = encode_command("GET", "k")
    vals = p.feed(frame * 1000)
    assert len(vals) == 1000
    assert vals[0] == [b"GET", b"k"]


def test_encode_command_types():
    assert encode_command("SET", b"k", 5) == b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\n5\r\n"


def test_encode_reply_round_trip():
    p = RespParser(use_native=False)
    vals = [None, True, 7, 2.5, b"raw", "text", [1, [2, b"x"]], {b"a": 1}]
    data = b"".join(encode_reply(v) for v in vals)
    out = p.feed(data)
    assert out[0] is None
    assert out[1] == 1  # booleans encode as :1 on the RESP2 reply path
    assert out[2] == 7
    assert out[3] == 2.5
    assert out[4] == b"raw"
    assert out[5] == b"text"
    assert out[6] == [1, [2, b"x"]]
    assert out[7] == {b"a": 1}  # dict rides a RESP3 map frame


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib unavailable")
def test_native_crc16_matches_python():
    keys = [b"foo", b"bar{tag}baz", b"{user1000}.following", b"", b"{}", b"{x}"]
    assert calc_slots(keys) == [calc_slot(k) for k in keys]


@pytest.mark.skipif(not HAS_NATIVE, reason="native lib unavailable")
def test_native_matches_python_parser_on_stream():
    import random

    rng = random.Random(0)
    frames = []
    for _ in range(200):
        n = rng.randint(0, 5)
        frames.append(encode_command(*[bytes([rng.randint(65, 90)]) * rng.randint(0, 20) for _ in range(n + 1)]))
        frames.append(b":%d\r\n" % rng.randint(-(10**12), 10**12))
    blob = b"".join(frames)
    pn, pp = RespParser(True), RespParser(False)
    # feed in ragged chunks
    out_n, out_p = [], []
    i = 0
    while i < len(blob):
        j = min(len(blob), i + rng.randint(1, 97))
        out_n.extend(pn.feed(blob[i:j]))
        out_p.extend(pp.feed(blob[i:j]))
        i = j
    assert out_n == out_p


@pytest.mark.parametrize("native", PARSERS)
def test_giant_aggregate_over_64k_tokens(native):
    """A single array with >64k elements must not stall the parser
    (token-buffer growth path in the native scanner)."""
    p = mk(native)
    n = 70_000
    data = b"*%d\r\n" % n + b":1\r\n" * n + b"+OK\r\n"
    (arr, ok) = p.feed(data)
    assert len(arr) == n and ok == b"OK"
    assert p.pending_bytes == 0


def test_safe_pickle_blocks_gadgets():
    import pickle

    from redisson_tpu.net.safe_pickle import safe_loads

    class Evil:
        def __reduce__(self):
            import os

            return (os.system, ("true",))

    payload = pickle.dumps(Evil())
    with pytest.raises(pickle.UnpicklingError):
        safe_loads(payload)
    # data payloads still round-trip
    import numpy as np

    ok = pickle.dumps(((np.arange(3), {"a": 1}), {"k": b"v"}))
    args, kwargs = safe_loads(ok)
    assert kwargs == {"k": b"v"} and args[1] == {"a": 1}


def test_safe_pickle_blocks_dangerous_builtins():
    import pickle

    from redisson_tpu.net.safe_pickle import safe_loads

    payload = b"cbuiltins\neval\n."  # GLOBAL builtins.eval
    with pytest.raises(pickle.UnpicklingError):
        safe_loads(payload)


def test_safe_pickle_blocks_numpy_runstring_gadget():
    """Module-root allowances are gadget mines: numpy.testing's runstring
    execs a string.  The allowlist must be per-global, not per-root."""
    import pickle

    from redisson_tpu.net.safe_pickle import safe_loads

    payload = b"cnumpy.testing._private.utils\nrunstring\n."
    with pytest.raises(pickle.UnpicklingError):
        safe_loads(payload)
    # exceptions (server error shipping) still pass
    rt = pickle.dumps(ValueError("boom"))
    e = safe_loads(rt)
    assert isinstance(e, ValueError)
