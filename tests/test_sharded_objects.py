"""Sharded object handles on the ENGINE path (VERDICT round-1 next-step #1).

Runs on the forced 8-CPU-device mesh (conftest): the same shardings a v5e-8
slice would use.  Covers: object API through the engine, actual device
sharding of the plane, checkpoint round-trip with lazy re-shard, dp>1
meshes, and the wire surface (OBJCALL through a real server).
"""
import os

import numpy as np
import pytest

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

import redisson_tpu
from redisson_tpu.client.objects.sharded import BLOOM_SPEC, HLL_SPEC
from redisson_tpu.parallel.manager import MeshManager
from redisson_tpu.parallel.mesh import DP_AXIS, SHARD_AXIS


@pytest.fixture()
def client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


def test_sharded_bloom_array_basic(client):
    bf = client.get_sharded_bloom_filter_array("sb")
    assert bf.try_init(tenants=8, expected_insertions=50_000, false_probability=0.01)
    assert not bf.try_init(8, 1000, 0.1)
    assert bf.shards() == 8  # all 8 forced devices on the shard axis (dp=1)
    assert bf.get_size() % (128 * 8) == 0

    rng = np.random.default_rng(1)
    keys = rng.integers(0, 1 << 60, 4000).astype(np.int64)
    tenants = (np.arange(4000) % 8).astype(np.int32)
    newly = bf.add_each(tenants, keys)
    assert newly.shape == (4000,)
    assert newly.mean() > 0.99  # fresh keys: (almost) all new

    found = bf.contains_each(tenants, keys)
    assert found.all(), "just-added keys must be found"

    absent = rng.integers(1 << 61, 1 << 62, 4000).astype(np.int64)
    fp = bf.contains_each(tenants, absent).mean()
    assert fp < 0.02, f"false-positive rate {fp} above configured bound"

    # wrong tenant must not see another tenant's keys (beyond fp noise)
    cross = bf.contains_each((tenants + 1) % 8, keys).mean()
    assert cross < 0.05


def test_sharded_bloom_plane_is_actually_sharded(client):
    bf = client.get_sharded_bloom_filter_array("sb-layout")
    bf.try_init(4, 10_000, 0.01)
    rec = client._engine.store.get("sb-layout")
    arr = rec.arrays["bits"]
    mgr = MeshManager.of(client._engine)
    assert arr.sharding == NamedSharding(mgr.mesh, BLOOM_SPEC)
    # 8 devices -> 8 address spaces, each holding 1/8 of the columns
    assert len(arr.sharding.device_set) == 8
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(4, arr.shape[1] // 8)}


def test_sharded_bloom_clear_tenant_and_counts(client):
    bf = client.get_sharded_bloom_filter_array("sb-clear")
    bf.try_init(4, 10_000, 0.01)
    keys = np.arange(1000, dtype=np.int64)
    bf.add_each(np.full(1000, 2, np.int32), keys)
    counts = bf.tenant_bit_counts()
    assert counts.shape == (4,)
    assert counts[2] > 0 and counts[0] == 0
    bf.clear_tenant(2)
    assert bf.tenant_bit_counts()[2] == 0
    assert not bf.contains_each(np.full(1000, 2, np.int32), keys).any()


def test_sharded_hll_array_estimates(client):
    h = client.get_sharded_hll_array("sh")
    assert h.try_init(tenants=8, p=12)
    assert not h.try_init(8)
    rng = np.random.default_rng(2)
    for t, n in ((0, 100), (3, 5_000), (7, 50_000)):
        keys = rng.integers(0, 1 << 62, n).astype(np.int64)
        h.add_each(np.full(n, t, np.int32), keys)
    ests = h.estimate_all()
    assert ests.shape == (8,)
    for t, n in ((0, 100), (3, 5_000), (7, 50_000)):
        assert abs(ests[t] - n) / n < 0.1, f"tenant {t}: est {ests[t]} vs {n}"
    assert ests[1] == 0
    assert h.estimate(3) == pytest.approx(5_000, rel=0.1)
    h.clear_tenant(7)
    assert h.estimate(7) < 100


def test_sharded_hll_tenant_axis_sharded(client):
    h = client.get_sharded_hll_array("sh-layout")
    h.try_init(tenants=16, p=10)
    rec = client._engine.store.get("sh-layout")
    arr = rec.arrays["regs"]
    mgr = MeshManager.of(client._engine)
    assert arr.sharding == NamedSharding(mgr.mesh, HLL_SPEC)
    shard_shapes = {s.data.shape for s in arr.addressable_shards}
    assert shard_shapes == {(2, arr.shape[1])}  # 16 tenants / 8 shards


def test_checkpoint_roundtrip_resharded(client, tmp_path):
    """Gather-on-save, lazy re-shard on first dispatch after restore."""
    from redisson_tpu.core import checkpoint

    bf = client.get_sharded_bloom_filter_array("ck")
    bf.try_init(4, 20_000, 0.01)
    h = client.get_sharded_hll_array("ckh")
    h.try_init(8, p=12)
    keys = np.arange(5000, dtype=np.int64)
    tenants = (np.arange(5000) % 4).astype(np.int32)
    bf.add_each(tenants, keys)
    h.add_each((np.arange(5000) % 8).astype(np.int32), keys * 31 + 7)
    path = str(tmp_path / "sharded.ckp")
    assert checkpoint.save(client._engine, path) >= 2

    fresh = redisson_tpu.create()
    try:
        assert checkpoint.load(fresh._engine, path) >= 2
        rec = fresh._engine.store.get("ck")
        # restored plane is NOT yet mesh-sharded (layout-free snapshot)...
        mgr = MeshManager.of(fresh._engine)
        bf2 = fresh.get_sharded_bloom_filter_array("ck")
        assert bf2.contains_each(tenants, keys).all()
        # ...but the first dispatch re-sharded it onto the mesh
        assert rec.arrays["bits"].sharding == NamedSharding(mgr.mesh, BLOOM_SPEC)
        h2 = fresh.get_sharded_hll_array("ckh")
        ests = h2.estimate_all()
        assert all(abs(e - 625) / 625 < 0.25 for e in ests)
    finally:
        fresh.shutdown()


def test_dp_mesh_geometry():
    """dp=2 x shard=4 over the same 8 devices, through the object API."""
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.mesh.dp = 2
    c = redisson_tpu.create(cfg)
    try:
        mgr = MeshManager.of(c._engine)
        assert dict(mgr.mesh.shape) == {DP_AXIS: 2, SHARD_AXIS: 4}
        bf = c.get_sharded_bloom_filter_array("dpb")
        bf.try_init(4, 10_000, 0.01)
        keys = np.arange(999, dtype=np.int64)  # odd batch: dp padding path
        tenants = (np.arange(999) % 4).astype(np.int32)
        assert bf.add_each(tenants, keys).mean() > 0.99
        assert bf.contains_each(tenants, keys).all()
        h = c.get_sharded_hll_array("dph")
        h.try_init(4, p=12)
        h.add_each(tenants, keys)
        assert abs(h.estimate(1) - 250) < 60
        # bitset dp-convergence: set (pmax combine) then clear (pmin
        # combine) with ops split over BOTH dp groups
        bs = c.get_sharded_bit_set("dpbits")
        bs.try_init(100_000)
        idx = np.arange(0, 99_000, 13)
        assert not bs.set_each(idx).any()
        assert bs.get_each(idx).all()
        assert bs.cardinality() == len(idx)
        assert bs.set_each(idx[::2], value=False).all()
        assert not bs.get_each(idx[::2]).any()
        assert bs.get_each(idx[1::2]).all()
        assert bs.cardinality() == len(idx) - len(idx[::2])
    finally:
        c.shutdown()


def test_sharded_over_the_wire():
    """OBJCALL surface: the same handles drive a real server's engine."""
    from redisson_tpu.harness import free_port
    from redisson_tpu.server.server import ServerThread

    st = ServerThread(port=free_port()).start()
    try:
        from redisson_tpu.client.remote import RemoteRedisson

        c = RemoteRedisson(f"127.0.0.1:{st.server.port}", timeout=60.0)
        bf = c.get_sharded_bloom_filter_array("wire-sb")
        assert bf.try_init(4, 10_000, 0.01)
        keys = np.arange(2000, dtype=np.int64)
        tenants = (np.arange(2000) % 4).astype(np.int32)
        newly = bf.add_each(tenants, keys)
        assert np.asarray(newly).mean() > 0.99
        assert np.asarray(bf.contains_each(tenants, keys)).all()
        h = c.get_sharded_hll_array("wire-sh")
        assert h.try_init(4, p=12)
        h.add_each(tenants, keys)
        ests = np.asarray(h.estimate_all())
        assert ests.shape == (4,)
        assert abs(ests[0] - 500) < 120
        c.shutdown()
    finally:
        st.stop()


class TestShardedBitSet:
    def test_basic_set_get_cardinality(self, client):
        bs = client.get_sharded_bit_set("sbs")
        assert bs.try_init(1_000_000)
        assert not bs.try_init(10)
        assert bs.shards() == 8
        assert bs.plane_width() % (128 * 8) == 0
        rng = np.random.default_rng(3)
        idx = np.unique(rng.integers(0, 1_000_000, 5000))
        old = bs.set_each(idx)
        assert not old.any(), "fresh plane: all previous values are 0"
        assert bs.get_each(idx).all()
        assert bs.cardinality() == len(idx)
        # single-bit ops agree with batch ops
        assert bs.get(int(idx[0])) is True
        assert bs.set(int(idx[0]), False) is True  # returns previous
        assert bs.get(int(idx[0])) is False
        assert bs.cardinality() == len(idx) - 1

    def test_plane_is_actually_sharded(self, client):
        from redisson_tpu.client.objects.sharded import BITSET_SPEC
        from jax.sharding import NamedSharding

        bs = client.get_sharded_bit_set("sbs-layout")
        bs.try_init(100_000)
        rec = client._engine.store.get("sbs-layout")
        mgr = MeshManager.of(client._engine)
        assert rec.arrays["bits"].sharding == NamedSharding(mgr.mesh, BITSET_SPEC)

    def test_clear_value_semantics(self, client):
        """set_each(value=False) clears, and dp-replica convergence holds
        in both directions (pmax for sets, pmin for clears)."""
        bs = client.get_sharded_bit_set("sbs-clear")
        bs.try_init(10_000)
        idx = np.arange(0, 10_000, 7)
        bs.set_each(idx)
        old = bs.set_each(idx[:10], value=False)
        assert old.all()
        assert not bs.get_each(idx[:10]).any()
        assert bs.get_each(idx[10:]).all()

    def test_bitops_and_not(self, client):
        a = client.get_sharded_bit_set("sbs-a")
        b = client.get_sharded_bit_set("sbs-b")
        a.try_init(50_000)
        b.try_init(50_000)
        a.set_each(np.array([1, 2, 3]))
        b.set_each(np.array([2, 3, 4]))
        a.or_("sbs-b")
        assert a.get_each(np.array([1, 2, 3, 4])).all()
        a.and_("sbs-b")
        assert list(a.get_each(np.array([1, 2, 3, 4]))) == [False, True, True, True]
        a.xor("sbs-b")
        assert a.cardinality() == 0  # identical planes cancel
        # not_ flips logical bits only: padding must not leak into counts
        a.not_()
        assert a.cardinality() == 50_000
        with pytest.raises(ValueError):
            a.or_("sbs-missing")
        c = client.get_sharded_bit_set("sbs-c")
        c.try_init(1)  # different plane width
        with pytest.raises(ValueError):
            a.or_("sbs-c")
        # same PLANE width but larger logical size: must refuse, or the
        # operand's high bits become ghosts past our size
        d = client.get_sharded_bit_set("sbs-d")
        d.try_init(50_001)
        assert d.plane_width() == a.plane_width()
        with pytest.raises(ValueError):
            a.or_("sbs-d")

    def test_index_validation(self, client):
        bs = client.get_sharded_bit_set("sbs-val")
        bs.try_init(100)
        with pytest.raises(IndexError):
            bs.set(100)
        with pytest.raises(IndexError):
            bs.get_each(np.array([-1]))
        assert bs.set_each(np.array([], dtype=np.int64)).shape == (0,)

    def test_checkpoint_roundtrip(self, client):
        import tempfile

        from redisson_tpu.core import checkpoint

        bs = client.get_sharded_bit_set("sbs-ckpt")
        bs.try_init(10_000)
        bs.set_each(np.array([5, 500, 5000]))
        with tempfile.TemporaryDirectory() as td:
            path = os.path.join(td, "s.ckp")
            assert checkpoint.save(client._engine, path) >= 1
            fresh = redisson_tpu.create()
            try:
                assert checkpoint.load(fresh._engine, path) >= 1
                bs2 = fresh.get_sharded_bit_set("sbs-ckpt")
                assert bs2.get_each(np.array([5, 500, 5000])).all()
                assert bs2.cardinality() == 3
            finally:
                fresh.shutdown()
