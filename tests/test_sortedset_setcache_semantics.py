"""SortedSet, LexSortedSet, SetCache, priority-queue family depth
(RedissonSortedSetTest / LexSortedSetTest / SetCacheTest 37 /
PriorityQueueTest) — VERDICT r3 #7, round-4 batch 9.
"""
import time

import pytest

import redisson_tpu
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def remote_client():
    with ServerThread(port=0) as st:
        c = RemoteRedisson(st.address, timeout=60.0)
        yield c
        c.shutdown()


@pytest.fixture(scope="module")
def embedded_client():
    c = redisson_tpu.create()
    yield c
    c.shutdown()


@pytest.fixture(params=["embedded", "remote"])
def client(request, embedded_client, remote_client):
    return embedded_client if request.param == "embedded" else remote_client


def nm(tag):
    return f"ssc-{tag}-{time.time_ns()}"


class TestSortedSet:
    def test_natural_ordering(self, client):
        s = client.get_sorted_set(nm("nat"))
        for v in (3, 1, 2):
            assert s.add(v) is True
        assert s.add(2) is False  # distinct values
        assert s.read_all() == [1, 2, 3]
        assert s.first() == 1 and s.last() == 3

    def test_remove_and_contains(self, client):
        s = client.get_sorted_set(nm("rm"))
        s.add_all(["b", "a", "c"])
        assert s.contains("b") is True
        assert s.remove("b") is True
        assert s.remove("b") is False
        assert s.read_all() == ["a", "c"]

    def test_comparator_key(self, embedded_client):
        """get_sorted_set(key=...) is the Comparator analog."""
        s = embedded_client.get_sorted_set(nm("cmp"), key=lambda v: -v)
        s.add_all([1, 3, 2])
        assert s.read_all() == [3, 2, 1]  # descending comparator

    def test_empty_first_last(self, client):
        s = client.get_sorted_set(nm("empty"))
        assert s.first() is None and s.last() is None
        assert s.size() == 0


class TestLexSortedSet:
    def seeded(self, client, tag):
        z = client.get_lex_sorted_set(nm(tag))
        z.add_all(["a", "b", "c", "d", "e"])
        return z

    def test_range_inclusive_exclusive(self, client):
        z = self.seeded(client, "rng")
        assert z.range("b", True, "d", True) == ["b", "c", "d"]
        assert z.range("b", False, "d", False) == ["c"]

    def test_head_tail(self, client):
        z = self.seeded(client, "ht")
        assert z.range_head("c", True) == ["a", "b", "c"]
        assert z.range_head("c", False) == ["a", "b"]
        assert z.range_tail("c", True) == ["c", "d", "e"]
        assert z.range_tail("c", False) == ["d", "e"]

    def test_count(self, client):
        z = self.seeded(client, "cnt")
        assert z.count("a", True, "e", True) == 5
        assert z.count("b", False, "d", False) == 1

    def test_lex_order_is_bytewise(self, client):
        z = client.get_lex_sorted_set(nm("ord"))
        z.add_all(["B", "a", "A", "b"])
        assert z.read_all() == ["A", "B", "a", "b"]


class TestSetCacheDepth:
    def test_mixed_ttl_and_permanent(self, client):
        sc = client.get_set_cache(nm("mix"))
        sc.add("p1")
        sc.add("t1", ttl=0.15)
        sc.add("t2", ttl=30.0)
        assert sc.size() == 3
        time.sleep(0.3)
        assert sc.size() == 2
        assert sorted(sc.read_all()) == ["p1", "t2"]

    def test_contains_respects_ttl(self, client):
        sc = client.get_set_cache(nm("ct"))
        sc.add("gone", ttl=0.15)
        assert sc.contains("gone")
        time.sleep(0.3)
        assert not sc.contains("gone")
        # re-adding a dead value works and reports fresh
        assert sc.add("gone") is True

    def test_remove_live_and_dead(self, client):
        sc = client.get_set_cache(nm("rm"))
        sc.add("live")
        sc.add("dead", ttl=0.1)
        time.sleep(0.25)
        assert sc.remove("dead") is False  # expired: nothing to remove
        assert sc.remove("live") is True

    def test_structured_values_with_ttl(self, client):
        sc = client.get_set_cache(nm("struct"))
        sc.add(("compound", 1), ttl=30.0)
        assert sc.contains(("compound", 1))
        assert not sc.contains(("compound", 2))


class TestPriorityQueues:
    def test_priority_order_not_fifo(self, client):
        pq = client.get_priority_queue(nm("pq"))
        for v in (5, 1, 3):
            pq.offer(v)
        assert pq.poll() == 1
        assert pq.poll() == 3
        assert pq.poll() == 5
        assert pq.poll() is None

    def test_priority_peek(self, client):
        pq = client.get_priority_queue(nm("peek"))
        pq.offer(9)
        pq.offer(2)
        assert pq.peek() == 2
        assert pq.size() == 2  # peek does not consume

    def test_priority_deque_both_ends(self, client):
        pd = client.get_priority_deque(nm("pd"))
        for v in (4, 1, 7):
            pd.offer(v)
        assert pd.poll_first() == 1   # min end
        assert pd.poll_last() == 7    # max end

    def test_comparator_key(self, embedded_client):
        pq = embedded_client.get_priority_queue(nm("cmp"), key=lambda v: v["p"])
        pq.offer({"p": 3, "v": "c"})
        pq.offer({"p": 1, "v": "a"})
        assert pq.poll()["v"] == "a"

    def test_priority_blocking_take(self, embedded_client):
        import threading

        pq = embedded_client.get_priority_blocking_queue(nm("blk"))
        got = []
        th = threading.Thread(target=lambda: got.append(pq.take()), daemon=True)
        th.start()
        time.sleep(0.1)
        assert not got
        pq.offer(42)
        th.join(5.0)
        assert got == [42]
