"""Resumable chunked REPLSNAPSHOT (ISSUE 16): the WAN-hardened full-sync.

The protocol under test (server/verbs/admin.py + replication.pull_snapshot):

  * ``REPLSNAPSHOT BEGIN [CHUNK n]`` stages an immutable cut master-side
    and answers ``[xfer_id, total, crc32, chunk]``;
  * ``FETCH <id> <offset>`` streams it — re-reads are idempotent, so a
    dropped link resumes at the SAME offset instead of re-shipping;
  * ``END <id>`` releases the stage (a stale-stage reaper is the backstop);
  * the assembled bytes are CRC-gated before apply — a torn snapshot can
    never reach ``apply_records``;
  * a legacy full-blob master still works (BEGIN args ignored, bytes back).
"""
import zlib

import pytest

from redisson_tpu.net.client import NodeClient
from redisson_tpu.net.resp import RespError
from redisson_tpu.server import replication
from redisson_tpu.server.server import ServerThread

CHUNK = 512


@pytest.fixture(scope="module")
def master():
    with ServerThread() as st:
        with st.client() as c:
            for i in range(200):
                c.execute("SET", f"snapkey-{i}", "v" * 64 + str(i))
        yield st


@pytest.fixture()
def link(master):
    nc = NodeClient(f"127.0.0.1:{master.port}", ping_interval=0,
                    retry_attempts=1)
    yield nc
    nc.close()


def _multi_chunk(total):
    assert total > 3 * CHUNK, (
        f"dataset too small to exercise resume: {total} bytes"
    )


# -- the happy chunked path ----------------------------------------------------

def test_begin_fetch_end_roundtrip(master, link):
    xid, total, crc, chunk = link.execute("REPLSNAPSHOT", "BEGIN",
                                          "CHUNK", CHUNK)
    xid, total, crc, chunk = bytes(xid).decode(), int(total), int(crc), \
        int(chunk)
    _multi_chunk(total)
    assert chunk == CHUNK
    buf = bytearray()
    while len(buf) < total:
        part = link.execute("REPLSNAPSHOT", "FETCH", xid, len(buf))
        assert len(part) <= CHUNK
        buf += bytes(part)
    assert len(buf) == total and zlib.crc32(bytes(buf)) == crc
    assert bytes(link.execute("REPLSNAPSHOT", "END", xid)) == b"OK"
    # the stage is GONE: a fetch after END is the restart signal, never
    # silently re-staged data
    with pytest.raises(RespError, match="SNAPEXPIRED"):
        link.execute("REPLSNAPSHOT", "FETCH", xid, 0)
    assert len(master.server._snap_stages) == 0


def test_fetch_rereads_are_idempotent(master, link):
    """The property the whole resume leans on: the staged cut is immutable,
    so asking for the same offset twice yields the same bytes."""
    xid, total, _, _ = link.execute("REPLSNAPSHOT", "BEGIN", "CHUNK", CHUNK)
    a = bytes(link.execute("REPLSNAPSHOT", "FETCH", xid, CHUNK))
    b = bytes(link.execute("REPLSNAPSHOT", "FETCH", xid, CHUNK))
    assert a == b
    link.execute("REPLSNAPSHOT", "END", xid)


def test_fetch_offset_bounds_checked(master, link):
    xid, total, _, _ = link.execute("REPLSNAPSHOT", "BEGIN", "CHUNK", CHUNK)
    with pytest.raises(RespError):
        link.execute("REPLSNAPSHOT", "FETCH", xid, int(total) + 1)
    link.execute("REPLSNAPSHOT", "END", xid)


# -- pull_snapshot under link chaos --------------------------------------------

class _Boundary:
    """Proxy link that raises ConnectionError the FIRST time each FETCH
    offset is requested — the link dies at EVERY chunk boundary — then
    lets the retry through."""

    def __init__(self, inner):
        self.inner = inner
        self.dropped = set()
        self.begins = 0

    def execute(self, *args, **kw):
        if len(args) >= 2 and args[1] == "BEGIN":
            self.begins += 1
        if len(args) >= 4 and args[1] == "FETCH" and \
                args[3] not in self.dropped:
            self.dropped.add(args[3])
            raise ConnectionError("chaos: link died at the boundary")
        return self.inner.execute(*args, **kw)


def test_pull_resumes_through_drop_at_every_boundary(master, link):
    """The acceptance storm: the link drops at EVERY chunk boundary and
    the pull still converges BIT-IDENTICAL to an unmolested pull — each
    resume re-asks for the same offset, nothing is re-shipped, nothing is
    skipped."""
    clean = replication.pull_snapshot(link, timeout=30.0, chunk_bytes=CHUNK)
    _multi_chunk(len(clean))
    flaky = _Boundary(link)
    blob = replication.pull_snapshot(
        flaky, timeout=30.0, chunk_bytes=CHUNK,
        max_link_errors=len(clean) // CHUNK + 8,
    )
    assert blob == clean
    assert flaky.begins == 1                    # resumed, never restarted
    assert len(flaky.dropped) == len(clean) // CHUNK + 1  # every boundary
    assert len(master.server._snap_stages) == 0  # ENDed eagerly


def test_pull_gives_up_after_link_error_budget(master, link):
    class Dead:
        def __init__(self, inner):
            self.inner = inner

        def execute(self, *args, **kw):
            if len(args) >= 2 and args[1] == "FETCH":
                raise ConnectionError("chaos: hard down")
            return self.inner.execute(*args, **kw)

    with pytest.raises(ConnectionError):
        replication.pull_snapshot(Dead(link), timeout=30.0,
                                  chunk_bytes=CHUNK, max_link_errors=3)


class _Expirer:
    """Proxy that ENDs the transfer behind the puller's back after the
    first chunk — the master-restarted/stage-reaped shape.  The puller
    must restart from a fresh BEGIN, not resume into a different cut."""

    def __init__(self, inner):
        self.inner = inner
        self.begins = 0
        self.sabotaged = False

    def execute(self, *args, **kw):
        if len(args) >= 2 and args[1] == "BEGIN":
            self.begins += 1
            self.last_xid = None
        out = self.inner.execute(*args, **kw)
        if len(args) >= 2 and args[1] == "BEGIN":
            self.last_xid = bytes(out[0]).decode()
        elif len(args) >= 2 and args[1] == "FETCH" and not self.sabotaged:
            self.sabotaged = True
            self.inner.execute("REPLSNAPSHOT", "END", self.last_xid)
        return out


def test_pull_restarts_on_snapexpired(master, link):
    wrapper = _Expirer(link)
    blob = replication.pull_snapshot(wrapper, timeout=30.0,
                                     chunk_bytes=CHUNK)
    assert wrapper.begins == 2                  # expired once, restarted once
    assert zlib.crc32(blob) == zlib.crc32(
        replication.pull_snapshot(link, timeout=30.0)
    )


def test_pull_restart_budget_bounded(master, link):
    class AlwaysExpired:
        def __init__(self, inner):
            self.inner = inner

        def execute(self, *args, **kw):
            if len(args) >= 2 and args[1] == "FETCH":
                raise RespError("SNAPEXPIRED unknown snapshot transfer x")
            return self.inner.execute(*args, **kw)

    with pytest.raises(RespError, match="SNAPEXPIRED"):
        replication.pull_snapshot(AlwaysExpired(link), timeout=30.0,
                                  chunk_bytes=CHUNK, max_restarts=2)


def test_torn_snapshot_is_never_returned(master, link):
    """CRC gate: a corrupted chunk (right length, wrong bytes — the
    torn/mixed-stage shape a length check cannot catch) must raise, so
    the replica NEVER applies a torn snapshot."""
    class Corruptor:
        def __init__(self, inner):
            self.inner = inner
            self.hit = False

        def execute(self, *args, **kw):
            out = self.inner.execute(*args, **kw)
            if len(args) >= 4 and args[1] == "FETCH" and not self.hit:
                self.hit = True
                return b"\x00" * len(out)
            return out

    with pytest.raises(ValueError, match="REPLSNAPSHOT torn"):
        replication.pull_snapshot(Corruptor(link), timeout=30.0,
                                  chunk_bytes=CHUNK, max_restarts=0)


def test_legacy_full_blob_master_fallback():
    """A master that predates the subcommands answers BEGIN with the whole
    blob: pull_snapshot returns it as-is — one ship, no FETCH, exactly the
    old behavior."""
    class Legacy:
        calls = []

        def execute(self, *args, **kw):
            self.calls.append(args)
            return b"legacy-blob-bytes"

    out = replication.pull_snapshot(Legacy(), timeout=5.0, chunk_bytes=CHUNK)
    assert out == b"legacy-blob-bytes"
    assert all(a[1] == "BEGIN" for a in Legacy.calls)


# -- stage lifecycle (master side) ---------------------------------------------

def test_stage_backstop_evicts_oldest(master, link):
    """An abandoned-puller storm cannot pin unbounded snapshot copies:
    the stage table is capped at SNAP_STAGE_MAX, least-recently-touched
    evicted first (SNAPEXPIRED tells that puller to restart)."""
    xids = []
    for _ in range(replication.SNAP_STAGE_MAX + 2):
        h = link.execute("REPLSNAPSHOT", "BEGIN", "CHUNK", CHUNK)
        xids.append(bytes(h[0]).decode())
    assert len(master.server._snap_stages) <= replication.SNAP_STAGE_MAX
    with pytest.raises(RespError, match="SNAPEXPIRED"):
        link.execute("REPLSNAPSHOT", "FETCH", xids[0], 0)
    # the newest stage survived the storm
    assert link.execute("REPLSNAPSHOT", "FETCH", xids[-1], 0)
    for x in xids:
        try:
            link.execute("REPLSNAPSHOT", "END", x)
        except RespError:
            pass
    assert len(master.server._snap_stages) == 0


# -- the real full-sync path ---------------------------------------------------

def test_replicaof_full_sync_rides_chunked_pull(monkeypatch):
    """REPLICAOF end to end with the chunk size squeezed far below the
    snapshot size: the replica's full sync runs BEGIN/FETCH/END, converges
    to the master's records, and drains the master's stage table."""
    monkeypatch.setattr(replication, "SNAPSHOT_CHUNK_BYTES", CHUNK)
    with ServerThread() as m, ServerThread() as r:
        with m.client() as c:
            for i in range(200):
                c.execute("SET", f"fs-{i}", "val" * 24 + str(i))
        with r.client() as c:
            reply = c.execute("REPLICAOF", "127.0.0.1", m.port,
                              timeout=60.0)
            assert bytes(reply) == b"OK"
        with r.client() as c:
            for i in (0, 57, 199):
                got = c.execute("GET", f"fs-{i}")
                assert bytes(got) == ("val" * 24 + str(i)).encode()
        assert len(m.server._snap_stages) == 0
