"""Tiered HBM residency (ISSUE 20): HOT/WARM/COLD demotion, fault-in on
first touch, the CLUSTER RESIDENCY verb, and the fleet pressure rebalancer.

Contracts pinned here:
  * a WARM->HOT promotion costs exactly ONE packed H2D (scatter_host_arrays
    once, no per-array fallback) and ZERO kernel rebuilds — the warm pool
    re-hits across demote/promote and across a bank reshard (grow);
  * replies are bit-identical armed-with-demotions vs disarmed
    (RTPU_NO_TIER=1), under the native wire plane and RTPU_NO_NATIVE=1;
  * fenced (migrating/importing/recovering) slots never demote, even
    force=True — handoff serializers own those records;
  * a tier change is invisible to the tracking plane (no version bump, no
    invalidation push); a real write after demotion still invalidates;
  * unsharded bank growth over device-budget-bytes demotes colder records
    FIRST and raises VectorBudgetError only when not enough was demotable
    (the refuse-vs-demote boundary);
  * census rows drain to absence on DEL (spill file GC'd, dev rows gone);
  * COLD spill files are CRC-verified (torn/forged files refuse to load);
  * the ResidencyRebalancer control loop sweeps first, sheds persistent
    pressure, and degrades per-node when a member is unreachable.
"""
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import redisson_tpu
from redisson_tpu.core import residency as _res
from redisson_tpu.core.engine import Engine
from redisson_tpu.net.client import Connection
from redisson_tpu.net.resp import RespError
from redisson_tpu.server.server import ServerThread


@pytest.fixture()
def armed_budget():
    """Arm the plane, hand the test set_device_budget_bytes, restore both."""
    prev_tier = _res.set_tier(True)
    prev_budget = _res.set_device_budget_bytes(0)
    try:
        yield _res.set_device_budget_bytes
    finally:
        _res.set_device_budget_bytes(prev_budget)
        _res.set_tier(prev_tier)


def _conn(st, handler=None):
    c = Connection(st.server.host, st.server.port, timeout=30.0)
    if handler is not None:
        c.push_handler = handler
    return c


# -- spill container: CRC-verified round trip ---------------------------------


def test_spill_round_trip_and_crc_corruption(tmp_path):
    from redisson_tpu.core.checkpoint import CheckpointCorruptError

    arrays = {
        "bits": np.arange(777, dtype=np.uint64),
        "flags": np.array([True, False, True]),
    }
    path = str(tmp_path / "r.spill")
    n = _res.write_spill(path, arrays)
    assert n == os.path.getsize(path)
    back = _res.load_spill(path)
    assert set(back) == {"bits", "flags"}
    np.testing.assert_array_equal(back["bits"], arrays["bits"])
    np.testing.assert_array_equal(back["flags"], arrays["flags"])

    # flip one payload byte: the CRC trailer must refuse the file
    blob = bytearray(open(path, "rb").read())
    blob[len(blob) // 2] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CheckpointCorruptError):
        _res.load_spill(path)

    # truncation (torn write) refuses too
    open(path, "wb").write(bytes(blob[: len(blob) // 3]))
    with pytest.raises(CheckpointCorruptError):
        _res.load_spill(path)


# -- embedded demote / fault-in correctness -----------------------------------


def test_demote_promote_read_through_warm_and_cold(armed_budget):
    client = redisson_tpu.create()
    eng = client._engine
    mgr = eng.enable_residency(min_idle_s=0.0)
    try:
        bf = client.get_bloom_filter("res:f")
        assert bf.try_init(20_000, 0.01)
        keys = [f"k{i}" for i in range(300)]
        bf.add_all(keys)
        baseline = np.asarray(bf.contains_each(keys))
        assert baseline.all()
        assert mgr.tier_of("res:f") == _res.HOT

        assert mgr.demote("res:f", force=True)
        assert mgr.tier_of("res:f") == _res.WARM
        rec = eng.store.get_unguarded("res:f")
        assert not rec.arrays and rec.stash is not None

        # WARM read-through: first touch faults in, replies identical
        np.testing.assert_array_equal(
            np.asarray(bf.contains_each(keys)), baseline
        )
        assert mgr.tier_of("res:f") == _res.HOT
        assert mgr.promotions == 1

        # COLD: HOT -> WARM -> spill file -> read-through again
        assert mgr.demote("res:f", cold=True, force=True)
        assert mgr.tier_of("res:f") == _res.COLD
        assert rec.cold_path is not None and os.path.exists(rec.cold_path)
        assert rec.stash is None
        np.testing.assert_array_equal(
            np.asarray(bf.contains_each(keys)), baseline
        )
        assert mgr.tier_of("res:f") == _res.HOT
        assert mgr.cold_loads == 1 and mgr.promotions == 2
        assert rec.cold_path is None
    finally:
        client.shutdown()


def test_promotion_costs_exactly_one_h2d(armed_budget, monkeypatch):
    import redisson_tpu.core.ioplane as iop

    client = redisson_tpu.create()
    eng = client._engine
    mgr = eng.enable_residency(min_idle_s=0.0)
    try:
        bf = client.get_bloom_filter("res:h2d")
        assert bf.try_init(50_000, 0.01)
        bf.add_all([f"m{i}" for i in range(200)])

        scatters = []
        orig = iop.scatter_host_arrays
        monkeypatch.setattr(
            iop, "scatter_host_arrays",
            lambda arrays, device, pool=None: (
                scatters.append(len(arrays)),
                orig(arrays, device, pool=pool),
            )[1],
        )
        puts = []
        import jax

        orig_put = jax.device_put
        monkeypatch.setattr(
            jax, "device_put",
            lambda *a, **kw: (puts.append(1), orig_put(*a, **kw))[1],
        )
        # a HOT probe's own device_put budget (query-key upload etc.) — the
        # promotion contract is +1 on top of this, the merged-stash upload
        assert bf.contains("m5") and bf.contains("m6")  # warm lazy paths
        puts.clear()
        assert bf.contains("m7")
        base = len(puts)

        puts.clear()
        assert mgr.demote("res:h2d", force=True)
        assert not scatters and not puts  # demotion is D2H only
        assert bf.contains("m8")          # first touch: the fault-in
        assert len(scatters) == 1, (
            f"promotion took {len(scatters)} packed uploads, contract is 1"
        )
        assert len(puts) == base + 1, (
            f"promotion cost {len(puts) - base} H2D transfers beyond the "
            f"probe's own {base}, contract is 1 (per-array fallback?)"
        )
        assert mgr.tier_of("res:h2d") == _res.HOT
        # steady HOT traffic pays zero further uploads
        puts.clear()
        assert bf.contains("m9")
        assert len(scatters) == 1 and len(puts) == base
    finally:
        client.shutdown()


def test_zero_kernel_rebuilds_across_demote_promote_and_reshard(armed_budget):
    from redisson_tpu.core import warmpool
    from redisson_tpu.services.search import SearchService
    from redisson_tpu.services.vector import DEFAULT_BLOCK, bank_record_name

    client = redisson_tpu.create()
    eng = client._engine
    mgr = eng.enable_residency(min_idle_s=0.0)
    try:
        bf = client.get_bloom_filter("res:wp")
        assert bf.try_init(30_000, 0.01)
        keys = [f"w{i}" for i in range(128)]
        bf.add_all(keys)
        baseline = np.asarray(bf.contains_each(keys))
        warms0 = warmpool.POOL.warms
        for cold in (False, True):
            assert mgr.demote("res:wp", cold=cold, force=True)
            np.testing.assert_array_equal(
                np.asarray(bf.contains_each(keys)), baseline
            )
        assert warmpool.POOL.warms == warms0, (
            "demote/promote rebuilt kernels — same geometry must re-hit"
        )

        # reshard (bank grow = new geometry) warms once; a tier cycle on the
        # GROWN bank must then re-hit with zero further rebuilds
        svc = SearchService(eng)
        svc.create_index("wi", {"emb": "VECTOR"}, vector={"emb": {"dim": 16}})
        rng = np.random.default_rng(7)
        for i in range(DEFAULT_BLOCK + 9):  # crosses one grow boundary
            svc.add_document("wi", f"d{i}", {
                "emb": rng.standard_normal(16).astype(np.float32)
            })
        q = rng.standard_normal(16).astype(np.float32)

        def _knn():
            dev, finish = svc.knn("wi", "emb", q, 5)
            if dev is None:
                return finish(None)[0]
            return finish(tuple(np.asarray(v) for v in dev))[0]

        res0 = _knn()
        warms1 = warmpool.POOL.warms
        bank = bank_record_name("wi", "emb")
        assert mgr.demote(bank, force=True)
        assert _knn() == res0
        assert warmpool.POOL.warms == warms1, (
            "tier cycle after a reshard rebuilt kernels"
        )
    finally:
        client.shutdown()


# -- refuse-vs-demote boundary (the VectorBudgetError bugfix) ------------------


def test_unsharded_growth_demotes_colder_records_before_refusing(armed_budget):
    from redisson_tpu.services.search import SearchService
    from redisson_tpu.services.vector import (
        DEFAULT_BLOCK, VectorBudgetError, bank_record_name,
    )

    eng = Engine()
    mgr = eng.enable_residency(min_idle_s=0.0)
    svc = SearchService(eng)
    rng = np.random.default_rng(3)
    dim = 64

    import itertools

    seq = itertools.count()

    def _fill(index, n):
        for _ in range(n):
            svc.add_document(index, f"{index}:d{next(seq)}", {
                "emb": rng.standard_normal(dim).astype(np.float32)
            })

    q = np.ones(dim, np.float32)

    def _knn(index):
        dev, finish = svc.knn(index, "emb", q, 5)
        return (finish(None) if dev is None else finish(
            tuple(np.asarray(v) for v in dev)
        ))[0]

    svc.create_index("ia", {"emb": "VECTOR"}, vector={"emb": {"dim": dim}})
    _fill("ia", DEFAULT_BLOCK)
    res_a = _knn("ia")  # flushes pending: bank A is clean
    bank_a = bank_record_name("ia", "emb")
    hot_a = sum(mgr.hot_bytes_by_device().values())
    assert hot_a > 0
    # budget fits bank A plus slack — NOT a second bank
    armed_budget(hot_a + 4096)

    # growth of a second bank demotes idle bank A instead of refusing
    svc.create_index("ib", {"emb": "VECTOR"}, vector={"emb": {"dim": dim}})
    _fill("ib", DEFAULT_BLOCK)  # no VectorBudgetError raised
    assert mgr.tier_of(bank_a) == _res.WARM, (
        "growth admission did not demote the colder bank first"
    )
    assert mgr.demotions_warm >= 1

    # further growth finds NOTHING left demotable (A already warm, B is the
    # grower itself) — refuse is the last resort, not the first
    with pytest.raises(VectorBudgetError):
        _fill("ib", DEFAULT_BLOCK + 1)
    assert mgr.tier_of(bank_a) == _res.WARM

    # lifting the budget lets A fault back in bit-identically
    armed_budget(0)
    assert _knn("ia") == res_a
    assert mgr.tier_of(bank_a) == _res.HOT


# -- census drain-to-absence ---------------------------------------------------


def test_census_rows_drain_to_absence_on_delete(armed_budget):
    client = redisson_tpu.create()
    eng = client._engine
    mgr = eng.enable_residency(min_idle_s=0.0)
    try:
        bf = client.get_bloom_filter("res:gone")
        assert bf.try_init(20_000, 0.01)
        bf.add_all([f"g{i}" for i in range(64)])
        assert any(
            k.startswith("residency_bytes_dev") and k.endswith("_hot")
            for k in mgr.census()
        )
        assert mgr.demote("res:gone", cold=True, force=True)
        spill = eng.store.get_unguarded("res:gone").cold_path
        assert spill and os.path.exists(spill)
        assert any(k.endswith("_cold") for k in mgr.census())

        assert eng.store.delete("res:gone")
        mgr.sweep()  # GC pass
        rows = mgr.census()
        assert not any(k.startswith("residency_bytes_dev") for k in rows), rows
        assert not os.path.exists(spill), "orphaned spill survived the GC"
    finally:
        client.shutdown()


# -- fences: migrating slots never demote -------------------------------------


def test_fenced_slot_never_demotes_even_forced():
    from redisson_tpu.utils.crc16 import calc_slot

    with ServerThread(port=0, workers=2) as st:
        srv = st.server
        c = _conn(st)
        try:
            prev_tier = _res.tier_enabled()
            prev_budget = _res.DEVICE_BUDGET_BYTES
            srv.enable_residency(min_idle_s=0.0)
            mgr = srv.engine.residency
            assert c.execute("BF.RESERVE", "res:fence", "0.01", "10000") == b"OK"
            c.execute("BF.MADD", "res:fence", "a", "b", "c")
            slot = calc_slot(b"res:fence")

            for table in (srv.migrating_slots, srv.importing_slots,
                          srv.recovering_slots):
                table[slot] = "peer"
                try:
                    assert not mgr.demote("res:fence", force=True)
                    assert c.execute(
                        "CLUSTER", "RESIDENCY", "DEMOTE", "res:fence"
                    ) == 0
                    assert mgr.tier_of("res:fence") == _res.HOT
                finally:
                    del table[slot]

            # fence lifted: the same demotion goes through
            assert c.execute(
                "CLUSTER", "RESIDENCY", "DEMOTE", "res:fence"
            ) == 1
            assert mgr.tier_of("res:fence") == _res.WARM
        finally:
            c.close()
            _res.set_device_budget_bytes(prev_budget)
            _res.set_tier(prev_tier)


# -- tracking: a tier change is not a write -----------------------------------


def test_demotion_sends_no_invalidation_but_writes_still_do():
    with ServerThread(port=0, workers=2) as st:
        srv = st.server
        pushes = []
        a = _conn(st, handler=pushes.append)
        w = _conn(st)
        try:
            prev_tier = _res.tier_enabled()
            prev_budget = _res.DEVICE_BUDGET_BYTES
            srv.enable_residency(min_idle_s=0.0)
            mgr = srv.engine.residency
            assert w.execute("BF.RESERVE", "res:trk", "0.01", "10000") == b"OK"
            w.execute("BF.MADD", "res:trk", "x", "y")
            a.execute("CLIENT", "TRACKING", "ON")
            assert a.execute("BF.EXISTS", "res:trk", "x") == 1  # registers
            rec = srv.engine.store.get_unguarded("res:trk")
            v0 = rec.version

            assert mgr.demote("res:trk", cold=True, force=True)
            a.execute("PING")  # drain any (wrong) push
            assert rec.version == v0, "tier change bumped the version"
            assert not pushes, f"demotion invalidated tracked caches: {pushes}"

            # a REAL write still invalidates the registration
            w.execute("BF.ADD", "res:trk", "z")
            deadline = time.time() + 5
            while time.time() < deadline and not pushes:
                a.execute("PING")
                time.sleep(0.01)
            assert any(
                p and p[0] == b"invalidate" and b"res:trk" in p[1]
                for p in pushes
            ), pushes
        finally:
            a.close()
            w.close()
            _res.set_device_budget_bytes(prev_budget)
            _res.set_tier(prev_tier)


# -- the CLUSTER RESIDENCY verb ------------------------------------------------


def test_cluster_residency_verb_table_tier_demote_sweep():
    with ServerThread(port=0, workers=2) as st:
        c = _conn(st)
        try:
            prev_tier = _res.tier_enabled()
            prev_budget = _res.DEVICE_BUDGET_BYTES
            # disarmed: short table, TIER is hot by construction, mutators err
            t = c.execute("CLUSTER", "RESIDENCY")
            assert t[0] == 0
            assert c.execute("CLUSTER", "RESIDENCY", "TIER", "nope") == b"hot"
            err = c.execute("CLUSTER", "RESIDENCY", "SWEEP")
            assert isinstance(err, RespError) and "residency plane" in str(err)

            assert c.execute(
                "CONFIG", "SET", "device-budget-bytes", "1000000"
            ) == b"OK"
            assert c.execute(
                "CONFIG", "SET", "residency-enabled", "yes"
            ) == b"OK"
            view = c.execute("CONFIG", "GET", "residency-enabled")
            assert view == [b"residency-enabled", b"1"]

            assert c.execute("BF.RESERVE", "res:v", "0.01", "10000") == b"OK"
            c.execute("BF.MADD", "res:v", *[f"v{i}" for i in range(50)])
            table = c.execute("CLUSTER", "RESIDENCY")
            assert table[0] == 1 and table[1] == 1000000
            devrows = [r for r in table[2:] if r and r[0] == b"DEV"]
            ctr = [r for r in table[2:] if r and r[0] == b"CTR"]
            assert devrows and devrows[0][2] > 0  # hot bytes
            assert len(ctr) == 1 and len(ctr[0]) == 7

            assert c.execute(
                "CLUSTER", "RESIDENCY", "DEMOTE", "res:v"
            ) == 1
            assert c.execute(
                "CLUSTER", "RESIDENCY", "TIER", "res:v"
            ) == b"warm"
            assert c.execute(
                "CLUSTER", "RESIDENCY", "DEMOTE", "res:v", "COLD"
            ) == 1
            assert c.execute(
                "CLUSTER", "RESIDENCY", "TIER", "res:v"
            ) == b"cold"
            # data read faults it back in transparently
            assert c.execute("BF.EXISTS", "res:v", "v7") == 1
            assert c.execute(
                "CLUSTER", "RESIDENCY", "TIER", "res:v"
            ) == b"hot"
            swept = c.execute("CLUSTER", "RESIDENCY", "SWEEP")
            assert isinstance(swept, list) and len(swept) == 3

            err = c.execute("CLUSTER", "RESIDENCY", "TIER", "missing")
            assert isinstance(err, RespError) and "no such key" in str(err)
            err = c.execute("CLUSTER", "RESIDENCY", "BOGUS")
            assert isinstance(err, RespError)
            assert "unknown CLUSTER RESIDENCY" in str(err)

            # disarm over the wire: table drops back, data still served
            assert c.execute(
                "CONFIG", "SET", "residency-enabled", "no"
            ) == b"OK"
            assert c.execute("CLUSTER", "RESIDENCY")[0] == 0
            assert c.execute("BF.EXISTS", "res:v", "v7") == 1
        finally:
            c.close()
            _res.set_device_budget_bytes(prev_budget)
            _res.set_tier(prev_tier)


# -- disarmed A/B wire bit-identity --------------------------------------------

_AB_DRIVER = r"""
import hashlib, os, socket
from redisson_tpu.net import resp
from redisson_tpu.server.server import ServerThread

ARMED = os.environ.get("AB_ARMED") == "1"
with ServerThread(port=0, workers=2) as st:
    srv = st.server
    if ARMED:
        srv.enable_residency(min_idle_s=0.0)
    s = socket.create_connection((srv.host, srv.port), timeout=30)
    parser = resp.RespParser(use_native=False)
    h = hashlib.sha256()

    def run(cmds):
        s.sendall(b"".join(resp.encode_command_python(*c) for c in cmds))
        got = 0
        while got < len(cmds):
            data = s.recv(1 << 16)
            assert data, "server closed early"
            h.update(data)
            got += len(parser.feed(data))

    def cycle():
        # armed leg: force a WARM then COLD round between reply waves; the
        # disarmed leg does nothing — the digests must match anyway
        if ARMED:
            mgr = srv.engine.residency
            assert mgr.demote("ab:f", force=True)
            assert mgr.demote("ab:f", cold=True, force=True)

    run([("BF.RESERVE", "ab:f", "0.01", "20000")]
        + [("BF.MADD", "ab:f", *[f"k{i}" for i in range(j, j + 50)])
           for j in range(0, 500, 50)]
        + [("SET", "ab:b", "v1"), ("GET", "ab:b")])
    cycle()
    run([("BF.MEXISTS", "ab:f", *[f"k{i}" for i in range(0, 500, 7)])])
    cycle()
    run([("BF.EXISTS", "ab:f", "k3"), ("BF.EXISTS", "ab:f", "nope"),
         ("BF.INFO", "ab:f"), ("GET", "ab:b"),
         ("BF.MEXISTS", "ab:f", *[f"k{i}" for i in range(100, 200, 3)])])
    s.close()
print(h.hexdigest())
"""


def test_wire_replies_bit_identical_armed_vs_disarmed_both_wire_planes():
    """ISSUE 20 acceptance: reply streams are byte-identical with the plane
    disarmed (RTPU_NO_TIER=1) vs armed with forced WARM/COLD cycles between
    waves — under the native wire plane AND RTPU_NO_NATIVE=1."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    digests = {}
    for wire, wire_env in (("native", {}), ("pyfallback", {"RTPU_NO_NATIVE": "1"})):
        for mode, mode_env in (
            ("armed", {"AB_ARMED": "1"}),
            ("disarmed", {"AB_ARMED": "0", "RTPU_NO_TIER": "1"}),
        ):
            env = dict(os.environ, JAX_PLATFORMS="cpu", **wire_env, **mode_env)
            out = subprocess.run(
                [sys.executable, "-c", _AB_DRIVER],
                capture_output=True, text=True, timeout=240, cwd=repo, env=env,
            )
            assert out.returncode == 0, (wire, mode, out.stdout, out.stderr)
            digests[(wire, mode)] = out.stdout.strip().splitlines()[-1]
    assert len(set(digests.values())) == 1, digests
    assert len(next(iter(digests.values()))) == 64


def test_plane_disarmed_by_default_and_env_killswitch_beats_arm():
    """The getter guard starts disarmed (armed-with-no-manager measurably
    taxed the interactive QoS p99 for nothing) and RTPU_NO_TIER=1 must
    refuse set_tier(True) — the operator's bit-identity guarantee beats any
    in-process arm, including CONFIG SET residency-enabled yes."""
    script = (
        "import os\n"
        "from redisson_tpu.core import residency as _res\n"
        "assert _res.tier_enabled() is False, 'must start disarmed'\n"
        "prev = _res.set_tier(True)\n"
        "assert prev is False\n"
        "want = os.environ.get('RTPU_NO_TIER') != '1'\n"
        "assert _res.tier_enabled() is want, (_res.tier_enabled(), want)\n"
        "if not want:\n"
        "    _res.set_tier(False)\n"
        "    from redisson_tpu.server.server import ServerThread\n"
        "    with ServerThread(port=0, workers=2) as st:\n"
        "        st.server.enable_residency(min_idle_s=0.0)\n"
        "        assert st.server.engine.residency is None, 'enable must refuse'\n"
        "        assert _res.tier_enabled() is False\n"
        "print('ok')\n"
    )
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for extra in ({}, {"RTPU_NO_TIER": "1"}):
        env = dict(os.environ, JAX_PLATFORMS="cpu", **extra)
        if not extra:
            env.pop("RTPU_NO_TIER", None)
        out = subprocess.run(
            [sys.executable, "-c", script],
            capture_output=True, text=True, timeout=120, cwd=repo, env=env,
        )
        assert out.returncode == 0, (extra, out.stdout, out.stderr)
        assert out.stdout.strip().endswith("ok")


# -- the fleet pressure rebalancer --------------------------------------------


def _table(armed, budget, devs):
    rows = [1 if armed else 0, budget]
    for d, (hot, warm, cold) in devs.items():
        rows.append([b"DEV", d, hot, warm, cold])
    rows.append([b"CTR", 0, 0, 0, 0, b"0.0", b"0.0"])
    return rows


class _FakeNode:
    """Conn factory double: serves a mutable CLUSTER RESIDENCY table and
    records every issued command."""

    def __init__(self, table):
        self.table = table
        self.cmds = []
        self.fail_issues = False
        self.dead = False

    def factory(self):
        node = self

        class _C:
            def __enter__(self):
                return self

            def __exit__(self, *exc):
                return False

            def execute(self, *args):
                if args == ("CLUSTER", "RESIDENCY"):
                    return node.table
                node.cmds.append(args)
                if node.fail_issues:
                    raise RespError("ERR TRYAGAIN rebalance in flight")
                return b"OK"

        def open_conn():
            if node.dead:
                raise ConnectionRefusedError("down")
            return _C()

        return open_conn


def test_parse_residency_table():
    from redisson_tpu.cluster.residency_control import parse_residency_table

    armed, budget, devs = parse_residency_table(
        _table(True, 1 << 20, {0: (900, 10, 5), 3: (1, 2, 3)})
    )
    assert armed and budget == 1 << 20
    assert devs == {0: (900, 10, 5), 3: (1, 2, 3)}
    # CTR row skipped, malformed replies degrade to empty
    assert parse_residency_table(None) == (False, 0, {})
    assert parse_residency_table([0]) == (False, 0, {})
    assert parse_residency_table([0, 5]) == (False, 5, {})


def test_rebalancer_sweeps_first_then_sheds_persistent_pressure(tmp_path):
    from redisson_tpu.cluster.residency_control import ResidencyRebalancer

    node = _FakeNode(_table(True, 1000, {0: (950, 0, 0), 1: (100, 0, 0)}))
    rb = ResidencyRebalancer(
        {"n1": node.factory()}, high_water=0.9, shed_after=2, shed_count=4,
        journal_dir=str(tmp_path),
    )
    # sweep 1: pressured dev0 gets a demote-first SWEEP, healthy dev1 nothing
    assert rb.step() == [("n1", "sweep", 0)]
    assert node.cmds[-1] == ("CLUSTER", "RESIDENCY", "SWEEP")
    # sweep 2: still pressured -> SHED with the bounded bite + journal dir
    assert rb.step() == [("n1", "shed", 0)]
    assert node.cmds[-1] == ("CLUSTER", "RESIDENCY", "SHED", "0",
                             "COUNT", "4", "DIR", str(tmp_path))
    assert rb.sweeps_issued == 1 and rb.sheds_issued == 1
    # shed resets the streak: next tick demotes-first again
    assert rb.step() == [("n1", "sweep", 0)]
    # pressure relieved: streak clears, nothing issued
    node.table = _table(True, 1000, {0: (100, 850, 0), 1: (100, 0, 0)})
    assert rb.step() == []
    node.table = _table(True, 1000, {0: (950, 0, 0)})
    assert rb.step() == [("n1", "sweep", 0)]  # streak restarted at 1


def test_rebalancer_degrades_on_dead_nodes_unarmed_nodes_and_push_errors():
    from redisson_tpu.cluster.residency_control import ResidencyRebalancer

    node = _FakeNode(_table(True, 1000, {0: (950, 0, 0)}))
    rb = ResidencyRebalancer({"n1": node.factory()}, shed_after=2)
    assert rb.step() == [("n1", "sweep", 0)]
    # a concurrent rebalance makes the SHED raise: push_errors, loop survives
    node.fail_issues = True
    assert rb.step() == []
    assert rb.push_errors == 1
    node.fail_issues = False
    # node death: contributes nothing, receives nothing, no exception
    node.dead = True
    assert rb.step() == []
    node.dead = False
    # disarmed node clears its pressure bookkeeping entirely
    node.table = _table(False, 1000, {0: (950, 0, 0)})
    assert rb.step() == []
    assert not rb._pressure
    # override budget: operator ceiling beats the node's scraped budget
    node.table = _table(True, 10**9, {0: (950, 0, 0)})
    rb2 = ResidencyRebalancer({"n1": node.factory()}, budget_bytes=1000)
    assert rb2.step() == [("n1", "sweep", 0)]
