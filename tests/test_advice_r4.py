"""Regressions for the round-3 advisor findings (ADVICE.md r3).

1. medium — eviction sweeps must be registered under the NameMapper-mapped
   key, or background reaping never runs for mapped caches.
2. low — _znumkeys verbs (LMPOP/ZMPOP/ZDIFF/ZINTER/ZUNION/...) validate
   numkeys like their blocking siblings instead of ERR internal.
3. low — MapCache max_size 0 = unbounded (trySetMaxSizeAsync only rejects
   negatives), with key-presence keeping the set-once contract.
4. low — wire RESTORE ttl 0 = no expiry (Redis semantics), carried-TTL
   behavior stays behind RObject.migrate.
5. low — WAIT timeout 0 has no deadline (blocks until replica count).
"""
import threading
import time

import pytest

import redisson_tpu
from redisson_tpu.client.remote import RemoteRedisson
from redisson_tpu.net.resp import RespError
from redisson_tpu.server.server import ServerThread


@pytest.fixture(scope="module")
def wire():
    with ServerThread(port=0) as st:
        client = RemoteRedisson(st.address, timeout=60.0)
        yield client
        client.shutdown()


def test_eviction_sweep_registered_under_mapped_name():
    """With a name_mapper, the sweep must watch the MAPPED record name —
    otherwise schedule_for_record sees exists()==False forever and the
    cache is only reaped lazily on access."""
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.name_mapper = type(
        "PrefixMapper", (), {
            "map": staticmethod(lambda n: f"tenant7:{n}"),
            "unmap": staticmethod(lambda n: n[len("tenant7:"):]),
        },
    )()
    c = redisson_tpu.create(cfg)
    try:
        for factory, nm in (
            (c.get_map_cache, "amc"),
            (c.get_set_cache, "asc"),
            (c.get_list_multimap_cache, "almc"),
            (c.get_set_multimap_cache, "asmc"),
        ):
            h = factory(nm)
            assert h._name.startswith("tenant7:")
            assert h._name in c._engine.eviction._tasks, factory.__name__
            assert nm not in c._engine.eviction._tasks, factory.__name__
    finally:
        c.shutdown()


def test_eviction_sweep_actually_reaps_mapped_cache():
    """End-to-end: a mapped MapCache's expired entry disappears via the
    background sweep, without any client access to trigger lazy reaping."""
    from redisson_tpu.config import Config

    cfg = Config()
    cfg.name_mapper = type(
        "PrefixMapper", (), {
            "map": staticmethod(lambda n: f"t:{n}"),
            "unmap": staticmethod(lambda n: n[2:]),
        },
    )()
    c = redisson_tpu.create(cfg)
    try:
        c._engine.eviction.start_delay = 0.05
        c._engine.eviction.min_delay = 0.05  # keep the adaptive reschedule fast
        mc = c.get_map_cache("reapme")
        mc.put_with_ttl("k", "v", ttl=0.05)
        rec = c._engine.store.get(mc._name)
        assert rec is not None and len(rec.host) == 1
        deadline = time.time() + 5.0
        while time.time() < deadline:
            rec = c._engine.store.get(mc._name)
            if rec is None or len(rec.host) == 0:
                break
            time.sleep(0.05)
        rec = c._engine.store.get(mc._name)
        assert rec is None or len(rec.host) == 0
    finally:
        c.shutdown()


@pytest.mark.parametrize("cmdline", [
    ("LMPOP", "0", "LEFT"),
    ("ZMPOP", "0", "MIN"),
    ("ZDIFF", "0"),
    ("ZINTER", "0"),
    ("ZUNION", "0"),
])
def test_numkeys_zero_is_syntax_error(wire, cmdline):
    with pytest.raises(RespError, match="numkeys"):
        wire.execute(*cmdline)


@pytest.mark.parametrize("cmdline", [
    ("LMPOP", "9", "kx", "LEFT"),
    ("ZMPOP", "9", "kx", "MIN"),
    ("ZUNION", "9", "kx"),
])
def test_numkeys_oversized_is_clean_error(wire, cmdline):
    """An oversized numkeys must not swallow the mode token as a key name
    and die with ERR internal."""
    with pytest.raises(RespError, match="[Nn]umber of keys|numkeys"):
        wire.execute(*cmdline)


def test_mapcache_max_size_zero_unbounded():
    c = redisson_tpu.create()
    try:
        mc = c.get_map_cache("msz")
        mc.set_max_size(0)  # must not raise; 0 == unbounded
        for i in range(50):
            mc.put(f"k{i}", i)
        assert mc.size() == 50  # nothing evicted
        assert mc.get_max_size() == 0
        with pytest.raises(ValueError, match="negative"):
            mc.set_max_size(-1)
        # set-once contract survives a 0 bound: presence, not truthiness
        mc2 = c.get_map_cache("msz2")
        assert mc2.try_set_max_size(0) is True
        assert mc2.try_set_max_size(5) is False
    finally:
        c.shutdown()


def test_wire_restore_ttl_zero_means_persist(wire):
    wire.execute("SET", "dmp-src", "payload")
    wire.execute("PEXPIRE", "dmp-src", "80")
    blob = wire.execute("DUMP", "dmp-src")
    assert blob is not None
    time.sleep(0.15)  # let the carried TTL elapse
    # ttl 0 == no expiry: must install fine even though the blob's own
    # carried expiry has already passed
    assert wire.execute("RESTORE", "dmp-restored", "0", blob) in (b"OK", "OK")
    assert wire.execute("GET", "dmp-restored") == b"payload"
    assert wire.execute("PTTL", "dmp-restored") == -1
    with pytest.raises(RespError, match="Invalid TTL"):
        wire.execute("RESTORE", "dmp-neg", "-1", blob)


def test_migrate_carries_remaining_ttl(wire):
    """RObject.migrate ships the remaining TTL as RESTORE's explicit ttl
    operand (Redis MIGRATE recipe) — wire RESTORE ttl 0 now means persist,
    so migrate must NOT rely on the blob-carried expiry."""
    c = redisson_tpu.create()
    try:
        b = c.get_bucket("mig-ttl")
        b.set("v")
        b.expire(60.0)
        b.migrate(f"tpu://{wire.node.host}:{wire.node.port}")
        pttl = wire.execute("PTTL", "mig-ttl")
        assert 1_000 < pttl <= 60_000, pttl
        # persistent records stay persistent (ttl operand 0)
        p = c.get_bucket("mig-per")
        p.set("w")
        p.migrate(f"tpu://{wire.node.host}:{wire.node.port}")
        assert wire.execute("PTTL", "mig-per") == -1
    finally:
        c.shutdown()


def test_wait_malformed_args_error(wire):
    with pytest.raises(RespError, match="wrong number"):
        wire.execute("WAIT", "1")
    with pytest.raises(RespError, match="negative"):
        wire.execute("WAIT", "1", "-100")


def test_wait_timeout_zero_blocks_until_count(wire):
    """WAIT n 0 must park (no replicas will ever attach here), not return
    after one probe; WAIT n small-timeout still honors the deadline."""
    t0 = time.time()
    assert wire.execute("WAIT", "0", "0") == 0  # satisfied instantly
    assert time.time() - t0 < 5.0

    got = []

    def parked_wait():
        try:
            got.append(wire.execute("WAIT", "1", "0"))
        except Exception:  # noqa: BLE001 — client closes under us at teardown
            pass

    th = threading.Thread(target=parked_wait, daemon=True)
    th.start()
    th.join(timeout=0.6)
    assert th.is_alive(), "WAIT 1 0 returned early; timeout 0 must block"
    # deadline path still works
    t0 = time.time()
    assert wire.execute("WAIT", "1", "120") == 0
    assert 0.05 <= time.time() - t0 < 5.0
